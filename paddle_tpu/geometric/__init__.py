"""paddle_tpu.geometric — graph learning ops.

reference: python/paddle/geometric/ (message_passing/send_recv.py
send_u_recv / send_ue_recv / segment_* , sampling/neighbors.py
sample_neighbors). TPU-native: message passing is gather (by edge source)
+ segment-reduce (by edge destination) — both static-shape XLA ops;
neighbor sampling is host-side (data-dependent sizes belong off-device).
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch, to_value

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "send_u_recv", "send_ue_recv", "sample_neighbors",
           "weighted_sample_neighbors", "reindex_graph", "send_uv",
           "reindex_heter_graph", "graph_khop_sampler"]


def _seg(reduce_fn, data, segment_ids, num_segments, name):
    ids = jnp.asarray(to_value(segment_ids), jnp.int32)
    n = int(num_segments) if num_segments is not None else \
        int(np.asarray(ids).max()) + 1
    data = data if isinstance(data, Tensor) else Tensor(data)
    # through dispatch so the op records a GradNode (gradients flow back
    # into upstream layers of a GNN)
    return dispatch(lambda d: reduce_fn(d, ids, num_segments=n), (data,),
                    name=name)


def segment_sum(data, segment_ids, num_segments=None):
    """reference: geometric/math.py segment_sum."""
    return _seg(jax.ops.segment_sum, data, segment_ids, num_segments,
                "segment_sum")


def segment_mean(data, segment_ids, num_segments=None):
    ids = jnp.asarray(to_value(segment_ids), jnp.int32)
    nd = np.ndim(to_value(data))
    n = int(num_segments) if num_segments is not None else \
        int(np.asarray(ids).max()) + 1

    def f(d):
        total = jax.ops.segment_sum(d, ids, num_segments=n)
        count = jax.ops.segment_sum(jnp.ones(d.shape[:1], d.dtype), ids,
                                    num_segments=n)
        return total / jnp.maximum(count, 1)[(...,) + (None,) * (nd - 1)]

    data = data if isinstance(data, Tensor) else Tensor(data)
    return dispatch(f, (data,), name="segment_mean")


def segment_max(data, segment_ids, num_segments=None):
    return _seg(jax.ops.segment_max, data, segment_ids, num_segments,
                "segment_max")


def segment_min(data, segment_ids, num_segments=None):
    return _seg(jax.ops.segment_min, data, segment_ids, num_segments,
                "segment_min")


_REDUCERS = {"sum": jax.ops.segment_sum, "mean": None,
             "max": jax.ops.segment_max, "min": jax.ops.segment_min}


def send_u_recv(x, src_index, dst_index, reduce_op: str = "sum",
                out_size: Optional[int] = None):
    """Gather messages from edge sources, reduce at destinations.
    reference: geometric/message_passing/send_recv.py send_u_recv."""
    src = jnp.asarray(to_value(src_index), jnp.int32)
    dst = jnp.asarray(to_value(dst_index), jnp.int32)
    n = int(out_size) if out_size is not None else np.shape(to_value(x))[0]
    x = x if isinstance(x, Tensor) else Tensor(x)
    if reduce_op == "mean":
        return segment_mean(
            dispatch(lambda v: jnp.take(v, src, axis=0), (x,),
                     name="gather"), dst, n)
    fn = _REDUCERS.get(reduce_op)
    if fn is None:
        raise ValueError(f"unsupported reduce_op {reduce_op}")

    def f(v):
        out = fn(jnp.take(v, src, axis=0), dst, num_segments=n)
        if reduce_op in ("max", "min"):
            # empty segments produce ±inf in jax; paddle semantics: 0
            out = jnp.where(jnp.isfinite(out), out, 0)
        return out

    return dispatch(f, (x,), name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op: str = "add",
                 reduce_op: str = "sum", out_size: Optional[int] = None):
    """Node features combined with edge features along edges.
    reference: send_recv.py send_ue_recv (message_op add/sub/mul/div)."""
    src = jnp.asarray(to_value(src_index), jnp.int32)
    dst = jnp.asarray(to_value(dst_index), jnp.int32)
    n = int(out_size) if out_size is not None else np.shape(to_value(x))[0]
    if message_op not in ("add", "sub", "mul", "div"):
        raise ValueError(f"unsupported message_op {message_op}")
    if reduce_op != "mean" and _REDUCERS.get(reduce_op) is None:
        raise ValueError(f"unsupported reduce_op {reduce_op}")
    x = x if isinstance(x, Tensor) else Tensor(x)
    y = y if isinstance(y, Tensor) else Tensor(y)

    def msg(v, ev):
        m = jnp.take(v, src, axis=0)
        return {"add": m + ev, "sub": m - ev, "mul": m * ev,
                "div": m / ev}[message_op]

    if reduce_op == "mean":
        msgs = dispatch(msg, (x, y), name="send_ue")
        return segment_mean(msgs, dst, n)

    def f(v, ev):
        out = _REDUCERS[reduce_op](msg(v, ev), dst, num_segments=n)
        if reduce_op in ("max", "min"):
            out = jnp.where(jnp.isfinite(out), out, 0)
        return out

    return dispatch(f, (x, y), name="send_ue_recv")


def sample_neighbors(row, colptr, input_nodes, sample_size: int = -1,
                     eids=None, return_eids: bool = False,
                     perm_buffer=None):
    """Uniform neighbor sampling from a CSC graph — host-side (dynamic
    output sizes; reference: geometric/sampling/neighbors.py)."""
    rowv = np.asarray(to_value(row)).ravel()
    colptrv = np.asarray(to_value(colptr)).ravel()
    nodes = np.asarray(to_value(input_nodes)).ravel()
    eids_v = np.asarray(to_value(eids)).ravel() if eids is not None \
        else None
    rng = np.random.default_rng()
    out_neighbors, out_counts, out_eids = [], [], []
    for nd in nodes:
        beg, end = int(colptrv[nd]), int(colptrv[nd + 1])
        neigh = rowv[beg:end]
        ids = eids_v[beg:end] if eids_v is not None \
            else np.arange(beg, end)
        if 0 <= sample_size < len(neigh):
            pick = rng.choice(len(neigh), sample_size, replace=False)
            neigh = neigh[pick]
            ids = ids[pick]
        out_neighbors.append(neigh)
        out_counts.append(len(neigh))
        out_eids.append(ids)
    neighbors = Tensor(np.concatenate(out_neighbors)
                       if out_neighbors else np.zeros(0, rowv.dtype))
    counts = Tensor(np.asarray(out_counts, np.int64))
    if return_eids:
        return neighbors, counts, Tensor(np.concatenate(out_eids))
    return neighbors, counts


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size: int = -1, eids=None,
                              return_eids: bool = False, name=None):
    """Weighted neighbor sampling from a CSC graph: selection probability
    proportional to edge weight, without replacement (A-Res reservoir
    keys: k_i = u_i^(1/w_i), take the top-k). Host-side like
    sample_neighbors (dynamic output sizes belong off-device).
    reference: geometric/sampling/neighbors.py weighted_sample_neighbors.
    """
    rowv = np.asarray(to_value(row)).ravel()
    colptrv = np.asarray(to_value(colptr)).ravel()
    wv = np.asarray(to_value(edge_weight)).ravel().astype(np.float64)
    nodes = np.asarray(to_value(input_nodes)).ravel()
    eids_v = np.asarray(to_value(eids)).ravel() if eids is not None else None
    rng = np.random.default_rng()
    out_neighbors, out_counts, out_eids = [], [], []
    for nd in nodes:
        beg, end = int(colptrv[nd]), int(colptrv[nd + 1])
        neigh = rowv[beg:end]
        w = wv[beg:end]
        ids = eids_v[beg:end] if eids_v is not None else np.arange(beg, end)
        if 0 <= sample_size < len(neigh):
            # exponential-sort trick == weighted sampling w/o replacement
            keys = rng.exponential(1.0, len(neigh)) / np.maximum(w, 1e-30)
            pick = np.argsort(keys)[:sample_size]
            neigh, ids = neigh[pick], ids[pick]
        out_neighbors.append(neigh)
        out_counts.append(len(neigh))
        out_eids.append(ids)
    neighbors = Tensor(np.concatenate(out_neighbors)
                       if out_neighbors else np.zeros(0, rowv.dtype))
    counts = Tensor(np.asarray(out_counts, np.int64))
    if return_eids:
        return neighbors, counts, Tensor(np.concatenate(out_eids)
                                         if out_eids
                                         else np.zeros(0, np.int64))
    return neighbors, counts


def _reindex(xv, neigh_list, count_list, centers_list=None):
    """Shared hashtable pass: out_nodes = x then first-appearance unique
    neighbors; edges are (reindexed neighbor -> reindexed center).

    ``centers_list`` gives each layer's center node IDS (khop layers
    beyond the first); default: every layer's centers are ``xv``.
    Centers must already be present in the mapping when their layer is
    processed (khop adds each layer's neighbors before using them as
    the next layer's centers)."""
    mapping = {int(v): i for i, v in enumerate(xv)}
    out_nodes = list(xv)
    src_lists, dst_lists = [], []
    if centers_list is None:
        centers_list = [xv] * len(neigh_list)
    for centers, neigh, cnt in zip(centers_list, neigh_list, count_list):
        src, dst = [], []
        pos = 0
        for center, c in zip(centers, cnt):
            ci = mapping[int(center)]
            for v in neigh[pos:pos + int(c)]:
                v = int(v)
                if v not in mapping:
                    mapping[v] = len(out_nodes)
                    out_nodes.append(v)
                src.append(mapping[v])
                dst.append(ci)
            pos += int(c)
        src_lists.append(src)
        dst_lists.append(dst)
    return src_lists, dst_lists, out_nodes, mapping


def reindex_graph(x, neighbors, count, value_buffer=None,
                  index_buffer=None, name=None):
    """reference: geometric/reindex.py:34 reindex_graph — renumber the
    sampled subgraph from 0 with the input nodes first; returns
    (reindex_src, reindex_dst, out_nodes)."""
    xv = np.asarray(to_value(x)).ravel()
    nv = np.asarray(to_value(neighbors)).ravel()
    cv = np.asarray(to_value(count)).ravel()
    src, dst, out_nodes, _ = _reindex(xv, [nv], [cv])
    return (Tensor(np.asarray(src[0], xv.dtype)),
            Tensor(np.asarray(dst[0], xv.dtype)),
            Tensor(np.asarray(out_nodes, xv.dtype)))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """reference: geometric/reindex.py:153 — multi-edge-type reindex
    over ONE shared hashtable; per-type edges are concatenated in
    order. Returns (reindex_src, reindex_dst, out_nodes)."""
    xv = np.asarray(to_value(x)).ravel()
    neighs = [np.asarray(to_value(n)).ravel() for n in neighbors]
    cnts = [np.asarray(to_value(c)).ravel() for c in count]
    src, dst, out_nodes, _ = _reindex(xv, neighs, cnts)
    flat_src = [s for lst in src for s in lst]
    flat_dst = [d for lst in dst for d in lst]
    return (Tensor(np.asarray(flat_src, xv.dtype)),
            Tensor(np.asarray(flat_dst, xv.dtype)),
            Tensor(np.asarray(out_nodes, xv.dtype)))


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """reference: incubate/operators/graph_khop_sampler.py:63 — k layers
    of neighbor sampling with a final subgraph reindex. Returns
    (edge_src, edge_dst, sample_index, reindex_nodes[, edge_eids])."""
    rowv = np.asarray(to_value(row)).ravel()
    colptrv = np.asarray(to_value(colptr)).ravel()
    nodes0 = np.asarray(to_value(input_nodes)).ravel()
    eids_v = np.asarray(to_value(sorted_eids)).ravel() \
        if sorted_eids is not None else None
    rng = np.random.default_rng()

    frontier = nodes0
    all_centers, all_neighbors, all_counts, all_eids = [], [], [], []
    seen = set(int(v) for v in nodes0)
    for size in sample_sizes:
        neighs, cnts, layer_eids = [], [], []
        for nd in frontier:
            beg, end = int(colptrv[nd]), int(colptrv[nd + 1])
            neigh = rowv[beg:end]
            ids = eids_v[beg:end] if eids_v is not None \
                else np.arange(beg, end)
            if 0 <= size < len(neigh):
                pick = rng.choice(len(neigh), size, replace=False)
                neigh, ids = neigh[pick], ids[pick]
            neighs.append(neigh)
            cnts.append(len(neigh))
            layer_eids.append(ids)
        layer_neigh = np.concatenate(neighs) if neighs \
            else np.zeros(0, rowv.dtype)
        all_centers.append(frontier)
        all_neighbors.append(layer_neigh)
        all_counts.append(np.asarray(cnts, np.int64))
        all_eids.append(np.concatenate(layer_eids) if layer_eids
                        else np.zeros(0, np.int64))
        # de-duplicate WITHIN the layer too: a node reached from several
        # parents must be expanded once, not once per parent
        nxt = []
        for v in layer_neigh:
            v = int(v)
            if v not in seen:
                seen.add(v)
                nxt.append(v)
        frontier = np.asarray(nxt, rowv.dtype)
        if len(frontier) == 0:
            break

    # one shared reindex over every layer's (centers, neighbors)
    src_lists, dst_lists, uniq, mapping = _reindex(
        nodes0, all_neighbors, all_counts, centers_list=all_centers)
    srcs = [s for lst in src_lists for s in lst]
    dsts = [d for lst in dst_lists for d in lst]
    edge_src = Tensor(np.asarray(srcs, rowv.dtype).reshape(-1, 1))
    edge_dst = Tensor(np.asarray(dsts, rowv.dtype).reshape(-1, 1))
    sample_index = Tensor(np.asarray(uniq, rowv.dtype))
    reindex_nodes = Tensor(np.asarray(
        [mapping[int(v)] for v in nodes0], rowv.dtype))
    if return_eids:
        return (edge_src, edge_dst, sample_index, reindex_nodes,
                Tensor(np.concatenate(all_eids) if all_eids
                       else np.zeros(0, np.int64)))
    return edge_src, edge_dst, sample_index, reindex_nodes


def send_uv(x, y, src_index, dst_index, message_op: str = "add",
            name=None):
    """reference: geometric/message_passing/send_recv.py send_uv —
    per-edge messages combining source-node and destination-node
    features (gather + elementwise; no reduce)."""
    src = jnp.asarray(to_value(src_index), jnp.int32)
    dst = jnp.asarray(to_value(dst_index), jnp.int32)
    if message_op not in ("add", "sub", "mul", "div"):
        raise ValueError(f"unsupported message_op {message_op}")
    x = x if isinstance(x, Tensor) else Tensor(x)
    y = y if isinstance(y, Tensor) else Tensor(y)

    def f(xv, yv):
        a = jnp.take(xv, src, axis=0)
        b = jnp.take(yv, dst, axis=0)
        return {"add": a + b, "sub": a - b, "mul": a * b,
                "div": a / b}[message_op]

    return dispatch(f, (x, y), name="send_uv")
