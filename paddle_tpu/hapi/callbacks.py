"""hapi callback machinery (reference: python/paddle/hapi/callbacks.py —
Callback:177, CallbackList:98, ProgBarLogger:365, ModelCheckpoint:637,
LRScheduler:710, EarlyStopping:814, VisualDL:977, ReduceLROnPlateau:1274).

Implemented from the reference's observable behavior: Model.fit drives
``config_callbacks`` -> CallbackList and each callback hooks the
train/eval/predict lifecycle. VisualDL's writer dependency is not in
this image, so the class logs scalars to a JSONL file with the same
call shape (gate, not stub — the data is real and greppable).
"""
from __future__ import annotations

import json
import numbers
import os
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Callback", "CallbackList", "config_callbacks", "ProgBarLogger",
           "ModelCheckpoint", "LRScheduler", "EarlyStopping", "VisualDL",
           "ReduceLROnPlateau"]


class Callback:
    """Base class; subclasses override any subset of the hooks."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    # lifecycle hooks, all optional -------------------------------------
    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: Optional[Sequence[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, callback):
        self.callbacks.append(callback)

    def __iter__(self):
        return iter(self.callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def on_begin(self, mode, logs=None):
        self._call(f"on_{mode}_begin", logs)

    def on_end(self, mode, logs=None):
        self._call(f"on_{mode}_end", logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_begin", step, logs)

    def on_batch_end(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_end", step, logs)


def config_callbacks(callbacks=None, model=None, batch_size=None,
                     epochs=None, steps=None, log_freq=2, verbose=2,
                     save_freq=1, save_dir=None, metrics=None,
                     mode="train"):
    """reference callbacks.py:55 — normalize the user list and install
    the default ProgBarLogger/ModelCheckpoint when absent."""
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({
        "batch_size": batch_size, "epochs": epochs, "steps": steps,
        "verbose": verbose, "metrics": list(metrics or ["loss"]),
    })
    return lst


def _scalar(v):
    if isinstance(v, (list, tuple, np.ndarray)):
        arr = np.asarray(v).ravel()
        return float(arr[0]) if arr.size else 0.0
    if isinstance(v, numbers.Number):
        return float(v)
    return v


class ProgBarLogger(Callback):
    """reference callbacks.py:365 — periodic stdout logging of loss,
    metrics and throughput."""

    def __init__(self, log_freq: int = 1, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose
        self.epochs = None
        self.steps = None

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()
        self._seen = 0

    def _line(self, step, logs, mode):
        logs = logs or {}
        items = [f"{k}: {_scalar(v):.4f}" if isinstance(
            _scalar(v), float) else f"{k}: {v}"
            for k, v in logs.items() if k not in ("batch_size",)]
        head = f"Epoch {self.epoch + 1}/{self.epochs}" \
            if mode == "train" and self.epochs else mode.capitalize()
        tot = f"/{self.steps}" if self.steps else ""
        dt = time.time() - self._t0
        ips = self._seen / dt if dt > 0 else 0.0
        print(f"{head} step {step + 1}{tot} - " + ", ".join(items)
              + (f" - {ips:.1f} samples/sec" if self._seen else ""))

    def on_train_batch_end(self, step, logs=None):
        self._seen += (logs or {}).get("batch_size", 0)
        if self.verbose and step % self.log_freq == 0:
            self._line(step, logs, "train")

    def on_eval_begin(self, logs=None):
        self.epoch = 0
        self.steps = None   # train steps/epoch is the wrong denominator
        self._t0 = time.time()
        self._seen = 0

    def on_eval_batch_end(self, step, logs=None):
        self._seen += (logs or {}).get("batch_size", 0)
        if self.verbose > 1 and step % self.log_freq == 0:
            self._line(step, logs, "eval")

    def on_eval_end(self, logs=None):
        if self.verbose:
            items = [f"{k}: {_scalar(v)}" for k, v in (logs or {}).items()]
            print("Eval done - " + ", ".join(items))


class ModelCheckpoint(Callback):
    """reference callbacks.py:637 — save every ``save_freq`` epochs to
    ``save_dir/{epoch}`` and to ``save_dir/final`` at train end."""

    def __init__(self, save_freq: int = 1, save_dir: Optional[str] = None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and self.save_dir and \
                epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            print(f"save checkpoint at {os.path.abspath(path)}")
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.model is not None and self.save_dir:
            path = os.path.join(self.save_dir, "final")
            print(f"save checkpoint at {os.path.abspath(path)}")
            self.model.save(path)


class LRScheduler(Callback):
    """reference callbacks.py:710 — step the optimizer's LR scheduler
    each train batch (``by_step``) and/or each epoch (``by_epoch``)."""

    def __init__(self, by_step: bool = True, by_epoch: bool = False):
        super().__init__()
        if by_step and by_epoch:
            raise ValueError(
                "by_step and by_epoch are mutually exclusive")
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()


class _MonitorMixin:
    def _init_monitor(self, monitor, mode, min_delta):
        self.monitor = monitor
        self.min_delta = abs(min_delta)
        if mode not in ("auto", "min", "max"):
            mode = "auto"
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best_value = -np.inf if mode == "max" else np.inf

    def _monitored(self, logs):
        v = (logs or {}).get(self.monitor)
        return None if v is None else _scalar(v)

    def _improved(self, v):
        if self.mode == "max":
            return v > self.best_value + self.min_delta
        return v < self.best_value - self.min_delta


class EarlyStopping(Callback, _MonitorMixin):
    """reference callbacks.py:814 — watch an eval metric; stop training
    after ``patience`` non-improving evals, optionally saving the best
    model (``save_dir/best_model``) and restoring nothing (parity)."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self._init_monitor(monitor, mode, min_delta)
        self.patience = patience
        self.verbose = verbose
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.save_dir = None        # set by Model.fit from its save_dir
        self.wait_epoch = 0
        self.stopped_epoch = 0

    def on_train_begin(self, logs=None):
        self.wait_epoch = 0
        if self.baseline is not None:
            self.best_value = self.baseline

    def on_eval_end(self, logs=None):
        v = self._monitored(logs)
        if v is None:
            return
        if self._improved(v):
            self.best_value = v
            self.wait_epoch = 0
            if self.save_best_model and self.save_dir and \
                    self.model is not None:
                self.model.save(os.path.join(self.save_dir, "best_model"))
        else:
            self.wait_epoch += 1
        if self.wait_epoch > self.patience:
            self.model.stop_training = True
            if self.verbose:
                print(f"EarlyStopping: stop at best {self.monitor} = "
                      f"{self.best_value}")


class ReduceLROnPlateau(Callback, _MonitorMixin):
    """reference callbacks.py:1274 — multiply the LR by ``factor`` after
    ``patience`` non-improving evals; floors at ``min_lr``."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0.0):
        super().__init__()
        self._init_monitor(monitor, mode, min_delta)
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.cooldown_counter = 0
        self.wait = 0

    def on_eval_end(self, logs=None):
        v = self._monitored(logs)
        if v is None:
            return
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self._improved(v):
            self.best_value = v
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                opt = getattr(self.model, "_optimizer", None)
                from ..optimizer.lr import LRScheduler as Sched
                if opt is not None and not isinstance(
                        getattr(opt, "_learning_rate", None), Sched):
                    old = opt.get_lr()
                    new = max(old * self.factor, self.min_lr)
                    if old - new > 1e-12:
                        opt.set_lr(new)
                        if self.verbose:
                            print(f"ReduceLROnPlateau: lr {old:.2e} -> "
                                  f"{new:.2e}")
                elif opt is not None and self.verbose:
                    # reference warns and skips for scheduler-driven LR
                    print("ReduceLROnPlateau: learning rate is a "
                          "scheduler; skipping adjustment")
                self.cooldown_counter = self.cooldown
                self.wait = 0


class VisualDL(Callback):
    """reference callbacks.py:977. The visualdl writer isn't in this
    image; scalars are appended to ``<log_dir>/scalars.jsonl`` with the
    same tag layout ({mode}/{metric}) so dashboards can ingest them."""

    def __init__(self, log_dir: str = "./log"):
        super().__init__()
        self.log_dir = log_dir
        self._step = {"train": 0, "eval": 0}

    def _write(self, mode, logs):
        os.makedirs(self.log_dir, exist_ok=True)
        rec = {f"{mode}/{k}": _scalar(v) for k, v in (logs or {}).items()
               if isinstance(_scalar(v), float)}
        if not rec:
            return
        rec["step"] = self._step[mode]
        with open(os.path.join(self.log_dir, "scalars.jsonl"), "a") as f:
            f.write(json.dumps(rec) + "\n")

    def on_train_batch_end(self, step, logs=None):
        self._step["train"] += 1
        self._write("train", logs)

    def on_eval_end(self, logs=None):
        self._step["eval"] += 1
        self._write("eval", logs)
