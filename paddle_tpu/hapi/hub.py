"""paddle.hub (reference: python/paddle/hapi/hub.py — list/help/load
over a repo's ``hubconf.py`` entrypoints).

This environment has no egress, so the github/gitee sources raise a
clear error pointing at ``source='local'`` (which implements the full
reference contract: import hubconf.py from the repo dir, check its
``dependencies`` list, expose non-underscore callables as entrypoints).
"""
from __future__ import annotations

import builtins
import os
import sys
import types
from typing import Any, Callable, List

__all__ = ["list", "help", "load"]

VAR_DEPENDENCY = "dependencies"
MODULE_HUBCONF = "hubconf.py"


def _import_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, MODULE_HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {MODULE_HUBCONF} in {repo_dir}")
    sys.path.insert(0, repo_dir)
    try:
        import importlib.util
        spec = importlib.util.spec_from_file_location("hubconf", path)
        m = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(m)
    finally:
        sys.path.remove(repo_dir)
    _check_dependencies(m)
    return m


def _check_module_exists(name: str) -> bool:
    try:
        __import__(name)
        return True
    except ImportError:
        return False


def _check_dependencies(m: types.ModuleType):
    deps = getattr(m, VAR_DEPENDENCY, None)
    if deps:
        missing = [d for d in deps if not _check_module_exists(d)]
        if missing:
            raise RuntimeError(
                "Missing dependencies: " + ", ".join(missing))


def _resolve(repo_dir: str, source: str):
    if source not in ("github", "gitee", "local"):
        raise ValueError(
            f'Unknown source: "{source}". Allowed values: "github" | '
            '"gitee" | "local".')
    if source != "local":
        raise RuntimeError(
            "this deployment has no network egress; clone the repo and "
            "use hub.load(path, ..., source='local')")
    return _import_hubconf(repo_dir)


def _entry(m, name: str) -> Callable:
    if not isinstance(name, str):
        raise ValueError("Invalid input: model should be a str of "
                         "function name")
    func = getattr(m, name, None)
    if func is None or not callable(func):
        raise RuntimeError(f"Cannot find callable {name} in hubconf")
    return func


def list(repo_dir: str, source: str = "github",
         force_reload: bool = False) -> builtins.list:
    """reference hub.py:188 — entrypoint names in the repo's hubconf."""
    m = _resolve(repo_dir, source)
    # every non-underscore callable, including ones hubconf imported
    # (`from models import resnet50` is the common pattern) — matching
    # the reference; modules themselves aren't callable so don't appear
    return [k for k, v in vars(m).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False) -> str:
    """reference hub.py:238 — the entrypoint's docstring."""
    return _entry(_resolve(repo_dir, source), model).__doc__


def load(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False, **kwargs) -> Any:
    """reference hub.py:286 — call the entrypoint with kwargs."""
    return _entry(_resolve(repo_dir, source), model)(**kwargs)
