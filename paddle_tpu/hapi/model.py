"""High-level Model API (reference: python/paddle/hapi/model.py:1472)."""
from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor, no_grad
from ..metric import Metric

__all__ = ["Model"]


class Model:
    """reference: python/paddle/hapi/model.py:1472 Model — the high-level
    train/eval/predict facade. ``inputs``/``labels`` are InputSpec lists
    (reference requires them in static mode; here they drive
    ``save(training=False)`` inference export and ``summary()``)."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False
        self._inputs = self._to_specs(inputs)
        self._labels = self._to_specs(labels)

    @staticmethod
    def _to_specs(specs):
        if specs is None:
            return None
        from ..static import InputSpec
        out = []
        for s in (specs if isinstance(specs, (list, tuple)) else [specs]):
            if isinstance(s, InputSpec):
                out.append(s)
            elif isinstance(s, (list, tuple)):
                out.append(InputSpec(s))
            elif isinstance(s, np.ndarray):
                out.append(InputSpec.from_numpy(s))
            elif isinstance(s, Tensor):
                out.append(InputSpec.from_tensor(s))
            else:
                raise TypeError(
                    "Model inputs/labels entries must be InputSpec, "
                    f"shape list, Tensor, or ndarray; got {type(s)}")
        return out

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]
        return self

    def _compute_loss(self, outputs, labels):
        if callable(self._loss):
            return self._loss(outputs, labels)
        raise RuntimeError("call prepare(loss=...) first")

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        outputs = self.network(*inputs)
        loss = self._compute_loss(outputs, labels)
        loss.backward()
        if update and self._optimizer is not None:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            m.update(m.compute(outputs, labels))
            metrics.append(m.accumulate())
        return (float(loss.item()), metrics) if metrics else \
            float(loss.item())

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        outputs = self.network(*inputs)
        loss = self._compute_loss(outputs, labels)
        metrics = []
        for m in self._metrics:
            m.update(m.compute(outputs, labels))
            metrics.append(m.accumulate())
        return (float(loss.item()), metrics) if metrics else \
            float(loss.item())

    @no_grad()
    def predict_batch(self, inputs):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        return self.network(*inputs)

    def _metric_names(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names.extend([n] if isinstance(n, str) else list(n))
        return names

    def _make_loader(self, data, batch_size, shuffle=False, drop_last=False,
                     num_workers=0):
        from ..io import DataLoader, Dataset
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              drop_last=drop_last, num_workers=num_workers)
        return data

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, accumulate_grad_batches=1, num_iters=None):
        """reference hapi/model.py Model.fit: drives the callback
        lifecycle (hapi/callbacks.py config_callbacks) around the
        train/eval loops; EarlyStopping sets ``stop_training``."""
        from .callbacks import EarlyStopping, config_callbacks
        train_loader = self._make_loader(train_data, batch_size, shuffle,
                                         drop_last, num_workers)
        steps = len(train_loader) if hasattr(train_loader, "__len__") \
            else None
        cbks = config_callbacks(
            callbacks, model=self, batch_size=batch_size, epochs=epochs,
            steps=steps, log_freq=log_freq, verbose=verbose,
            save_freq=save_freq, save_dir=save_dir,
            metrics=self._metric_names())
        for c in cbks:
            if isinstance(c, EarlyStopping) and c.save_dir is None:
                c.save_dir = save_dir
        self.stop_training = False
        cbks.on_begin("train")
        it = 0
        hit_iters = False
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = None
            for step, batch in enumerate(train_loader):
                cbks.on_batch_begin("train", step)
                *xs, y = batch if isinstance(batch, (list, tuple)) else \
                    (batch,)
                res = self.train_batch(xs, y)
                loss, mvals = res if isinstance(res, tuple) else (res, [])
                try:
                    bs = int(np.asarray(y).shape[0])
                except (IndexError, TypeError):
                    bs = batch_size   # scalar/0-d labels: fall back
                logs = {"loss": loss, "batch_size": bs}
                for m, v in zip(self._metrics, mvals):
                    n = m.name()
                    if isinstance(n, str):
                        logs[n] = v
                    else:   # multi-name metric (e.g. acc_top1/acc_top5)
                        for nm, vv in zip(n, np.ravel(v)):
                            logs[nm] = vv
                cbks.on_batch_end("train", step, logs)
                it += 1
                if num_iters is not None and it >= num_iters:
                    hit_iters = True
                    break
            cbks.on_epoch_end(epoch, logs)
            if hit_iters:   # bounded run: skip eval, stop now (parity
                break       # with the pre-callback immediate return)
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_data, batch_size=batch_size,
                              verbose=verbose, callbacks=cbks)
            if self.stop_training:
                break
        cbks.on_end("train")

    @no_grad()
    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        from .callbacks import CallbackList, config_callbacks
        loader = self._make_loader(eval_data, batch_size,
                                   num_workers=num_workers)
        if isinstance(callbacks, CallbackList):
            cbks = callbacks   # fit() passes its configured list through
        else:
            cbks = config_callbacks(callbacks, model=self,
                                    batch_size=batch_size, log_freq=log_freq,
                                    verbose=verbose,
                                    metrics=self._metric_names())
        for m in self._metrics:
            m.reset()
        cbks.on_begin("eval")
        losses = []
        for step, batch in enumerate(loader):
            cbks.on_batch_begin("eval", step)
            *xs, y = batch if isinstance(batch, (list, tuple)) else (batch,)
            res = self.eval_batch(xs, y)
            loss = res[0] if isinstance(res, tuple) else res
            losses.append(loss)
            cbks.on_batch_end("eval", step, {"loss": loss,
                                             "batch_size": batch_size})
            if num_iters is not None and step + 1 >= num_iters:
                break
        result = {"loss": [float(np.mean(losses))]}
        for m in self._metrics:
            result[m.name() if isinstance(m.name(), str) else
                   m.name()[0]] = m.accumulate()
        cbks.on_end("eval", result)
        return result

    @no_grad()
    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        from .callbacks import config_callbacks
        loader = self._make_loader(test_data, batch_size,
                                   num_workers=num_workers)
        cbks = config_callbacks(callbacks, model=self,
                                batch_size=batch_size, verbose=0)
        cbks.on_begin("predict")
        outputs = []
        for step, batch in enumerate(loader):
            cbks.on_batch_begin("predict", step)
            xs = batch[:-1] if isinstance(batch, (list, tuple)) and \
                len(batch) > 1 else (batch if isinstance(batch, (list, tuple))
                                     else [batch])
            outputs.append(self.predict_batch(list(xs)))
            cbks.on_batch_end("predict", step)
        cbks.on_end("predict")
        return outputs

    def save(self, path, training=True):
        """training=True: checkpoint (params + optimizer state).
        training=False: inference export via jit.save (reference
        Model.save -> paddle.jit.save with the prepared input specs)."""
        if not training:
            from .. import jit
            if self._inputs is None:
                raise ValueError(
                    "save(training=False) needs Model(inputs=[InputSpec])")
            jit.save(self.network, path, input_spec=self._inputs)
            return
        from ..framework.io import save as psave
        psave(self.network.state_dict(), path + ".pdparams")
        if self._optimizer is not None:
            psave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as pload
        state = pload(path + ".pdparams")
        self.network.set_state_dict(state)
        if not reset_optimizer and self._optimizer is not None:
            import os
            if os.path.exists(path + ".pdopt"):
                self._optimizer.set_state_dict(pload(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary
        if input_size is None and self._inputs:
            input_size = [tuple(1 if d is None else d for d in s.shape)
                          for s in self._inputs]
            if len(input_size) == 1:
                input_size = input_size[0]
        return summary(self.network, input_size, dtypes=dtype)
