"""High-level Model API (reference: python/paddle/hapi/model.py:1472)."""
from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor, no_grad
from ..metric import Metric

__all__ = ["Model"]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]
        return self

    def _compute_loss(self, outputs, labels):
        if callable(self._loss):
            return self._loss(outputs, labels)
        raise RuntimeError("call prepare(loss=...) first")

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        outputs = self.network(*inputs)
        loss = self._compute_loss(outputs, labels)
        loss.backward()
        if update and self._optimizer is not None:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            m.update(m.compute(outputs, labels))
            metrics.append(m.accumulate())
        return (float(loss.item()), metrics) if metrics else \
            float(loss.item())

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        outputs = self.network(*inputs)
        loss = self._compute_loss(outputs, labels)
        metrics = []
        for m in self._metrics:
            m.update(m.compute(outputs, labels))
            metrics.append(m.accumulate())
        return (float(loss.item()), metrics) if metrics else \
            float(loss.item())

    @no_grad()
    def predict_batch(self, inputs):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        return self.network(*inputs)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, accumulate_grad_batches=1, num_iters=None):
        from ..io import DataLoader, Dataset
        if isinstance(train_data, Dataset):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        it = 0
        for epoch in range(epochs):
            for m in self._metrics:
                m.reset()
            t0 = time.time()
            for step, batch in enumerate(train_loader):
                *xs, y = batch if isinstance(batch, (list, tuple)) else \
                    (batch,)
                res = self.train_batch(xs, y)
                it += 1
                if verbose and step % log_freq == 0:
                    loss = res[0] if isinstance(res, tuple) else res
                    print(f"Epoch {epoch + 1}/{epochs} step {step} "
                          f"loss: {loss:.4f}")
                if num_iters is not None and it >= num_iters:
                    return
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_data, batch_size=batch_size,
                              verbose=verbose)
            if save_dir is not None and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/epoch_{epoch}")

    @no_grad()
    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        from ..io import DataLoader, Dataset
        if isinstance(eval_data, Dataset):
            loader = DataLoader(eval_data, batch_size=batch_size,
                                num_workers=num_workers)
        else:
            loader = eval_data
        for m in self._metrics:
            m.reset()
        losses = []
        for step, batch in enumerate(loader):
            *xs, y = batch if isinstance(batch, (list, tuple)) else (batch,)
            res = self.eval_batch(xs, y)
            losses.append(res[0] if isinstance(res, tuple) else res)
            if num_iters is not None and step + 1 >= num_iters:
                break
        result = {"loss": [float(np.mean(losses))]}
        for m in self._metrics:
            result[m.name() if isinstance(m.name(), str) else
                   m.name()[0]] = m.accumulate()
        if verbose:
            print("Eval:", result)
        return result

    @no_grad()
    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        from ..io import DataLoader, Dataset
        if isinstance(test_data, Dataset):
            loader = DataLoader(test_data, batch_size=batch_size,
                                num_workers=num_workers)
        else:
            loader = test_data
        outputs = []
        for batch in loader:
            xs = batch[:-1] if isinstance(batch, (list, tuple)) and \
                len(batch) > 1 else (batch if isinstance(batch, (list, tuple))
                                     else [batch])
            outputs.append(self.predict_batch(list(xs)))
        return outputs

    def save(self, path, training=True):
        from ..framework.io import save as psave
        psave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            psave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as pload
        state = pload(path + ".pdparams")
        self.network.set_state_dict(state)
        if not reset_optimizer and self._optimizer is not None:
            import os
            if os.path.exists(path + ".pdopt"):
                self._optimizer.set_state_dict(pload(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary
        return summary(self.network, input_size, dtypes=dtype)
