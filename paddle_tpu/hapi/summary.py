"""Model summary (reference: python/paddle/hapi/model_summary.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor, no_grad


def _example_inputs(input_size, dtypes):
    sizes = input_size
    if isinstance(sizes, tuple) or (isinstance(sizes, list)
                                    and sizes and not isinstance(
                                        sizes[0], (list, tuple))):
        sizes = [sizes]
    if dtypes is None:
        dtypes = ["float32"] * len(sizes)
    elif isinstance(dtypes, str):
        dtypes = [dtypes] * len(sizes)
    return [Tensor(np.zeros(tuple(1 if d is None else int(d) for d in s),
                            np.dtype(dt)))
            for s, dt in zip(sizes, dtypes)]


def summary(net, input_size=None, dtypes=None, input=None):
    """Parameter table; with ``input_size``/``input`` also runs one
    forward pass to record each sublayer's output shape (reference
    model_summary hooks)."""
    out_shapes = {}
    if input is not None or input_size is not None:
        xs = [input] if isinstance(input, Tensor) else (
            list(input) if input is not None
            else _example_inputs(input_size, dtypes))
        hooks = []
        for name, layer in net.named_sublayers():
            def mk(nm):
                def hook(lyr, inp, out):
                    leaf = out[0] if isinstance(out, (tuple, list)) else out
                    if isinstance(leaf, Tensor):
                        out_shapes[nm] = list(leaf.shape)
                return hook
            hooks.append(layer.register_forward_post_hook(mk(name)))
        try:
            with no_grad():
                net(*xs)
        finally:
            for h in hooks:
                h.remove()

    rows = []
    total_params = 0
    trainable_params = 0
    for name, layer in net.named_sublayers(include_self=True):
        if not name:
            continue
        total = sum(p.size for p in layer._parameters.values()
                    if p is not None)
        rows.append((name, type(layer).__name__, total,
                     out_shapes.get(name)))
    for p in net.parameters():
        total_params += p.size
        if p.trainable:
            trainable_params += p.size
    width = max((len(r[0]) for r in rows), default=20) + 2
    shape_col = 20 if out_shapes else 0
    hdr = f"{'Layer':<{width}}{'Type':<24}{'Params':>12}"
    if shape_col:
        hdr += f"  {'Output Shape':<{shape_col}}"
    print(hdr)
    print("-" * (width + 36 + (shape_col + 2 if shape_col else 0)))
    for name, tname, n, shape in rows:
        line = f"{name:<{width}}{tname:<24}{n:>12,}"
        if shape_col:
            line += f"  {str(shape) if shape else '':<{shape_col}}"
        print(line)
    print("-" * (width + 36 + (shape_col + 2 if shape_col else 0)))
    print(f"Total params: {total_params:,}")
    print(f"Trainable params: {trainable_params:,}")
    result = {"total_params": total_params,
              "trainable_params": trainable_params}
    if out_shapes:
        result["output_shapes"] = out_shapes
    return result
