"""paddle_tpu.incubate (reference: python/paddle/incubate/)."""
from . import nn  # noqa: F401
from . import asp  # noqa: F401

# graph-learning op aliases (reference: incubate/operators/* re-exports
# of the geometric kernels, kept for script compatibility)
from ..geometric import (graph_khop_sampler,  # noqa: F401
                         segment_max, segment_mean, segment_min,
                         segment_sum)
from ..geometric import reindex_graph as graph_reindex  # noqa: F401
from ..geometric import sample_neighbors as \
    graph_sample_neighbors  # noqa: F401
from ..geometric import send_u_recv as graph_send_recv  # noqa: F401


def identity_loss(x, reduction="none"):
    """reference: incubate/operators/identity_loss — marks a tensor as a
    loss for the IPU backend; here it is the reduction itself."""
    import jax.numpy as jnp
    from ..core.tensor import Tensor, dispatch
    x = x if isinstance(x, Tensor) else Tensor(x)
    red = {"none": lambda v: v, 0: lambda v: v,
           "sum": jnp.sum, 1: jnp.sum,
           "mean": jnp.mean, 2: jnp.mean}
    if reduction not in red:
        raise ValueError(f"unsupported reduction {reduction}")
    return dispatch(red[reduction], (x,), name="identity_loss")


def softmax_mask_fuse(x, mask, name=None):
    """reference: incubate/operators/softmax_mask_fuse — softmax(x +
    mask) fused; XLA fuses the add into the softmax on TPU."""
    import jax
    from ..core.tensor import Tensor, dispatch
    x = x if isinstance(x, Tensor) else Tensor(x)
    mask = mask if isinstance(mask, Tensor) else Tensor(mask)
    return dispatch(lambda v, m: jax.nn.softmax(v + m, axis=-1),
                    (x, mask), name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x):
    """reference: incubate/operators/softmax_mask_fuse_upper_triangle —
    causal-masked softmax (upper triangle masked out)."""
    import jax
    import jax.numpy as jnp
    from ..core.tensor import Tensor, dispatch
    x = x if isinstance(x, Tensor) else Tensor(x)

    def f(v):
        q, k = v.shape[-2], v.shape[-1]
        causal = jnp.tril(jnp.ones((q, k), bool))
        return jax.nn.softmax(jnp.where(causal, v, -1e30), axis=-1)

    return dispatch(f, (x,), name="softmax_mask_fuse_upper_triangle")


from .optimizer import LookAhead, ModelAverage  # noqa: F401,E402
