"""Automatic SParsity (2:4 structured sparsity).

reference: python/paddle/incubate/asp/ — create 2:4 masks
(utils.py create_mask / check_mask_2d), prune_model, and the
mask-preserving optimizer decoration so pruned weights stay zero through
training. On TPU there is no sparse-tensor-core analog today, so the
mask enforces the sparsity *pattern* (model-compression capability
parity); compute runs dense.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor, to_value
from ... import nn

__all__ = ["create_mask", "check_mask_1d", "prune_model", "decorate",
           "reset_excluded_layers", "set_excluded_layers"]

_EXCLUDED: set = set()
# models registered by prune_model; decorate(optimizer) with no explicit
# model re-applies masks for all of them (reference: asp.py keeps a global
# workspace of supported layers/masks)
_PRUNED_MODELS: List = []


def create_mask(weight, n: int = 2, m: int = 4) -> np.ndarray:
    """n:m mask along the LAST axis: keep the n largest |w| of every
    group of m (reference: asp/utils.py get_mask_1d)."""
    v = np.asarray(to_value(weight))
    orig_shape = v.shape
    last = orig_shape[-1]
    pad = (-last) % m
    if pad:
        v = np.concatenate(
            [v, np.zeros(orig_shape[:-1] + (pad,), v.dtype)], axis=-1)
    groups = v.reshape(-1, m)
    order = np.argsort(-np.abs(groups), axis=1)
    mask = np.zeros_like(groups, dtype=bool)
    np.put_along_axis(mask, order[:, :n], True, axis=1)
    mask = mask.reshape(v.shape)
    if pad:
        mask = mask[..., :last]
    return mask


def check_mask_1d(mat, n: int = 2, m: int = 4) -> bool:
    """True iff every group of m along the last axis has ≤ n nonzeros
    (reference: asp/utils.py check_mask_1d)."""
    v = np.asarray(to_value(mat))
    last = v.shape[-1]
    pad = (-last) % m
    if pad:
        v = np.concatenate(
            [v, np.zeros(v.shape[:-1] + (pad,), v.dtype)], axis=-1)
    groups = (v.reshape(-1, m) != 0).sum(axis=1)
    return bool((groups <= n).all())


def set_excluded_layers(layer_names: List[str]):
    _EXCLUDED.update(layer_names)


def reset_excluded_layers():
    _EXCLUDED.clear()


def _prunable(name: str, layer) -> bool:
    return isinstance(layer, nn.Linear) and name not in _EXCLUDED


def prune_model(model: nn.Layer, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d") -> Dict[str, np.ndarray]:
    """Apply n:m masks to every prunable layer's weight in place; returns
    {layer_name: mask} (reference: asp/asp.py prune_model)."""
    masks: Dict[str, np.ndarray] = {}
    for name, layer in model.named_sublayers():
        if not _prunable(name, layer):
            continue
        mask = create_mask(layer.weight, n, m)
        layer.weight._value = layer.weight._value * jnp.asarray(
            mask, layer.weight._value.dtype)
        masks[name] = mask
    model._asp_masks = masks
    _PRUNED_MODELS.append(model)
    return masks


def decorate(optimizer, model: Optional[nn.Layer] = None):
    """Wrap optimizer.step to re-apply masks after each update, so pruned
    weights stay pruned (reference: asp/asp.py decorate + OptimizerWithSparsityGuarantee)."""

    # (layer, mask) pairs resolved lazily and cached per mask-dict
    # identity: decorate() may legally be called BEFORE prune_model
    # (the reference's documented order), and per-step named_sublayers()
    # traversal would be hot-path overhead
    cache = {"key": None, "pairs": []}

    def resolve():
        models = [model] if model is not None else list(_PRUNED_MODELS)
        key = tuple(id(getattr(m, "_asp_masks", None)) for m in models)
        if cache["key"] != key:
            pairs = []
            for m in models:
                masks = getattr(m, "_asp_masks", None)
                if not masks:
                    continue
                by_name = dict(m.named_sublayers())
                pairs += [(by_name[n], msk) for n, msk in masks.items()
                          if n in by_name]
            cache["key"] = key
            cache["pairs"] = pairs
        return cache["pairs"]

    class _ASPOptimizer:
        def __init__(self, opt):
            self._opt = opt

        def __getattr__(self, item):
            return getattr(self._opt, item)

        def step(self):
            self._opt.step()
            for layer, mask in resolve():
                layer.weight._value = layer.weight._value * jnp.asarray(
                    mask, layer.weight._value.dtype)

    return _ASPOptimizer(optimizer)
