from . import functional  # noqa: F401
from .layers import (FusedLinear, FusedDropoutAdd,  # noqa: F401,E402
                     FusedBiasDropoutResidualLayerNorm, FusedFeedForward,
                     FusedMultiHeadAttention, FusedMultiTransformer,
                     FusedTransformerEncoderLayer, FP8Linear)
