"""Fused-op python APIs (reference: python/paddle/incubate/nn/functional/ —
fused_rms_norm, fused_rotary_position_embedding, swiglu, fused_adamw,
variable_length_memory_efficient_attention, block_multihead_attention, …).

On TPU these route to the ops/ pack (Pallas kernels + XLA compositions)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ....core.tensor import Tensor, dispatch
from .... import ops as _ops


def _ensure(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kw):
    def f(v, w, *b):
        out = _ops.rms_norm(v, w, epsilon)
        if b:
            out = out + b[0]
        return out
    args = (_ensure(x), _ensure(norm_weight))
    if norm_bias is not None:
        args += (_ensure(norm_bias),)
    out = dispatch(f, args, name="rms_norm")
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, **kw):
    args = (_ensure(x), _ensure(norm_weight), _ensure(norm_bias))
    return dispatch(lambda v, w, b: _ops.layer_norm(v, w, b, epsilon), args,
                    name="layer_norm")


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True):
    from ....ops.rope import apply_rope
    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        args = [_ensure(t)]
        extra = {}
        sin_v = sin._value if isinstance(sin, Tensor) else sin
        cos_v = cos._value if isinstance(cos, Tensor) else cos
        pid = position_ids._value if isinstance(position_ids, Tensor) \
            else position_ids
        outs.append(dispatch(
            lambda x: apply_rope(x, sin_v, cos_v, pid,
                                 use_neox_rotary_style),
            (args[0],), name="fused_rope"))
    return tuple(outs)


def swiglu(x, y=None, name=None):
    if y is None:
        return dispatch(lambda v: _ops.swiglu(v), (_ensure(x),),
                        name="swiglu")
    return dispatch(lambda a, b: _ops.swiglu(a, b),
                    (_ensure(x), _ensure(y)), name="swiglu")


def fused_bias_act(x, bias=None, act_method="gelu", **kw):
    import jax
    acts = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
            "swiglu": _ops.swiglu, "geglu": None}

    def f(v, *b):
        if b:
            v = v + b[0]
        return acts[act_method](v)
    args = (_ensure(x),)
    if bias is not None:
        args += (_ensure(bias),)
    return dispatch(f, args, name="fused_bias_act")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def f(v, w, *b):
        if transpose_weight:
            w = w.T
        out = v @ w
        if b:
            out = out + b[0]
        return out
    args = (_ensure(x), _ensure(weight))
    if bias is not None:
        args += (_ensure(bias),)
    return dispatch(f, args, name="matmul")


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    from ....nn.functional.common import dropout
    return dropout(x, p=p, training=training, mode=mode) + y


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn1_scale=None, ffn2_bias=None, ffn2_scale=None,
              quant_method="None", moe_topk=2, norm_topk_prob=True,
              capacity_factor=2.0):
    """Fused mixture-of-experts FFN (reference:
    python/paddle/incubate/nn/functional/fused_moe.py — a CUTLASS grouped
    GEMM on GPU).

    TPU-native formulation: GShard-style dense dispatch — gate top-k,
    scatter tokens into per-expert capacity buckets with one einsum, run
    every expert as one batched matmul ([E, C, D] @ [E, D, F], MXU-shaped,
    static shapes), combine with the gate weights. Unlike the exact
    grouped GEMM, tokens past ``capacity_factor * topk * T / E`` per
    expert are dropped (standard GShard semantics; raise the factor for
    exactness).

    x [B, S, D] (or [T, D]); gate_weight [D, E]; ffn1_weight [E, D, 2F]
    (swiglu) or [E, D, F] (gelu); ffn2_weight [E, F, D].
    """
    import jax
    import jax.numpy as jnp

    from ....core.tensor import dispatch
    from ....distributed.fleet.moe import moe_dispatch_combine

    if quant_method not in ("None", None, "none"):
        raise NotImplementedError(
            "fused_moe: weight quantization not supported (reference "
            "marks it 'currently not supported' too)")

    args = [_ensure(x), _ensure(gate_weight), _ensure(ffn1_weight),
            _ensure(ffn2_weight)]
    n_fixed = len(args)
    has_b1 = ffn1_bias is not None
    has_b2 = ffn2_bias is not None
    if has_b1:
        args.append(_ensure(ffn1_bias))
    if has_b2:
        args.append(_ensure(ffn2_bias))

    def f(xv, gw, w1, w2, *rest):
        b1 = rest[0] if has_b1 else None
        b2 = rest[int(has_b1)] if has_b2 else None
        lead = xv.shape[:-1]
        d = xv.shape[-1]
        flat = xv.reshape(-1, d)
        logits = flat.astype(jnp.float32) @ gw.astype(jnp.float32)
        e, _, two_f = w1.shape
        f_dim = w2.shape[1]
        glu = two_f == 2 * f_dim

        def expert_fn(expert_in):       # [E, C, D]
            h = jnp.einsum("ecd,edf->ecf", expert_in, w1)
            if b1 is not None:
                h = h + b1.reshape(e, 1, -1)
            if glu:
                a, g = jnp.split(h, 2, axis=-1)
                h = jax.nn.silu(a) * g
            else:
                h = jax.nn.gelu(h)
            out = jnp.einsum("ecf,efd->ecd", h, w2)
            if b2 is not None:
                out = out + b2.reshape(e, 1, -1)
            return out

        out, _aux = moe_dispatch_combine(
            flat, logits, expert_fn, top_k=moe_topk,
            capacity_factor=capacity_factor,
            norm_topk_prob=norm_topk_prob, warn_on_drop=True)
        return out.reshape(*lead, d)

    return dispatch(f, args, name="fused_moe")


from .fp8 import (quantize_fp8, dequantize_fp8, fp8_gemm,  # noqa: F401,E402
                  fp8_linear, fp8_delayed_state, quantize_fp8_delayed,
                  fp8_linear_delayed)


def fused_matmul_bias(x, y, bias=None, transpose_x=False,
                      transpose_y=False, name=None):
    """reference: incubate/nn/functional/fused_matmul_bias — one fused
    GEMM+bias (XLA fuses the bias add into the matmul epilogue)."""
    def f(a, b, *bb):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        return out + bb[0] if bb else out

    args = (_ensure(x), _ensure(y)) + ((_ensure(bias),)
                                       if bias is not None else ())
    return dispatch(f, args, name="fused_matmul_bias")


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu", name=None):
    """reference: fused_linear_activation — GEMM + bias + epilogue act."""
    import jax
    out = fused_matmul_bias(x, y, bias, trans_x, trans_y)
    acts = {"gelu": lambda v: jax.nn.gelu(v, approximate=True),
            "relu": lambda v: jnp.maximum(v, 0),
            "none": lambda v: v}
    if activation not in acts:
        raise ValueError(f"unsupported activation {activation}")
    return dispatch(acts[activation], (out,), name="fused_act")


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", name=None):
    """reference: incubate/nn/functional/fused_transformer.py
    fused_bias_dropout_residual_layer_norm:
    LN(residual + dropout(x + bias))."""
    from ....nn.functional.common import dropout
    from ....nn.functional.norm import layer_norm
    h = x if bias is None else x + _ensure(bias)
    h = dropout(h, p=dropout_rate, training=training, mode=mode)
    h = h + _ensure(residual)
    d = h.shape[-1]
    return layer_norm(h, (d,), weight=ln_scale, bias=ln_bias,
                      epsilon=ln_epsilon)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode=
                      "upscale_in_train", name=None):
    """reference: fused_transformer.py fused_feedforward —
    residual + dropout2(linear2(dropout1(act(linear1(maybe_ln(x))))))
    with pre- or post-LN."""
    from ....nn.functional.common import dropout
    from ....nn.functional.norm import layer_norm
    residual = x
    d = x.shape[-1]
    if pre_layer_norm:
        x = layer_norm(x, (d,), weight=ln1_scale, bias=ln1_bias,
                       epsilon=ln1_epsilon)
    h = fused_linear_activation(x, linear1_weight, linear1_bias,
                                activation=activation)
    h = dropout(h, p=dropout1_rate, training=training, mode=mode)
    h = fused_matmul_bias(h, linear2_weight, linear2_bias)
    h = dropout(h, p=dropout2_rate, training=training, mode=mode)
    out = _ensure(residual) + h
    if not pre_layer_norm:
        out = layer_norm(out, (d,), weight=ln2_scale, bias=ln2_bias,
                         epsilon=ln2_epsilon)
    return out


def _rotary_pairs(x, cos, sin, dims):
    """Pairwise (even, odd) rotary rotation, paddle fused-op convention:
    out[2i]   = x[2i]*cos[2i]   - x[2i+1]*sin[2i]
    out[2i+1] = x[2i+1]*cos[2i+1] + x[2i]*sin[2i+1]
    With dims==2 the head_dim splits into two halves, each rotated with
    its own cos/sin slice (reference rotary_emb_dims semantics).
    x [..., hd]; cos/sin broadcastable to x."""
    if dims <= 0:
        return x
    hd = x.shape[-1]
    chunk = hd // dims
    outs = []
    for i in range(dims):
        xp = x[..., i * chunk:(i + 1) * chunk]
        cp = jnp.broadcast_to(cos[..., i * chunk:(i + 1) * chunk],
                              xp.shape)
        sp = jnp.broadcast_to(sin[..., i * chunk:(i + 1) * chunk],
                              xp.shape)
        x_ev, x_od = xp[..., 0::2], xp[..., 1::2]
        r_ev = x_ev * cp[..., 0::2] - x_od * sp[..., 0::2]
        r_od = x_od * cp[..., 1::2] + x_ev * sp[..., 1::2]
        outs.append(jnp.stack([r_ev, r_od], axis=-1)
                    .reshape(xp.shape))
    return jnp.concatenate(outs, axis=-1) if dims > 1 else outs[0]


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm=False,
        pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None,
        pre_ln_epsilon=1e-5, qkv_bias=None, linear_bias=None,
        cache_kv=None, attn_mask=None, dropout_rate=0.5,
        attn_dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", ring_id=-1, add_residual=True,
        num_heads=None, name=None):
    """reference: fused_transformer.py fused_multi_head_attention —
    the whole MHA block (optional pre-LN, packed QKV GEMM, SDPA,
    out-projection, dropout, residual, optional post-LN) as one
    composition XLA fuses. qkv_weight [3, H, D, hidden].

    With cache_kv [2, B, H, C, hd] the call is a decode step: the new
    tokens' k/v are appended (cache grows, eager-mode semantics like the
    reference's CacheKVOut) and the query attends the full cache;
    returns (out, cache_kv_out). For a fixed-size jit-able cache use
    fused_multi_transformer(time_step=...) or the inference paged path."""
    import jax
    from ....nn.functional.common import dropout
    from ....nn.functional.norm import layer_norm
    if cache_kv is not None:
        return _fused_mha_cached(
            x, qkv_weight, linear_weight, cache_kv,
            pre_layer_norm=pre_layer_norm, pre_ln_scale=pre_ln_scale,
            pre_ln_bias=pre_ln_bias, ln_scale=ln_scale, ln_bias=ln_bias,
            pre_ln_epsilon=pre_ln_epsilon, qkv_bias=qkv_bias,
            linear_bias=linear_bias, attn_mask=attn_mask,
            ln_epsilon=ln_epsilon, add_residual=add_residual,
            dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate, training=training,
            mode=mode)
    residual = x
    hid = x.shape[-1]
    if pre_layer_norm:
        x = layer_norm(x, (hid,), weight=pre_ln_scale, bias=pre_ln_bias,
                       epsilon=pre_ln_epsilon)
    qkv_w = _ensure(qkv_weight)
    mask_t = _ensure(attn_mask) if attn_mask is not None else None
    args = (_ensure(x), qkv_w) + \
        ((_ensure(qkv_bias),) if qkv_bias is not None else ()) + \
        ((mask_t,) if mask_t is not None else ())
    has_bias = qkv_bias is not None
    has_mask = attn_mask is not None
    # a learned additive bias (ALiBi/relative-position) must keep its
    # gradient through the kernel
    mask_grad = has_mask and not mask_t.stop_gradient
    attn_drop = attn_dropout_rate if training else 0.0

    def attn(xv, wv, *rest):
        b, s, _ = xv.shape
        three, nh, hd, _ = wv.shape
        qkv = jnp.einsum("bsd,thed->bsthe", xv, wv)   # [B,S,3,H,hd]
        if has_bias:
            qkv = qkv + rest[0]
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        # the softmax(QK^T)V core rides the flash kernel (Pallas on TPU,
        # fused reference composition elsewhere); the additive mask maps
        # onto the kernel's bias operand (broadcast to full [.,.,S,S] —
        # the kernel requires explicit q/k dims), and attention dropout
        # is the kernel's in-probability dropout, matching the
        # reference's Philox-on-softmax semantics
        from ....ops.flash_attention import flash_attention as _fa
        bias = None
        if has_mask:
            m = rest[-1]
            bias = jnp.broadcast_to(
                m, (m.shape[0], m.shape[1], s, k.shape[1]))
        out = _fa(q, k, v, causal=False, bias=bias,
                  bias_grad=mask_grad, dropout_rate=attn_drop)
        return out.reshape(b, s, nh * hd)

    ctx = dispatch(attn, args, name="fused_mha_core")
    out = fused_matmul_bias(ctx, linear_weight, linear_bias)
    out = dropout(out, p=dropout_rate, training=training, mode=mode)
    if add_residual:
        out = _ensure(residual) + out
    if not pre_layer_norm:
        out = layer_norm(out, (hid,), weight=ln_scale, bias=ln_bias,
                         epsilon=ln_epsilon)
    return out


def _fused_mha_cached(x, qkv_weight, linear_weight, cache_kv,
                      pre_layer_norm, pre_ln_scale, pre_ln_bias, ln_scale,
                      ln_bias, pre_ln_epsilon, qkv_bias, linear_bias,
                      attn_mask, ln_epsilon, add_residual,
                      dropout_rate=0.0, attn_dropout_rate=0.0,
                      training=False, mode="upscale_in_train"):
    """Decode step for fused_multi_head_attention: append the new
    tokens' k/v to the [2, B, H, C, hd] cache, attend the grown cache
    (plain attention + user attn_mask, like the reference and the
    non-cached path), return (out, cache_kv_out). Attention-probability
    and output dropout apply exactly as in the non-cached path."""
    import jax
    from ....nn.functional.common import dropout
    from ....nn.functional.norm import layer_norm
    residual = x
    hid = x.shape[-1]
    if pre_layer_norm:
        x = layer_norm(x, (hid,), weight=pre_ln_scale, bias=pre_ln_bias,
                       epsilon=pre_ln_epsilon)
    has_bias = qkv_bias is not None
    has_mask = attn_mask is not None
    attn_drop = float(attn_dropout_rate) if training else 0.0
    key_t = None
    if attn_drop:
        from ....core import random as _rnd
        key_t = Tensor(_rnd.next_key())
    args = (_ensure(x), _ensure(qkv_weight), _ensure(cache_kv)) + \
        ((_ensure(qkv_bias),) if has_bias else ()) + \
        ((_ensure(attn_mask),) if has_mask else ()) + \
        ((key_t,) if key_t is not None else ())

    def attn(xv, wv, cache, *rest):
        ri = 0
        bias_v = mask_v = key_v = None
        if has_bias:
            bias_v, ri = rest[ri], ri + 1
        if has_mask:
            mask_v, ri = rest[ri], ri + 1
        if attn_drop:
            key_v = rest[ri]
        b, s, _ = xv.shape
        _, nh, hd, _ = wv.shape
        qkv = jnp.einsum("bsd,thed->bsthe", xv, wv)
        if has_bias:
            qkv = qkv + bias_v
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        # [B,S,H,hd] -> [B,H,S,hd], then grow the cache along seq
        k_new = jnp.moveaxis(k, 1, 2)
        v_new = jnp.moveaxis(v, 1, 2)
        k_all = jnp.concatenate([cache[0], k_new.astype(cache.dtype)], 2)
        v_all = jnp.concatenate([cache[1], v_new.astype(cache.dtype)], 2)
        score = jnp.einsum("bshe,bhte->bhst", q.astype(jnp.float32),
                           k_all.astype(jnp.float32)) / np.sqrt(hd)
        if has_mask:
            score = score + jnp.broadcast_to(
                mask_v.astype(jnp.float32), score.shape)
        # reference semantics: plain attention over [cache; new] — no
        # implicit causal mask (same as the non-cached path, which runs
        # flash_attention(causal=False)); decoders pass attn_mask for
        # causality during multi-token prefill, decode is s=1 anyway
        p = jax.nn.softmax(score, -1)
        if attn_drop:
            keep = jax.random.bernoulli(key_v, 1.0 - attn_drop,
                                        p.shape)
            if mode == "upscale_in_train":
                p = jnp.where(keep, p, 0.0) / (1.0 - attn_drop)
            else:
                p = jnp.where(keep, p, 0.0)
        ctx = jnp.einsum("bhst,bhte->bshe", p,
                         v_all.astype(jnp.float32)).astype(xv.dtype)
        return (ctx.reshape(b, s, nh * hd),
                jnp.stack([k_all, v_all]))

    ctx, cache_out = dispatch(attn, args, name="fused_mha_cached",
                              multi_output=True)
    out = fused_matmul_bias(ctx, linear_weight, linear_bias)
    out = dropout(out, p=dropout_rate, training=training, mode=mode)
    if add_residual:
        out = _ensure(residual) + out
    if not pre_layer_norm:
        out = layer_norm(out, (hid,), weight=ln_scale, bias=ln_bias,
                         epsilon=ln_epsilon)
    return out, cache_out


def fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, pre_layer_norm=True,
        epsilon=1e-5, cache_kvs=None, pre_caches=None, seq_lens=None,
        rotary_embs=None, rotary_emb_dims=0, time_step=None,
        attn_mask=None, dropout_rate=0.0, activation="gelu",
        training=False, mode="upscale_in_train", trans_qkvw=True,
        ring_id=-1, name=None):
    """reference: fused_transformer.py fused_multi_transformer — an
    N-layer pre-LN decoder stack in one call (the serving fast path;
    phi/kernels/fusion/gpu/fused_multi_transformer_*). Composes the
    per-layer fused MHA/FFN above; the compiled-generate path in
    paddle_tpu.inference covers the compiled generate/paged serving
    path; this op also serves cached decode directly:

    - cache_kvs: list of [2, B, H, max_seq, hd] per layer. Prefill
      (time_step None): the prompt's k/v (after the pre_caches prefix,
      if any) are written into positions [P, P+S) and the call returns
      (out, cache_kvs) with the caches updated in place. Decode
      (time_step=t, the real current cache length): x is [B, 1, hid],
      k/v written at position t, the query attends cache[0..t].
    - rotary_embs [2, B, 1, S, hd] (cos, sin): pairwise rotary applied
      to q/k per _rotary_pairs, rotary_emb_dims 1 or 2.
    - seq_lens [B]: per-example valid lengths. In prefill, shorter
      prompts' padded key slots are masked. In decode, seq_lens is the
      per-example current cache length: the new token writes at
      position seq_lens[b] and attends j <= seq_lens[b] (so garbage
      pad slots from a padded prefill are never read); the caller
      increments seq_lens by 1 each step.
    The whole N-layer stack + cache updates dispatch as ONE XLA program
    (static shapes, dynamic_update_slice at the traced time_step), so
    the decode step jits cleanly."""
    if cache_kvs is not None:
        return _fused_mt_cached(
            x, ln_scales, ln_biases, qkv_weights, qkv_biases,
            linear_weights, linear_biases, ffn_ln_scales, ffn_ln_biases,
            ffn1_weights, ffn1_biases, ffn2_weights, ffn2_biases,
            pre_layer_norm, epsilon, cache_kvs, pre_caches, seq_lens,
            rotary_embs, rotary_emb_dims, time_step, attn_mask,
            activation, trans_qkvw)
    if pre_caches is not None or time_step is not None or \
            rotary_embs is not None:
        raise ValueError(
            "fused_multi_transformer: pre_caches/time_step/rotary_embs "
            "require cache_kvs (generation mode)")
    if not trans_qkvw:
        raise NotImplementedError(
            "fused_multi_transformer: trans_qkvw=False layout not "
            "supported (pass [3, H, head_dim, hidden] weights)")
    out = x
    n_layers = len(qkv_weights)
    for i in range(n_layers):
        out = fused_multi_head_attention(
            out, qkv_weights[i],
            linear_weights[i], pre_layer_norm=pre_layer_norm,
            pre_ln_scale=ln_scales[i],
            pre_ln_bias=ln_biases[i] if ln_biases else None,
            qkv_bias=qkv_biases[i] if qkv_biases else None,
            linear_bias=linear_biases[i] if linear_biases else None,
            attn_mask=attn_mask, dropout_rate=dropout_rate,
            attn_dropout_rate=dropout_rate, pre_ln_epsilon=epsilon,
            training=training, mode=mode)
        out = fused_feedforward(
            out, ffn1_weights[i], ffn2_weights[i],
            linear1_bias=ffn1_biases[i] if ffn1_biases else None,
            linear2_bias=ffn2_biases[i] if ffn2_biases else None,
            ln1_scale=ffn_ln_scales[i],
            ln1_bias=ffn_ln_biases[i] if ffn_ln_biases else None,
            dropout1_rate=dropout_rate, dropout2_rate=dropout_rate,
            activation=activation, ln1_epsilon=epsilon,
            pre_layer_norm=pre_layer_norm, training=training, mode=mode)
    return out


def _fused_mt_cached(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                     linear_weights, linear_biases, ffn_ln_scales,
                     ffn_ln_biases, ffn1_weights, ffn1_biases,
                     ffn2_weights, ffn2_biases, pre_layer_norm, epsilon,
                     cache_kvs, pre_caches, seq_lens, rotary_embs,
                     rotary_emb_dims, time_step, attn_mask, activation,
                     trans_qkvw):
    """Generation-mode fused_multi_transformer (cache_kvs given): the
    N-layer stack, cache writes included, as ONE dispatched XLA program.
    See fused_multi_transformer's docstring for the phase semantics."""
    import jax
    if not trans_qkvw:
        raise NotImplementedError(
            "fused_multi_transformer: trans_qkvw=False layout not "
            "supported (pass [3, H, head_dim, hidden] weights)")
    n = len(qkv_weights)
    # ensure ONCE so the in-place _replace_value at the end hits the
    # same objects we return (a numpy-array cache would otherwise be
    # wrapped in a throwaway Tensor and the update silently lost)
    cache_kvs = [_ensure(c) for c in cache_kvs]
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu}[activation]
    has = {
        "ln_b": bool(ln_biases), "qkv_b": bool(qkv_biases),
        "lin_b": bool(linear_biases), "ffn_ln_b": bool(ffn_ln_biases),
        "ffn1_b": bool(ffn1_biases), "ffn2_b": bool(ffn2_biases),
        "pre": pre_caches is not None, "sl": seq_lens is not None,
        "rot": rotary_embs is not None, "mask": attn_mask is not None,
    }
    decode = time_step is not None

    per_layer, stride_keys = [], []
    for i in range(n):
        row = [ln_scales[i], qkv_weights[i], linear_weights[i],
               ffn_ln_scales[i], ffn1_weights[i], ffn2_weights[i],
               cache_kvs[i]]
        for flag, lst in (("ln_b", ln_biases), ("qkv_b", qkv_biases),
                          ("lin_b", linear_biases),
                          ("ffn_ln_b", ffn_ln_biases),
                          ("ffn1_b", ffn1_biases), ("ffn2_b", ffn2_biases),
                          ("pre", pre_caches)):
            if has[flag]:
                row.append(lst[i])
        per_layer.append([_ensure(v) for v in row])
    stride = len(per_layer[0])

    extras = []
    if has["sl"]:
        extras.append(_ensure(seq_lens))
    if has["rot"]:
        extras.append(_ensure(rotary_embs))
    if has["mask"]:
        extras.append(_ensure(attn_mask))
    if decode:
        ts = time_step if isinstance(time_step, Tensor) else \
            Tensor(np.asarray(time_step, np.int32).reshape(-1))
        extras.append(ts)

    args = (_ensure(x),) + tuple(v for row in per_layer for v in row) + \
        tuple(extras)

    def f(xv, *flat):
        layers = [flat[i * stride:(i + 1) * stride] for i in range(n)]
        rest = list(flat[n * stride:])
        sl = rest.pop(0) if has["sl"] else None
        rot = rest.pop(0) if has["rot"] else None
        mask = rest.pop(0) if has["mask"] else None
        t = rest.pop(0).reshape(()).astype(jnp.int32) if decode else None

        b, s, hid = xv.shape
        new_caches = []
        h = xv
        for row in layers:
            it = iter(row)
            ln_s, qkv_w, lin_w, ffn_ln_s, ffn1_w, ffn2_w, cache = \
                (next(it) for _ in range(7))
            ln_b = next(it) if has["ln_b"] else None
            qkv_b = next(it) if has["qkv_b"] else None
            lin_b = next(it) if has["lin_b"] else None
            ffn_ln_b = next(it) if has["ffn_ln_b"] else None
            ffn1_b = next(it) if has["ffn1_b"] else None
            ffn2_b = next(it) if has["ffn2_b"] else None
            pre = next(it) if has["pre"] else None

            def ln(v, w, bb):
                mu = jnp.mean(v, -1, keepdims=True)
                var = jnp.var(v, -1, keepdims=True)
                o = (v - mu) * jax.lax.rsqrt(var + epsilon)
                if w is not None:
                    o = o * w
                if bb is not None:
                    o = o + bb
                return o

            resid = h
            hin = ln(h, ln_s, ln_b) if pre_layer_norm else h
            _, nh, hd, _ = qkv_w.shape
            qkv = jnp.einsum("bsd,thed->bsthe", hin, qkv_w)
            if qkv_b is not None:
                qkv = qkv + qkv_b
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            if rot is not None:
                cos = rot[0][:, 0][:, :, None, :]   # [B,S,1,hd]
                sin = rot[1][:, 0][:, :, None, :]
                q = _rotary_pairs(q, cos, sin, max(rotary_emb_dims, 1))
                k = _rotary_pairs(k, cos, sin, max(rotary_emb_dims, 1))
            k_t = jnp.moveaxis(k, 1, 2).astype(cache.dtype)  # [B,H,S,hd]
            v_t = jnp.moveaxis(v, 1, 2).astype(cache.dtype)
            m_max = cache.shape[3]

            if decode:
                kv_new = jnp.stack([k_t, v_t])     # [2,B,H,1,hd]
                if sl is not None:
                    # ragged decode: each example's cache is its real
                    # prompt [0, sl[b]) plus its decoded tokens; the new
                    # token writes at sl[b] and attends j <= sl[b], so
                    # padded prompts' garbage slots are never read.
                    # The caller increments seq_lens each step.
                    idx = sl.reshape(b).astype(jnp.int32)
                    at = jnp.arange(m_max)[None, :] == idx[:, None]
                    cache = jnp.where(at[None, :, None, :, None],
                                      kv_new, cache)
                    live = (jnp.arange(m_max)[None, None, None, :]
                            <= idx[:, None, None, None])
                else:
                    z = jnp.asarray(0, jnp.int32)
                    cache = jax.lax.dynamic_update_slice(
                        cache, kv_new, (z, z, z, t, z))
                    live = jnp.arange(m_max)[None, None, None, :] <= t
                k_all, v_all = cache[0], cache[1]
                score = jnp.einsum(
                    "bshe,bhte->bhst", q.astype(jnp.float32),
                    k_all.astype(jnp.float32)) / np.sqrt(hd)
                if mask is not None:
                    score = score + jnp.broadcast_to(
                        mask.astype(jnp.float32), score.shape)
                score = jnp.where(live, score, -1e30)
            else:
                p_len = pre.shape[3] if pre is not None else 0
                if pre is not None:
                    cache = jax.lax.dynamic_update_slice(
                        cache, pre.astype(cache.dtype), (0, 0, 0, 0, 0))
                cache = jax.lax.dynamic_update_slice(
                    cache, jnp.stack([k_t, v_t]), (0, 0, 0, p_len, 0))
                sk = p_len + s
                k_all = cache[0, :, :, :sk]
                v_all = cache[1, :, :, :sk]
                score = jnp.einsum(
                    "bshe,bhte->bhst", q.astype(jnp.float32),
                    k_all.astype(jnp.float32)) / np.sqrt(hd)
                rows = jnp.arange(s)[:, None]
                cols = jnp.arange(sk)[None, :]
                causal = (cols < p_len) | (cols - p_len <= rows)
                if sl is not None:
                    valid = (cols[None] < p_len) | \
                        ((cols[None] - p_len) <
                         sl.reshape(b, 1, 1).astype(jnp.int32))
                    causal = causal[None] & valid
                    score = jnp.where(causal[:, None], score, -1e30)
                else:
                    score = jnp.where(causal[None, None], score, -1e30)
                if mask is not None:
                    mm = mask.astype(jnp.float32)
                    if mm.shape[-1] == s and p_len:
                        mm = jnp.pad(
                            mm, [(0, 0)] * (mm.ndim - 1) + [(p_len, 0)])
                    score = score + jnp.broadcast_to(mm, score.shape)

            p = jax.nn.softmax(score, -1)
            ctx = jnp.einsum("bhst,bhte->bshe", p,
                             v_all.astype(jnp.float32)).astype(h.dtype)
            attn_out = ctx.reshape(b, s, nh * hd) @ lin_w
            if lin_b is not None:
                attn_out = attn_out + lin_b
            h = resid + attn_out
            if not pre_layer_norm:
                h = ln(h, ln_s, ln_b)

            resid = h
            hin = ln(h, ffn_ln_s, ffn_ln_b) if pre_layer_norm else h
            ff = act(hin @ ffn1_w + (ffn1_b if ffn1_b is not None else 0))
            ff = ff @ ffn2_w + (ffn2_b if ffn2_b is not None else 0)
            h = resid + ff
            if not pre_layer_norm:
                h = ln(h, ffn_ln_s, ffn_ln_b)
            new_caches.append(cache)
        return (h,) + tuple(new_caches)

    outs = dispatch(f, args, name="fused_multi_transformer_cached",
                    multi_output=True)
    out, new_caches = outs[0], outs[1:]
    # reference semantics: cache_kvs is updated in place (the list was
    # _ensure'd to Tensors above, so these are the returned objects)
    for old, new in zip(cache_kvs, new_caches):
        old._replace_value(new._value)
    return out, list(cache_kvs)


def variable_length_memory_efficient_attention(
        query, key, value, seq_lens, kv_seq_lens, mask=None, scale=None,
        causal=False, pre_cache_length=0):
    """reference: incubate/nn/memory_efficient_attention.py varlen form
    — q/k/v [B, H, S, D] with per-example valid lengths; invalid
    positions masked out of the softmax. pre_cache_length P marks the
    first P key positions as an always-attendable prefix (prompt-tuning
    prefix cache): they bypass both kv_seq_lens and the causal rule, and
    kv_seq_lens counts only the non-prefix keys."""
    import jax
    q, k, v = _ensure(query), _ensure(key), _ensure(value)
    sl, kl = _ensure(seq_lens), _ensure(kv_seq_lens)
    args = (q, k, v, sl, kl) + ((_ensure(mask),)
                                if mask is not None else ())
    has_mask = mask is not None

    def f(qv, kv, vv, slv, klv, *m):
        b, h, sq, d = qv.shape
        sk = kv.shape[2]
        sc = scale if scale is not None else 1.0 / np.sqrt(d)
        score = jnp.einsum("bhsd,bhtd->bhst", qv.astype(jnp.float32),
                           kv.astype(jnp.float32)) * sc
        if has_mask:
            score = score + m[0]
        pcl = pre_cache_length
        live_q = jnp.arange(sq)[None, :] < slv.reshape(b, 1)
        kpos = jnp.arange(sk)[None, :]
        live_k = (kpos < pcl) | (kpos - pcl < klv.reshape(b, 1))
        score = jnp.where(live_k[:, None, None, :], score, -1e30)
        if causal:
            # bottom-right-aligned causal over the non-prefix keys:
            # query i sees key j iff j < P (prefix) or
            # j - P <= i + (sk - P - sq) (correct when sq != sk - P)
            rows = jnp.arange(sq)[:, None]
            cols = jnp.arange(sk)[None, :]
            ok = (cols < pcl) | (cols - pcl <= rows + (sk - pcl - sq))
            score = jnp.where(ok[None, None], score, -1e30)
        p = jax.nn.softmax(score, -1)
        out = jnp.einsum("bhst,bhtd->bhsd", p,
                         vv.astype(jnp.float32))
        out = jnp.where(live_q[:, None, :, None], out, 0.0)
        return out.astype(qv.dtype)

    return dispatch(f, args, name="varlen_mem_efficient_attention")


from .serving_attention import (blha_get_max_len,  # noqa: F401, E402
                                block_multihead_attention,
                                masked_multihead_attention)
