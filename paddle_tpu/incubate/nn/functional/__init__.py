"""Fused-op python APIs (reference: python/paddle/incubate/nn/functional/ —
fused_rms_norm, fused_rotary_position_embedding, swiglu, fused_adamw,
variable_length_memory_efficient_attention, block_multihead_attention, …).

On TPU these route to the ops/ pack (Pallas kernels + XLA compositions)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ....core.tensor import Tensor, dispatch
from .... import ops as _ops


def _ensure(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kw):
    def f(v, w, *b):
        out = _ops.rms_norm(v, w, epsilon)
        if b:
            out = out + b[0]
        return out
    args = (_ensure(x), _ensure(norm_weight))
    if norm_bias is not None:
        args += (_ensure(norm_bias),)
    out = dispatch(f, args, name="rms_norm")
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, **kw):
    args = (_ensure(x), _ensure(norm_weight), _ensure(norm_bias))
    return dispatch(lambda v, w, b: _ops.layer_norm(v, w, b, epsilon), args,
                    name="layer_norm")


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True):
    from ....ops.rope import apply_rope
    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        args = [_ensure(t)]
        extra = {}
        sin_v = sin._value if isinstance(sin, Tensor) else sin
        cos_v = cos._value if isinstance(cos, Tensor) else cos
        pid = position_ids._value if isinstance(position_ids, Tensor) \
            else position_ids
        outs.append(dispatch(
            lambda x: apply_rope(x, sin_v, cos_v, pid,
                                 use_neox_rotary_style),
            (args[0],), name="fused_rope"))
    return tuple(outs)


def swiglu(x, y=None, name=None):
    if y is None:
        return dispatch(lambda v: _ops.swiglu(v), (_ensure(x),),
                        name="swiglu")
    return dispatch(lambda a, b: _ops.swiglu(a, b),
                    (_ensure(x), _ensure(y)), name="swiglu")


def fused_bias_act(x, bias=None, act_method="gelu", **kw):
    import jax
    acts = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
            "swiglu": _ops.swiglu, "geglu": None}

    def f(v, *b):
        if b:
            v = v + b[0]
        return acts[act_method](v)
    args = (_ensure(x),)
    if bias is not None:
        args += (_ensure(bias),)
    return dispatch(f, args, name="fused_bias_act")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def f(v, w, *b):
        if transpose_weight:
            w = w.T
        out = v @ w
        if b:
            out = out + b[0]
        return out
    args = (_ensure(x), _ensure(weight))
    if bias is not None:
        args += (_ensure(bias),)
    return dispatch(f, args, name="matmul")


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    from ....nn.functional.common import dropout
    return dropout(x, p=p, training=training, mode=mode) + y


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn1_scale=None, ffn2_bias=None, ffn2_scale=None,
              quant_method="None", moe_topk=2, norm_topk_prob=True,
              capacity_factor=2.0):
    """Fused mixture-of-experts FFN (reference:
    python/paddle/incubate/nn/functional/fused_moe.py — a CUTLASS grouped
    GEMM on GPU).

    TPU-native formulation: GShard-style dense dispatch — gate top-k,
    scatter tokens into per-expert capacity buckets with one einsum, run
    every expert as one batched matmul ([E, C, D] @ [E, D, F], MXU-shaped,
    static shapes), combine with the gate weights. Unlike the exact
    grouped GEMM, tokens past ``capacity_factor * topk * T / E`` per
    expert are dropped (standard GShard semantics; raise the factor for
    exactness).

    x [B, S, D] (or [T, D]); gate_weight [D, E]; ffn1_weight [E, D, 2F]
    (swiglu) or [E, D, F] (gelu); ffn2_weight [E, F, D].
    """
    import jax
    import jax.numpy as jnp

    from ....core.tensor import dispatch
    from ....distributed.fleet.moe import moe_dispatch_combine

    if quant_method not in ("None", None, "none"):
        raise NotImplementedError(
            "fused_moe: weight quantization not supported (reference "
            "marks it 'currently not supported' too)")

    args = [_ensure(x), _ensure(gate_weight), _ensure(ffn1_weight),
            _ensure(ffn2_weight)]
    n_fixed = len(args)
    has_b1 = ffn1_bias is not None
    has_b2 = ffn2_bias is not None
    if has_b1:
        args.append(_ensure(ffn1_bias))
    if has_b2:
        args.append(_ensure(ffn2_bias))

    def f(xv, gw, w1, w2, *rest):
        b1 = rest[0] if has_b1 else None
        b2 = rest[int(has_b1)] if has_b2 else None
        lead = xv.shape[:-1]
        d = xv.shape[-1]
        flat = xv.reshape(-1, d)
        logits = flat.astype(jnp.float32) @ gw.astype(jnp.float32)
        e, _, two_f = w1.shape
        f_dim = w2.shape[1]
        glu = two_f == 2 * f_dim

        def expert_fn(expert_in):       # [E, C, D]
            h = jnp.einsum("ecd,edf->ecf", expert_in, w1)
            if b1 is not None:
                h = h + b1.reshape(e, 1, -1)
            if glu:
                a, g = jnp.split(h, 2, axis=-1)
                h = jax.nn.silu(a) * g
            else:
                h = jax.nn.gelu(h)
            out = jnp.einsum("ecf,efd->ecd", h, w2)
            if b2 is not None:
                out = out + b2.reshape(e, 1, -1)
            return out

        out, _aux = moe_dispatch_combine(
            flat, logits, expert_fn, top_k=moe_topk,
            capacity_factor=capacity_factor,
            norm_topk_prob=norm_topk_prob, warn_on_drop=True)
        return out.reshape(*lead, d)

    return dispatch(f, args, name="fused_moe")


from .fp8 import (quantize_fp8, dequantize_fp8, fp8_gemm,  # noqa: F401,E402
                  fp8_linear)


def fused_matmul_bias(x, y, bias=None, transpose_x=False,
                      transpose_y=False, name=None):
    """reference: incubate/nn/functional/fused_matmul_bias — one fused
    GEMM+bias (XLA fuses the bias add into the matmul epilogue)."""
    def f(a, b, *bb):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        return out + bb[0] if bb else out

    args = (_ensure(x), _ensure(y)) + ((_ensure(bias),)
                                       if bias is not None else ())
    return dispatch(f, args, name="fused_matmul_bias")


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu", name=None):
    """reference: fused_linear_activation — GEMM + bias + epilogue act."""
    import jax
    out = fused_matmul_bias(x, y, bias, trans_x, trans_y)
    acts = {"gelu": lambda v: jax.nn.gelu(v, approximate=True),
            "relu": lambda v: jnp.maximum(v, 0),
            "none": lambda v: v}
    if activation not in acts:
        raise ValueError(f"unsupported activation {activation}")
    return dispatch(acts[activation], (out,), name="fused_act")


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", name=None):
    """reference: incubate/nn/functional/fused_transformer.py
    fused_bias_dropout_residual_layer_norm:
    LN(residual + dropout(x + bias))."""
    from ....nn.functional.common import dropout
    from ....nn.functional.norm import layer_norm
    h = x if bias is None else x + _ensure(bias)
    h = dropout(h, p=dropout_rate, training=training, mode=mode)
    h = h + _ensure(residual)
    d = h.shape[-1]
    return layer_norm(h, (d,), weight=ln_scale, bias=ln_bias,
                      epsilon=ln_epsilon)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode=
                      "upscale_in_train", name=None):
    """reference: fused_transformer.py fused_feedforward —
    residual + dropout2(linear2(dropout1(act(linear1(maybe_ln(x))))))
    with pre- or post-LN."""
    from ....nn.functional.common import dropout
    from ....nn.functional.norm import layer_norm
    residual = x
    d = x.shape[-1]
    if pre_layer_norm:
        x = layer_norm(x, (d,), weight=ln1_scale, bias=ln1_bias,
                       epsilon=ln1_epsilon)
    h = fused_linear_activation(x, linear1_weight, linear1_bias,
                                activation=activation)
    h = dropout(h, p=dropout1_rate, training=training, mode=mode)
    h = fused_matmul_bias(h, linear2_weight, linear2_bias)
    h = dropout(h, p=dropout2_rate, training=training, mode=mode)
    out = _ensure(residual) + h
    if not pre_layer_norm:
        out = layer_norm(out, (d,), weight=ln2_scale, bias=ln2_bias,
                         epsilon=ln2_epsilon)
    return out


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm=False,
        pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None,
        pre_ln_epsilon=1e-5, qkv_bias=None, linear_bias=None,
        cache_kv=None, attn_mask=None, dropout_rate=0.5,
        attn_dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", ring_id=-1, add_residual=True,
        num_heads=None, name=None):
    """reference: fused_transformer.py fused_multi_head_attention —
    the whole MHA block (optional pre-LN, packed QKV GEMM, SDPA,
    out-projection, dropout, residual, optional post-LN) as one
    composition XLA fuses. qkv_weight [3, H, D, hidden]."""
    import jax
    from ....nn.functional.common import dropout
    from ....nn.functional.norm import layer_norm
    if cache_kv is not None:
        raise NotImplementedError(
            "fused_multi_head_attention: cache_kv decode is served by "
            "paddle_tpu.inference's compiled generate/paged path")
    residual = x
    hid = x.shape[-1]
    if pre_layer_norm:
        x = layer_norm(x, (hid,), weight=pre_ln_scale, bias=pre_ln_bias,
                       epsilon=pre_ln_epsilon)
    qkv_w = _ensure(qkv_weight)
    mask_t = _ensure(attn_mask) if attn_mask is not None else None
    args = (_ensure(x), qkv_w) + \
        ((_ensure(qkv_bias),) if qkv_bias is not None else ()) + \
        ((mask_t,) if mask_t is not None else ())
    has_bias = qkv_bias is not None
    has_mask = attn_mask is not None
    # a learned additive bias (ALiBi/relative-position) must keep its
    # gradient through the kernel
    mask_grad = has_mask and not mask_t.stop_gradient
    attn_drop = attn_dropout_rate if training else 0.0

    def attn(xv, wv, *rest):
        b, s, _ = xv.shape
        three, nh, hd, _ = wv.shape
        qkv = jnp.einsum("bsd,thed->bsthe", xv, wv)   # [B,S,3,H,hd]
        if has_bias:
            qkv = qkv + rest[0]
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        # the softmax(QK^T)V core rides the flash kernel (Pallas on TPU,
        # fused reference composition elsewhere); the additive mask maps
        # onto the kernel's bias operand (broadcast to full [.,.,S,S] —
        # the kernel requires explicit q/k dims), and attention dropout
        # is the kernel's in-probability dropout, matching the
        # reference's Philox-on-softmax semantics
        from ....ops.flash_attention import flash_attention as _fa
        bias = None
        if has_mask:
            m = rest[-1]
            bias = jnp.broadcast_to(
                m, (m.shape[0], m.shape[1], s, k.shape[1]))
        out = _fa(q, k, v, causal=False, bias=bias,
                  bias_grad=mask_grad, dropout_rate=attn_drop)
        return out.reshape(b, s, nh * hd)

    ctx = dispatch(attn, args, name="fused_mha_core")
    out = fused_matmul_bias(ctx, linear_weight, linear_bias)
    out = dropout(out, p=dropout_rate, training=training, mode=mode)
    if add_residual:
        out = _ensure(residual) + out
    if not pre_layer_norm:
        out = layer_norm(out, (hid,), weight=ln_scale, bias=ln_bias,
                         epsilon=ln_epsilon)
    return out


def fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, pre_layer_norm=True,
        epsilon=1e-5, cache_kvs=None, pre_caches=None, seq_lens=None,
        rotary_embs=None, rotary_emb_dims=0, time_step=None,
        attn_mask=None, dropout_rate=0.0, activation="gelu",
        training=False, mode="upscale_in_train", trans_qkvw=True,
        ring_id=-1, name=None):
    """reference: fused_transformer.py fused_multi_transformer — an
    N-layer pre-LN decoder stack in one call (the serving fast path;
    phi/kernels/fusion/gpu/fused_multi_transformer_*). Composes the
    per-layer fused MHA/FFN above; the compiled-generate path in
    paddle_tpu.inference covers the cached-decode use."""
    if cache_kvs is not None or pre_caches is not None or \
            time_step is not None or rotary_embs is not None:
        raise NotImplementedError(
            "fused_multi_transformer: cached/rotary decode is served by "
            "paddle_tpu.inference's compiled generate/paged path")
    if not trans_qkvw:
        raise NotImplementedError(
            "fused_multi_transformer: trans_qkvw=False layout not "
            "supported (pass [3, H, head_dim, hidden] weights)")
    out = x
    n_layers = len(qkv_weights)
    for i in range(n_layers):
        out = fused_multi_head_attention(
            out, qkv_weights[i],
            linear_weights[i], pre_layer_norm=pre_layer_norm,
            pre_ln_scale=ln_scales[i],
            pre_ln_bias=ln_biases[i] if ln_biases else None,
            qkv_bias=qkv_biases[i] if qkv_biases else None,
            linear_bias=linear_biases[i] if linear_biases else None,
            attn_mask=attn_mask, dropout_rate=dropout_rate,
            attn_dropout_rate=dropout_rate, pre_ln_epsilon=epsilon,
            training=training, mode=mode)
        out = fused_feedforward(
            out, ffn1_weights[i], ffn2_weights[i],
            linear1_bias=ffn1_biases[i] if ffn1_biases else None,
            linear2_bias=ffn2_biases[i] if ffn2_biases else None,
            ln1_scale=ffn_ln_scales[i],
            ln1_bias=ffn_ln_biases[i] if ffn_ln_biases else None,
            dropout1_rate=dropout_rate, dropout2_rate=dropout_rate,
            activation=activation, ln1_epsilon=epsilon,
            pre_layer_norm=pre_layer_norm, training=training, mode=mode)
    return out


def variable_length_memory_efficient_attention(
        query, key, value, seq_lens, kv_seq_lens, mask=None, scale=None,
        causal=False, pre_cache_length=0):
    """reference: incubate/nn/memory_efficient_attention.py varlen form
    — q/k/v [B, H, S, D] with per-example valid lengths; invalid
    positions masked out of the softmax."""
    import jax
    if pre_cache_length:
        raise NotImplementedError(
            "variable_length_memory_efficient_attention: "
            "pre_cache_length != 0 is served by the paged/compiled "
            "decode path in paddle_tpu.inference")
    q, k, v = _ensure(query), _ensure(key), _ensure(value)
    sl, kl = _ensure(seq_lens), _ensure(kv_seq_lens)
    args = (q, k, v, sl, kl) + ((_ensure(mask),)
                                if mask is not None else ())
    has_mask = mask is not None

    def f(qv, kv, vv, slv, klv, *m):
        b, h, sq, d = qv.shape
        sk = kv.shape[2]
        sc = scale if scale is not None else 1.0 / np.sqrt(d)
        score = jnp.einsum("bhsd,bhtd->bhst", qv.astype(jnp.float32),
                           kv.astype(jnp.float32)) * sc
        if has_mask:
            score = score + m[0]
        live_q = jnp.arange(sq)[None, :] < slv.reshape(b, 1)
        live_k = jnp.arange(sk)[None, :] < klv.reshape(b, 1)
        score = jnp.where(live_k[:, None, None, :], score, -1e30)
        if causal:
            # bottom-right-aligned causal: query i sees key j iff
            # j <= i + (sk - sq) (correct when sq != sk, e.g. decode)
            rows = jnp.arange(sq)[:, None]
            cols = jnp.arange(sk)[None, :]
            score = jnp.where((cols <= rows + (sk - sq))[None, None],
                              score, -1e30)
        p = jax.nn.softmax(score, -1)
        out = jnp.einsum("bhst,bhtd->bhsd", p,
                         vv.astype(jnp.float32))
        out = jnp.where(live_q[:, None, :, None], out, 0.0)
        return out.astype(qv.dtype)

    return dispatch(f, args, name="varlen_mem_efficient_attention")
