"""Fused-op python APIs (reference: python/paddle/incubate/nn/functional/ —
fused_rms_norm, fused_rotary_position_embedding, swiglu, fused_adamw,
variable_length_memory_efficient_attention, block_multihead_attention, …).

On TPU these route to the ops/ pack (Pallas kernels + XLA compositions)."""
from __future__ import annotations

import jax.numpy as jnp

from ....core.tensor import Tensor, dispatch
from .... import ops as _ops


def _ensure(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kw):
    def f(v, w, *b):
        out = _ops.rms_norm(v, w, epsilon)
        if b:
            out = out + b[0]
        return out
    args = (_ensure(x), _ensure(norm_weight))
    if norm_bias is not None:
        args += (_ensure(norm_bias),)
    out = dispatch(f, args, name="rms_norm")
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, **kw):
    args = (_ensure(x), _ensure(norm_weight), _ensure(norm_bias))
    return dispatch(lambda v, w, b: _ops.layer_norm(v, w, b, epsilon), args,
                    name="layer_norm")


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True):
    from ....ops.rope import apply_rope
    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        args = [_ensure(t)]
        extra = {}
        sin_v = sin._value if isinstance(sin, Tensor) else sin
        cos_v = cos._value if isinstance(cos, Tensor) else cos
        pid = position_ids._value if isinstance(position_ids, Tensor) \
            else position_ids
        outs.append(dispatch(
            lambda x: apply_rope(x, sin_v, cos_v, pid,
                                 use_neox_rotary_style),
            (args[0],), name="fused_rope"))
    return tuple(outs)


def swiglu(x, y=None, name=None):
    if y is None:
        return dispatch(lambda v: _ops.swiglu(v), (_ensure(x),),
                        name="swiglu")
    return dispatch(lambda a, b: _ops.swiglu(a, b),
                    (_ensure(x), _ensure(y)), name="swiglu")


def fused_bias_act(x, bias=None, act_method="gelu", **kw):
    import jax
    acts = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
            "swiglu": _ops.swiglu, "geglu": None}

    def f(v, *b):
        if b:
            v = v + b[0]
        return acts[act_method](v)
    args = (_ensure(x),)
    if bias is not None:
        args += (_ensure(bias),)
    return dispatch(f, args, name="fused_bias_act")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def f(v, w, *b):
        if transpose_weight:
            w = w.T
        out = v @ w
        if b:
            out = out + b[0]
        return out
    args = (_ensure(x), _ensure(weight))
    if bias is not None:
        args += (_ensure(bias),)
    return dispatch(f, args, name="matmul")


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    from ....nn.functional.common import dropout
    return dropout(x, p=p, training=training, mode=mode) + y


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn1_scale=None, ffn2_bias=None, ffn2_scale=None,
              quant_method="None", moe_topk=2, norm_topk_prob=True,
              capacity_factor=2.0):
    """Fused mixture-of-experts FFN (reference:
    python/paddle/incubate/nn/functional/fused_moe.py — a CUTLASS grouped
    GEMM on GPU).

    TPU-native formulation: GShard-style dense dispatch — gate top-k,
    scatter tokens into per-expert capacity buckets with one einsum, run
    every expert as one batched matmul ([E, C, D] @ [E, D, F], MXU-shaped,
    static shapes), combine with the gate weights. Unlike the exact
    grouped GEMM, tokens past ``capacity_factor * topk * T / E`` per
    expert are dropped (standard GShard semantics; raise the factor for
    exactness).

    x [B, S, D] (or [T, D]); gate_weight [D, E]; ffn1_weight [E, D, 2F]
    (swiglu) or [E, D, F] (gelu); ffn2_weight [E, F, D].
    """
    import jax
    import jax.numpy as jnp

    from ....core.tensor import dispatch
    from ....distributed.fleet.moe import moe_dispatch_combine

    if quant_method not in ("None", None, "none"):
        raise NotImplementedError(
            "fused_moe: weight quantization not supported (reference "
            "marks it 'currently not supported' too)")

    args = [_ensure(x), _ensure(gate_weight), _ensure(ffn1_weight),
            _ensure(ffn2_weight)]
    n_fixed = len(args)
    has_b1 = ffn1_bias is not None
    has_b2 = ffn2_bias is not None
    if has_b1:
        args.append(_ensure(ffn1_bias))
    if has_b2:
        args.append(_ensure(ffn2_bias))

    def f(xv, gw, w1, w2, *rest):
        b1 = rest[0] if has_b1 else None
        b2 = rest[int(has_b1)] if has_b2 else None
        lead = xv.shape[:-1]
        d = xv.shape[-1]
        flat = xv.reshape(-1, d)
        logits = flat.astype(jnp.float32) @ gw.astype(jnp.float32)
        e, _, two_f = w1.shape
        f_dim = w2.shape[1]
        glu = two_f == 2 * f_dim

        def expert_fn(expert_in):       # [E, C, D]
            h = jnp.einsum("ecd,edf->ecf", expert_in, w1)
            if b1 is not None:
                h = h + b1.reshape(e, 1, -1)
            if glu:
                a, g = jnp.split(h, 2, axis=-1)
                h = jax.nn.silu(a) * g
            else:
                h = jax.nn.gelu(h)
            out = jnp.einsum("ecf,efd->ecd", h, w2)
            if b2 is not None:
                out = out + b2.reshape(e, 1, -1)
            return out

        out, _aux = moe_dispatch_combine(
            flat, logits, expert_fn, top_k=moe_topk,
            capacity_factor=capacity_factor,
            norm_topk_prob=norm_topk_prob, warn_on_drop=True)
        return out.reshape(*lead, d)

    return dispatch(f, args, name="fused_moe")


from .fp8 import (quantize_fp8, dequantize_fp8, fp8_gemm,  # noqa: F401,E402
                  fp8_linear)
