"""Fused-op python APIs (reference: python/paddle/incubate/nn/functional/ —
fused_rms_norm, fused_rotary_position_embedding, swiglu, fused_adamw,
variable_length_memory_efficient_attention, block_multihead_attention, …).

On TPU these route to the ops/ pack (Pallas kernels + XLA compositions)."""
from __future__ import annotations

import jax.numpy as jnp

from ....core.tensor import Tensor, dispatch
from .... import ops as _ops


def _ensure(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kw):
    def f(v, w, *b):
        out = _ops.rms_norm(v, w, epsilon)
        if b:
            out = out + b[0]
        return out
    args = (_ensure(x), _ensure(norm_weight))
    if norm_bias is not None:
        args += (_ensure(norm_bias),)
    out = dispatch(f, args, name="rms_norm")
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, **kw):
    args = (_ensure(x), _ensure(norm_weight), _ensure(norm_bias))
    return dispatch(lambda v, w, b: _ops.layer_norm(v, w, b, epsilon), args,
                    name="layer_norm")


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True):
    from ....ops.rope import apply_rope
    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        args = [_ensure(t)]
        extra = {}
        sin_v = sin._value if isinstance(sin, Tensor) else sin
        cos_v = cos._value if isinstance(cos, Tensor) else cos
        pid = position_ids._value if isinstance(position_ids, Tensor) \
            else position_ids
        outs.append(dispatch(
            lambda x: apply_rope(x, sin_v, cos_v, pid,
                                 use_neox_rotary_style),
            (args[0],), name="fused_rope"))
    return tuple(outs)


def swiglu(x, y=None, name=None):
    if y is None:
        return dispatch(lambda v: _ops.swiglu(v), (_ensure(x),),
                        name="swiglu")
    return dispatch(lambda a, b: _ops.swiglu(a, b),
                    (_ensure(x), _ensure(y)), name="swiglu")


def fused_bias_act(x, bias=None, act_method="gelu", **kw):
    import jax
    acts = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
            "swiglu": _ops.swiglu, "geglu": None}

    def f(v, *b):
        if b:
            v = v + b[0]
        return acts[act_method](v)
    args = (_ensure(x),)
    if bias is not None:
        args += (_ensure(bias),)
    return dispatch(f, args, name="fused_bias_act")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def f(v, w, *b):
        if transpose_weight:
            w = w.T
        out = v @ w
        if b:
            out = out + b[0]
        return out
    args = (_ensure(x), _ensure(weight))
    if bias is not None:
        args += (_ensure(bias),)
    return dispatch(f, args, name="matmul")


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    from ....nn.functional.common import dropout
    return dropout(x, p=p, training=training, mode=mode) + y
