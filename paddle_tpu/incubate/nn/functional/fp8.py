"""FP8 path (reference: paddle/phi/kernels/fusion/fp8_gemm/ — CUTLASS
fp8 GEMM with per-tensor scales and fused epilogues; exposed via
incubate fused ops).

TPU-native form: newer TPU generations execute fp8 matmuls on the MXU
directly; under XLA that is ``lax.dot_general`` on float8_e4m3fn /
float8_e5m2 operands with ``preferred_element_type`` carrying the
accumulator dtype. The pattern is the standard per-tensor dynamic
scaling recipe: quantize each operand to fp8 with its own scale,
multiply in fp8, rescale the accumulator once.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ....core.tensor import Tensor, dispatch, to_value

__all__ = ["quantize_fp8", "dequantize_fp8", "fp8_gemm", "fp8_linear",
           "fp8_delayed_state", "quantize_fp8_delayed",
           "fp8_linear_delayed"]

_FP8 = {"e4m3": jnp.float8_e4m3fn, "e5m2": jnp.float8_e5m2}
_FP8_MAX = {"e4m3": 448.0, "e5m2": 57344.0}


def _fmt(format):
    if format not in _FP8:
        raise ValueError(f"fp8 format must be e4m3 or e5m2, got {format}")
    return _FP8[format], _FP8_MAX[format]


def quantize_fp8(x, scale=None, format="e4m3"):
    """Per-tensor quantize to fp8. scale=None computes the dynamic
    per-tensor scale amax/fp8_max (the reference's delayed-scaling
    counterpart is an amax history; per-call amax is the static-graph
    equivalent). Returns ``(x_fp8, scale)``; ``x ~= x_fp8 * scale``."""
    dt, fmax = _fmt(format)
    x = x if isinstance(x, Tensor) else Tensor(x)

    def f(v):
        v32 = v.astype(jnp.float32)
        s = (jnp.max(jnp.abs(v32)) / fmax if scale is None
             else jnp.asarray(scale, jnp.float32))
        s = jnp.maximum(s, 1e-12)
        q = jnp.clip(v32 / s, -fmax, fmax).astype(dt)
        return q, s

    return dispatch(f, (x,), name="quantize_fp8", multi_output=True)


def dequantize_fp8(x_fp8, scale):
    x_fp8 = x_fp8 if isinstance(x_fp8, Tensor) else Tensor(x_fp8)
    scale = scale if isinstance(scale, Tensor) else Tensor(scale)
    return dispatch(lambda q, s: q.astype(jnp.float32) * s,
                    (x_fp8, scale), name="dequantize_fp8")


def fp8_gemm(x_fp8, x_scale, w_fp8, w_scale, bias=None,
             transpose_w=False, out_dtype="bfloat16"):
    """fp8 x fp8 -> out_dtype matmul with one accumulator rescale
    (reference fp8_gemm fused epilogue: alpha = sx*sw, beta-bias)."""
    args = [t if isinstance(t, Tensor) else Tensor(t)
            for t in (x_fp8, x_scale, w_fp8, w_scale)]
    if bias is not None:
        args.append(bias if isinstance(bias, Tensor) else Tensor(bias))
    odt = jnp.dtype(out_dtype)

    def f(q, sx, w, sw, *b):
        if transpose_w:
            w = w.T
        acc = lax.dot_general(
            q, w, (((q.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        out = acc * (sx * sw)
        if b:
            out = out + b[0].astype(jnp.float32)
        return out.astype(odt)

    return dispatch(f, tuple(args), name="fp8_gemm")


def fp8_linear(x, weight, bias=None, format="e4m3", out_dtype="bfloat16"):
    """Dynamic-scaling fp8 linear: quantize x and weight per-tensor,
    multiply in fp8 on the MXU, rescale once. Gradients flow via the
    straight-through pattern of the quantize ops' vjp."""
    xq, sx = quantize_fp8(x, format=format)
    wq, sw = quantize_fp8(weight, format=format)
    return fp8_gemm(xq, sx, wq, sw, bias=bias, out_dtype=out_dtype)


# ---------------------------------------------------------------------------
# delayed scaling (amax history) — the reference's production fp8 recipe
# ---------------------------------------------------------------------------

def fp8_delayed_state(history_len=16):
    """Fresh delayed-scaling state for ONE tensor: a rolling amax
    history (reference: transformer-engine-style recipe the fp8_gemm
    kernels are driven by in production; scale is derived from the max
    of the last `history_len` amaxes instead of the current batch, so
    quantization runs scale-first without a pre-pass over the data).
    The state is a plain dict of Tensors so it checkpoints like any
    other optimizer/layer state."""
    return {"amax_history": Tensor(jnp.zeros((history_len,),
                                             jnp.float32))}


def quantize_fp8_delayed(x, state, format="e4m3", margin=0.0):
    """Quantize with the DELAYED scale (from the state's amax history),
    then record the current amax into the rolling history. Returns
    ``(x_fp8, scale_used, new_state)`` — functional update; callers
    carry new_state forward (and may checkpoint it).

    First call (all-zero history) falls back to the current amax so the
    initial step is not catastrophically clipped."""
    dt, fmax = _fmt(format)
    x = x if isinstance(x, Tensor) else Tensor(x)
    hist = state["amax_history"]
    hist = hist if isinstance(hist, Tensor) else Tensor(hist)

    def f(v, h):
        v32 = v.astype(jnp.float32)
        amax_now = jnp.max(jnp.abs(v32))
        amax_hist = jnp.max(h)
        amax = jnp.where(amax_hist > 0.0, amax_hist, amax_now)
        s = jnp.maximum(amax / fmax * (2.0 ** margin), 1e-12)
        q = jnp.clip(v32 / s, -fmax, fmax).astype(dt)
        new_h = jnp.roll(h, 1).at[0].set(amax_now)
        return q, s, new_h

    q, s, new_h = dispatch(f, (x, hist), name="quantize_fp8_delayed",
                           multi_output=True)
    return q, s, {"amax_history": new_h}


def fp8_linear_delayed(x, weight, x_state, w_state, bias=None,
                       format="e4m3", out_dtype="bfloat16", margin=0.0):
    """fp8 linear under delayed scaling: both operands quantize with
    their history-derived scales (no data pre-pass on the hot path).
    Returns ``(out, new_x_state, new_w_state)``."""
    xq, sx, x_state = quantize_fp8_delayed(x, x_state, format=format,
                                           margin=margin)
    wq, sw, w_state = quantize_fp8_delayed(weight, w_state,
                                           format=format, margin=margin)
    out = fp8_gemm(xq, sx, wq, sw, bias=bias, out_dtype=out_dtype)
    return out, x_state, w_state
