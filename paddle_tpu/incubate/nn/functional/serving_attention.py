"""Serving-side fused attention ops (reference:
python/paddle/incubate/nn/functional/block_multihead_attention.py:33,
masked_multihead_attention.py:74, blha_get_max_len.py:26 — the CUDA
fusion kernels behind paddle/phi/kernels/fusion/gpu/
block_multi_head_attention_kernel.cu and masked_multihead_attention).

TPU-native design: both ops are one jit-fusable jnp program — the
block (paged) variant drives the same pool/table machinery as
``paddle_tpu.ops.paged_attention`` (the Pallas decode kernel underneath
on TPU), the masked variant is a single fused decode step over a dense
[2, B, H, S, D] cache. Quantized-cache / beam-search / smooth-quant
extras are gated loudly (the serving path here runs bf16 caches; int8
cache quant is a memory optimization the paged pools don't need at
these shapes).
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ....core.tensor import Tensor, dispatch

__all__ = ["blha_get_max_len", "block_multihead_attention",
           "masked_multihead_attention"]


def _ensure(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size):
    """Max encoder/decoder length this step (reference:
    blha_get_max_len.py:26 — a tiny fused reduction used to pick the
    kernel path before block_multihead_attention)."""
    a = _ensure(seq_lens_encoder)
    b = _ensure(seq_lens_decoder)
    return dispatch(
        lambda e, d: (jnp.max(e).astype(jnp.int32).reshape(1),
                      jnp.max(d).astype(jnp.int32).reshape(1)),
        (a, b), name="blha_get_max_len", multi_output=True)


def _gate(kwargs):
    unsupported = {k: v for k, v in kwargs.items() if v is not None}
    if unsupported:
        raise NotImplementedError(
            "block/masked multihead attention: quantized-cache / "
            "beam-search / smooth-quant arguments are not part of the "
            f"TPU serving path (got {sorted(unsupported)}); the bf16 "
            "paged pools make the int8-cache memory optimization "
            "unnecessary at serving shapes")


def masked_multihead_attention(
        x, cache_kv=None, bias=None, src_mask=None, cum_offsets=None,
        sequence_lengths=None, rotary_tensor=None, beam_cache_offset=None,
        qkv_out_scale=None, out_shift=None, out_smooth=None, seq_len=1,
        rotary_emb_dims=0, use_neox_rotary_style=False,
        compute_dtype="default", out_scale=-1, quant_round_type=1,
        quant_max_bound=127.0, quant_min_bound=-127.0):
    """One fused decode step over a dense cache (reference:
    masked_multihead_attention.py:74). x [B, 3*H*D] packed qkv for the
    CURRENT token; cache_kv [2, B, H, S_max, D]; sequence_lengths [B]
    or [B,1] = number of tokens already in the cache (the new token is
    written at that slot). Returns (out [B, H*D], cache_kv_out)."""
    _gate(dict(cum_offsets=cum_offsets, rotary_tensor=rotary_tensor,
               beam_cache_offset=beam_cache_offset,
               qkv_out_scale=qkv_out_scale, out_shift=out_shift,
               out_smooth=out_smooth))
    if cache_kv is None:
        raise ValueError("masked_multihead_attention requires cache_kv")
    if out_scale is not None and out_scale > 0:
        raise NotImplementedError(
            "masked_multihead_attention: quantized output "
            "(out_scale > 0) is not part of the TPU serving path")
    x = _ensure(x)
    cache_kv = _ensure(cache_kv)
    args = [x, cache_kv]
    if bias is not None:
        args.append(_ensure(bias))
    if src_mask is not None:
        args.append(_ensure(src_mask))
    if sequence_lengths is not None:
        args.append(_ensure(sequence_lengths))
    has_bias = bias is not None
    has_mask = src_mask is not None
    has_lens = sequence_lengths is not None

    def f(xv, cache, *rest):
        i = 0
        b = rest[i] if has_bias else None
        i += int(has_bias)
        m = rest[i] if has_mask else None
        i += int(has_mask)
        lens = rest[i] if has_lens else None
        _, B, H, S, D = cache.shape
        qkv = xv.reshape(B, 3, H, D)
        if b is not None:
            qkv = qkv + b.reshape(1, 3, H, D).astype(qkv.dtype)
        q, k_new, v_new = qkv[:, 0], qkv[:, 1], qkv[:, 2]   # [B, H, D]
        if lens is None:
            pos = jnp.full((B,), seq_len - 1, jnp.int32)
        else:
            pos = lens.reshape(B).astype(jnp.int32)
        # write the new token at its slot: one-hot scatter keeps the
        # whole step a single fused program (no dynamic slices per seq)
        onehot = (jnp.arange(S)[None, :] == pos[:, None])   # [B, S]
        sel = onehot[:, None, :, None]                      # [B,1,S,1]
        kc = jnp.where(sel, k_new[:, :, None, :].astype(cache.dtype),
                       cache[0])
        vc = jnp.where(sel, v_new[:, :, None, :].astype(cache.dtype),
                       cache[1])
        # attend over positions <= pos
        live = jnp.arange(S)[None, :] <= pos[:, None]       # [B, S]
        s = jnp.einsum("bhd,bhsd->bhs", q.astype(jnp.float32),
                       kc.astype(jnp.float32)) / np.sqrt(D)
        if m is not None:
            mm = m.astype(jnp.float32).reshape(B, 1, -1)
            # clamp BEFORE padding, mirroring the decode tgt_mask path:
            # a mask longer than the cache S_max would make the pad
            # width negative and jnp.pad raises
            mm = mm[:, :, :S]
            s = s + jnp.pad(mm, ((0, 0), (0, 0), (0, S - mm.shape[-1])))
        s = jnp.where(live[:, None, :], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhs,bhsd->bhd", p, vc.astype(jnp.float32))
        out = o.reshape(B, H * D).astype(xv.dtype)
        return out, jnp.stack([kc, vc])

    return dispatch(f, tuple(args), name="masked_multihead_attention",
                    multi_output=True)


def block_multihead_attention(
        qkv, key_cache, value_cache, seq_lens_encoder, seq_lens_decoder,
        seq_lens_this_time, padding_offsets, cum_offsets, cu_seqlens_q,
        cu_seqlens_k, block_tables, pre_key_cache=None,
        pre_value_cache=None, cache_k_quant_scales=None,
        cache_v_quant_scales=None, cache_k_dequant_scales=None,
        cache_v_dequant_scales=None, qkv_out_scale=None, qkv_bias=None,
        out_shift=None, out_smooth=None, max_enc_len_this_time=None,
        max_dec_len_this_time=None, rope_emb=None, mask=None,
        tgt_mask=None, max_seq_len=-1, block_size=64,
        use_neox_style=False, use_dynamic_cachekv_quant=False,
        quant_round_type=1, quant_max_bound=127.0,
        quant_min_bound=-127.0, out_scale=-1,
        compute_dtype="default", rope_theta=10000.0):
    """Paged-KV fused attention for serving (reference:
    block_multihead_attention.py:33 / the CUDA kernel in
    phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu).

    Two phases, selected per call like the reference:
    - PREFILL (``seq_lens_encoder`` > 0): causal self-attention over
      each sequence's prompt tokens (varlen, ``cu_seqlens_q``) and the
      K/V written into the paged caches through ``block_tables``.
    - DECODE (``seq_lens_decoder`` > 0): one token per sequence,
      appended to its pages, attention over all cached tokens — the
      same math as ``ops.paged_attention`` (Pallas kernel on TPU).

    Mixed prefill+decode batches and the quantized-cache / pre-cache /
    smooth-quant arguments are gated (see ``_gate``). Cache layout
    matches the reference: [max_block_num, num_head, block_size,
    head_size]. Returns (out, qkv, key_cache, value_cache)."""
    _gate(dict(pre_key_cache=pre_key_cache,
               pre_value_cache=pre_value_cache,
               qkv_out_scale=qkv_out_scale, out_shift=out_shift,
               out_smooth=out_smooth, rope_emb=rope_emb))
    if use_dynamic_cachekv_quant and (
            cache_k_quant_scales is not None
            or cache_k_dequant_scales is not None):
        raise NotImplementedError(
            "dynamic cache-kv quantization: the TPU path supports the "
            "STATIC per-head scale mode (use_dynamic_cachekv_quant="
            "False)")
    qkv = _ensure(qkv)
    key_cache, value_cache = _ensure(key_cache), _ensure(value_cache)
    enc = np.asarray(_ensure(seq_lens_encoder)._value).reshape(-1)
    dec = np.asarray(_ensure(seq_lens_decoder)._value).reshape(-1)
    tables = _ensure(block_tables)
    decode_mode = bool((enc == 0).all())
    if not decode_mode and not (dec == 0).all():
        raise NotImplementedError(
            "mixed prefill+decode batches: split the batch (the "
            "reference dispatches separate kernels per phase too)")
    kd = cache_k_dequant_scales
    vd = cache_v_dequant_scales
    has_quant = kd is not None or vd is not None
    if has_quant and (kd is None or vd is None):
        raise ValueError("pass BOTH cache_k/v_dequant_scales")
    if (cache_k_quant_scales is not None
            or cache_v_quant_scales is not None) and not has_quant:
        # quant-side scales without dequant-side would silently run the
        # raw bf16 write path against int8 caches — garbage, not an A/B
        raise ValueError(
            "static int8 cache mode reads cache_k/v_DEQUANT_scales "
            "(the write side derives from the same per-head scales); "
            "pass them too")
    if has_quant and not decode_mode:
        raise NotImplementedError(
            "int8 cache in the prefill phase: quantize the pools after "
            "prefill (inference.generate_paged(cache_dtype='int8') "
            "shows the calibration point); the static-scale decode "
            "phase is supported here")
    args = (qkv, key_cache, value_cache, tables)
    if qkv_bias is not None:
        args = args + (_ensure(qkv_bias),)
    has_bias = qkv_bias is not None
    extra_mask = tgt_mask if decode_mode else mask
    if extra_mask is not None:
        args = args + (_ensure(extra_mask),)
    has_mask = extra_mask is not None
    if has_quant:
        args = args + (_ensure(kd), _ensure(vd))
    B = enc.shape[0]
    dec_lens = jnp.asarray(dec, jnp.int32)
    cu_q = np.asarray(_ensure(cu_seqlens_q)._value).reshape(-1)

    def f(qkv_v, kc, vc, bt, *rest):
        i = 0
        b = rest[i] if has_bias else None
        i += int(has_bias)
        am = rest[i] if has_mask else None
        i += int(has_mask)
        ksc = rest[i].reshape(-1) if has_quant else None
        vsc = rest[i + 1].reshape(-1) if has_quant else None
        NB, H, BS, D = kc.shape
        if b is not None:
            qkv_v = qkv_v + b.reshape(1, -1).astype(qkv_v.dtype)
        if decode_mode:
            # [B, 3, H, D] — one token per sequence
            pk = qkv_v.reshape(B, 3, H, D)
            q, kn, vn = pk[:, 0], pk[:, 1], pk[:, 2]
            # append at dec_lens: pools in our [N, BS, H, D] layout
            from ....ops.paged_attention import (
                paged_attention_decode, paged_attention_decode_quant,
                write_to_pool, write_to_pool_quant)
            kp = jnp.swapaxes(kc, 1, 2)        # [NB, BS, H, D]
            vp = jnp.swapaxes(vc, 1, 2)
            if ksc is not None:
                kp, vp = write_to_pool_quant(kp, vp, bt, dec_lens,
                                             kn, vn, ksc, vsc)
            else:
                kp, vp = write_to_pool(kp, vp, bt, dec_lens,
                                       kn.astype(kp.dtype),
                                       vn.astype(vp.dtype))
            if am is None and ksc is not None:
                o = paged_attention_decode_quant(
                    q, kp, vp, bt, dec_lens + 1, ksc, vsc)
            elif am is None:
                o = paged_attention_decode(q, kp, vp, bt, dec_lens + 1)
            else:
                # additive tgt_mask [B, 1, 1, S]: gather composition —
                # an arbitrary bias cannot ride the paged kernel
                MBb = bt.shape[1]
                S = MBb * BS
                kk = kp[bt].reshape(B, S, H, D).astype(jnp.float32)
                vv = vp[bt].reshape(B, S, H, D).astype(jnp.float32)
                if ksc is not None:   # int8 pools: per-head dequant
                    kk = kk * ksc[None, None, :, None]
                    vv = vv * vsc[None, None, :, None]
                s = jnp.einsum("bhd,bkhd->bhk", q.astype(jnp.float32),
                               kk) / np.sqrt(D)
                amb = am.astype(jnp.float32).reshape(B, 1, -1)
                amb = (jnp.pad(amb, ((0, 0), (0, 0),
                                     (0, max(0, S - amb.shape[-1]))))
                       [:, :, :S])
                s = s + amb
                live = jnp.arange(S)[None, :] <= dec_lens[:, None]
                s = jnp.where(live[:, None, :], s, -jnp.inf)
                p = jax.nn.softmax(s, axis=-1)
                o = jnp.einsum("bhk,bkhd->bhd", p, vv)
            out = o.reshape(B, H * D).astype(qkv_v.dtype)
            return (out, qkv_v, jnp.swapaxes(kp, 1, 2).astype(kc.dtype),
                    jnp.swapaxes(vp, 1, 2).astype(vc.dtype))
        # prefill: varlen causal attention token-major [T, 3, H, D]
        T = qkv_v.shape[0]
        pk = qkv_v.reshape(T, 3, H, D)
        q, k, v = pk[:, 0], pk[:, 1], pk[:, 2]
        # segment ids from cu_seqlens (static host values)
        seg = np.zeros((T,), np.int32)
        for i in range(B):
            seg[cu_q[i]:cu_q[i + 1]] = i
        seg = jnp.asarray(seg)
        qf = q.astype(jnp.float32)
        kf = k.astype(jnp.float32)
        s = jnp.einsum("thd,shd->hts", qf, kf) / np.sqrt(D)
        same = (seg[:, None] == seg[None, :])
        causal = (jnp.arange(T)[:, None] >= jnp.arange(T)[None, :])
        pos_q = jnp.arange(T) - jnp.asarray(cu_q[:-1])[seg]
        if am is not None:
            # additive mask [B, 1, S, S] gathered onto flat token pairs
            s = s + am.astype(jnp.float32)[seg[:, None], 0,
                                           pos_q[:, None],
                                           pos_q[None, :]][None]
        s = jnp.where((same & causal)[None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("hts,shd->thd", p, v.astype(jnp.float32))
        out = o.reshape(T, H * D).astype(qkv_v.dtype)
        # write prompt K/V into the pages: token t of sequence i lands
        # in page bt[i, pos // BS] at slot pos % BS
        page = bt[seg, pos_q // BS]                         # [T]
        slot = pos_q % BS
        kc = kc.at[page, :, slot].set(k.astype(kc.dtype))
        vc = vc.at[page, :, slot].set(v.astype(vc.dtype))
        return out, qkv_v, kc, vc

    return dispatch(f, args, name="block_multihead_attention",
                    multi_output=True)
