"""Incubate fused layers (reference: python/paddle/incubate/nn/layer/
fused_transformer.py + fused_dropout_add.py) — parameter-holding
wrappers over the fused functionals; on TPU the "fusion" is XLA's,
applied to the single composed program each functional builds."""
from __future__ import annotations

import numpy as np

from ...nn import Layer
from . import functional as F


class FusedLinear(Layer):
    """reference: FusedLinear — GEMM + bias epilogue."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        shape = [out_features, in_features] if transpose_weight \
            else [in_features, out_features]
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = self.create_parameter([out_features], attr=bias_attr,
                                          is_bias=True)
        self.transpose_weight = transpose_weight

    def forward(self, x):
        return F.fused_matmul_bias(x, self.weight, self.bias,
                                   transpose_y=self.transpose_weight)


class FusedDropoutAdd(Layer):
    """reference: FusedDropoutAdd — y + dropout(x)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return F.fused_dropout_add(x, y, p=self.p,
                                   training=self.training,
                                   mode=self.mode)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """reference: FusedBiasDropoutResidualLayerNorm."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.linear_bias = self.create_parameter([embed_dim],
                                                 attr=bias_attr,
                                                 is_bias=True)
        from ...nn import initializer as I
        self.ln_scale = self.create_parameter(
            [embed_dim], default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon

    def forward(self, x, residual):
        return F.fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self.dropout_rate,
            ln_epsilon=self.epsilon, training=self.training)


class FusedFeedForward(Layer):
    """reference: FusedFeedForward."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, name=None):
        super().__init__()
        from ...nn import initializer as I
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr)
        self.linear1_bias = self.create_parameter(
            [dim_feedforward], attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr)
        self.linear2_bias = self.create_parameter(
            [d_model], attr=linear2_bias_attr, is_bias=True)
        one = I.Constant(1.0)
        self.ln1_scale = self.create_parameter(
            [d_model], default_initializer=one)
        self.ln1_bias = self.create_parameter([d_model], is_bias=True)
        self.ln2_scale = self.create_parameter(
            [d_model], default_initializer=one)
        self.ln2_bias = self.create_parameter([d_model], is_bias=True)
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = act_dropout_rate \
            if act_dropout_rate is not None else dropout_rate
        self.activation = activation
        self.epsilon = epsilon
        self.normalize_before = normalize_before

    def forward(self, x):
        return F.fused_feedforward(
            x, self.linear1_weight, self.linear2_weight,
            linear1_bias=self.linear1_bias,
            linear2_bias=self.linear2_bias,
            ln1_scale=self.ln1_scale, ln1_bias=self.ln1_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            dropout1_rate=self.act_dropout_rate,
            dropout2_rate=self.dropout_rate,
            activation=self.activation, ln1_epsilon=self.epsilon,
            ln2_epsilon=self.epsilon,
            pre_layer_norm=self.normalize_before,
            training=self.training)


class FusedMultiHeadAttention(Layer):
    """reference: FusedMultiHeadAttention — packed-QKV MHA block."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        from ...nn import initializer as I
        if embed_dim % num_heads:
            raise ValueError("embed_dim must divide num_heads")
        hd = embed_dim // num_heads
        self.num_heads = num_heads
        self.qkv_weight = self.create_parameter(
            [3, num_heads, hd, embed_dim], attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter(
            [3, num_heads, hd], attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], attr=linear_weight_attr)
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=linear_bias_attr, is_bias=True)
        one = I.Constant(1.0)
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], default_initializer=one)
        self.pre_ln_bias = self.create_parameter([embed_dim],
                                                 is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], default_initializer=one)
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.normalize_before = normalize_before
        self.epsilon = epsilon

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        if (key is not None and key is not query) or \
                (value is not None and value is not query):
            raise NotImplementedError(
                "FusedMultiHeadAttention packs QKV from one input "
                "(self-attention); use nn.MultiHeadAttention for "
                "cross-attention")
        return F.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale,
            pre_ln_bias=self.pre_ln_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, qkv_bias=self.qkv_bias,
            linear_bias=self.linear_bias, cache_kv=cache,
            attn_mask=attn_mask,
            dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate,
            ln_epsilon=self.epsilon, training=self.training,
            num_heads=self.num_heads)


class FusedTransformerEncoderLayer(Layer):
    """reference: FusedTransformerEncoderLayer — fused MHA + fused FFN."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate
            if attn_dropout_rate is not None else dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedMultiTransformer(Layer):
    """reference: FusedMultiTransformer — N pre-LN decoder layers via
    the fused_multi_transformer functional."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu",
                 normalize_before=True, num_layers=1, nranks=1,
                 trans_qkvw=True, ring_id=-1, name=None, **kwargs):
        super().__init__()
        from ...nn import initializer as I
        if not normalize_before:
            raise NotImplementedError(
                "FusedMultiTransformer is pre-LN only (reference "
                "fused_transformer.py asserts normalize_before)")
        hd = embed_dim // num_heads
        one = I.Constant(1.0)
        self.num_layers = num_layers
        (self.ln_scales, self.ln_biases, self.qkv_weights,
         self.qkv_biases, self.linear_weights, self.linear_biases,
         self.ffn_ln_scales, self.ffn_ln_biases, self.ffn1_weights,
         self.ffn1_biases, self.ffn2_weights, self.ffn2_biases) = \
            ([] for _ in range(12))
        for i in range(num_layers):
            mk = self.create_parameter
            self.ln_scales.append(mk([embed_dim],
                                     default_initializer=one))
            self.ln_biases.append(mk([embed_dim], is_bias=True))
            self.qkv_weights.append(mk([3, num_heads, hd, embed_dim]))
            self.qkv_biases.append(mk([3, num_heads, hd], is_bias=True))
            self.linear_weights.append(mk([embed_dim, embed_dim]))
            self.linear_biases.append(mk([embed_dim], is_bias=True))
            self.ffn_ln_scales.append(mk([embed_dim],
                                         default_initializer=one))
            self.ffn_ln_biases.append(mk([embed_dim], is_bias=True))
            self.ffn1_weights.append(mk([embed_dim, dim_feedforward]))
            self.ffn1_biases.append(mk([dim_feedforward], is_bias=True))
            self.ffn2_weights.append(mk([dim_feedforward, embed_dim]))
            self.ffn2_biases.append(mk([embed_dim], is_bias=True))
            for j, lst in enumerate((
                    self.ln_scales, self.ln_biases, self.qkv_weights,
                    self.qkv_biases, self.linear_weights,
                    self.linear_biases, self.ffn_ln_scales,
                    self.ffn_ln_biases, self.ffn1_weights,
                    self.ffn1_biases, self.ffn2_weights,
                    self.ffn2_biases)):
                self.add_parameter(f"l{i}_p{j}", lst[i])
        self.dropout_rate = dropout_rate
        self.activation = activation

    def forward(self, src, attn_mask=None, caches=None, time_step=None,
                pre_caches=None, seq_lens=None, rotary_embs=None,
                rotary_emb_dims=0):
        return F.fused_multi_transformer(
            src, self.ln_scales, self.ln_biases, self.qkv_weights,
            self.qkv_biases, self.linear_weights, self.linear_biases,
            self.ffn_ln_scales, self.ffn_ln_biases, self.ffn1_weights,
            self.ffn1_biases, self.ffn2_weights, self.ffn2_biases,
            pre_layer_norm=True, attn_mask=attn_mask,
            cache_kvs=caches, pre_caches=pre_caches, seq_lens=seq_lens,
            rotary_embs=rotary_embs, rotary_emb_dims=rotary_emb_dims,
            time_step=time_step,
            dropout_rate=self.dropout_rate, activation=self.activation,
            training=self.training)


class FP8Linear(Layer):
    """Linear layer computing on the MXU in fp8 under delayed scaling
    (reference capability: paddle/phi/kernels/fusion/fp8_gemm/ driven by
    a transformer-engine-style amax-history recipe). The per-operand
    amax histories live as non-trainable buffers, updated on every
    forward, so they ride checkpoints with the rest of the state."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, format="e4m3", history_len=16,
                 margin=0.0, out_dtype="bfloat16", name=None):
        super().__init__()
        self.weight = self.create_parameter([in_features, out_features],
                                            attr=weight_attr)
        self.bias = self.create_parameter([out_features], attr=bias_attr,
                                          is_bias=True)
        self.format = format
        self.margin = margin
        self.out_dtype = out_dtype
        for nm in ("x_amax_history", "w_amax_history"):
            self.register_buffer(
                nm, F.fp8_delayed_state(history_len)["amax_history"])

    def forward(self, x):
        out, xs, ws = F.fp8_linear_delayed(
            x, self.weight, {"amax_history": self.x_amax_history},
            {"amax_history": self.w_amax_history}, bias=self.bias,
            format=self.format, out_dtype=self.out_dtype,
            margin=self.margin)
        # rolling histories update in place (buffers, not outputs)
        self.x_amax_history._replace_value(
            xs["amax_history"]._value)
        self.w_amax_history._replace_value(
            ws["amax_history"]._value)
        return out
