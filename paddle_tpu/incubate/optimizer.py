"""Incubate optimizer wrappers (reference:
python/paddle/incubate/optimizer/lookahead.py LookAhead,
python/paddle/incubate/optimizer/modelaverage.py ModelAverage)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import to_value


class LookAhead:
    """reference lookahead.py: wrap an inner optimizer; every k steps
    pull the fast weights toward slow weights:
    slow += alpha * (fast - slow); fast = slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if inner_optimizer is None:
            raise ValueError("inner optimizer can not be None")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha should be in [0, 1]")
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = int(k)
        self._steps = 0
        self._slow = {}     # id(param) -> slow weight value

    @property
    def _parameters(self):
        return self.inner_optimizer._parameter_list

    def step(self):
        self.inner_optimizer.step()
        self._steps += 1
        params = self.inner_optimizer._parameter_list
        if self._steps % self.k == 0:
            for p in params:
                pid = id(p)
                # copy: the param buffer is DONATED by fused optimizer
                # steps, so an alias held across steps would be deleted
                fast = to_value(p).astype(jnp.float32).copy()
                slow = self._slow.get(pid)
                if slow is None:
                    slow = fast
                slow = slow + self.alpha * (fast - slow)
                self._slow[pid] = slow
                # hand the param a SEPARATE buffer: astype to the same
                # dtype is a no-op alias, and the param buffer gets
                # donated by the next fused optimizer step
                p._replace_value(
                    jnp.asarray(slow, to_value(p).dtype).copy())

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        return {"inner": self.inner_optimizer.state_dict(),
                "steps": self._steps,
                "slow": {i: np.asarray(v)
                         for i, v in enumerate(self._slow.values())}}

    def minimize(self, loss):
        loss.backward()
        self.step()
        self.clear_grad()


class ModelAverage:
    """reference modelaverage.py: a TRUE running average (sum / count,
    not an EMA — an EMA from zero under-counts short runs); the window
    restarts once the accumulate count passes max_average_window, like
    the reference's old/num accumulator fold."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self.rate = average_window_rate
        self.min_w = min_average_window
        self.max_w = max_average_window
        self._params = list(parameters or [])
        self._sum = {id(p): jnp.zeros_like(to_value(p), jnp.float32)
                     for p in self._params}
        self._n = 0
        self._backup = {}

    def step(self):
        window = max(self.min_w, min(self.max_w,
                                     int((self._n + 1) * self.rate) or 1))
        if self._n >= window:
            # restart: keep the current average as one pseudo-sample
            for pid in self._sum:
                self._sum[pid] = self._sum[pid] / self._n
            self._n = 1
        self._n += 1
        for p in self._params:
            pid = id(p)
            self._sum[pid] = self._sum[pid] + \
                to_value(p).astype(jnp.float32).copy()

    def apply(self, executor=None, need_restore=True):
        class _Ctx:
            def __init__(ctx):
                pass

            def __enter__(ctx):
                self._backup = {id(p): p._value for p in self._params}
                n = max(self._n, 1)
                for p in self._params:
                    avg = self._sum[id(p)] / n
                    p._replace_value(
                        jnp.asarray(avg, p._value.dtype).copy())
                return ctx

            def __exit__(ctx, *exc):
                if need_restore:
                    self.restore()

        return _Ctx()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._replace_value(self._backup[id(p)])
        self._backup = {}

    def minimize(self, loss):
        raise RuntimeError(
            "ModelAverage wraps evaluation, not training; call step() "
            "after your optimizer's step()")
