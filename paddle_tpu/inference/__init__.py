"""paddle_tpu.inference — deploy path (reference:
paddle/fluid/inference/ AnalysisPredictor/AnalysisConfig + the
fused-transformer serving kernels). StableHLO artifacts + XLA AOT compile
replace the pass pipeline; paged attention + the jitted generate loop
replace the CUDA decode kernels."""
from .predictor import Config, Predictor, create_predictor
from .generation import (GenerationConfig, generate, cached_forward,
                         init_cache, sample_token)

__all__ = ["Config", "Predictor", "create_predictor", "GenerationConfig",
           "generate", "cached_forward", "init_cache", "sample_token"]
