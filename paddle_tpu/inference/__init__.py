"""paddle_tpu.inference — deploy path (reference:
paddle/fluid/inference/ AnalysisPredictor/AnalysisConfig + the
fused-transformer serving kernels). StableHLO artifacts + XLA AOT compile
replace the pass pipeline; paged attention + the jitted generate loop
replace the CUDA decode kernels."""
from .predictor import Config, Predictor, create_predictor
from .generation import (GenerationConfig, generate, generate_paged,
                         cached_forward, init_cache, sample_token)
from .serving import Request, ServingEngine
from .prefix_cache import PrefixCache, PagedKVCacheStore
from .tp import ServingMesh
from .admission import AdmissionQueue
from .disagg import DisaggregatedEngine
from .fleet import ServingFleet

__all__ = ["Config", "Predictor", "create_predictor", "GenerationConfig",
           "DataType", "PlaceType", "PrecisionType", "PredictorPool",
           "XpuConfig", "get_version", "get_num_bytes_of_data_type",
           "get_trt_compile_version", "get_trt_runtime_version",
           "convert_to_mixed_precision",
           "generate", "generate_paged", "cached_forward", "init_cache",
           "sample_token", "Request", "ServingEngine", "ServingMesh",
           "PrefixCache", "PagedKVCacheStore", "AdmissionQueue",
           "DisaggregatedEngine", "ServingFleet"]


class DataType:
    """reference: paddle_infer.DataType enum."""
    FLOAT32 = 0
    FLOAT16 = 1
    INT64 = 2
    INT32 = 3
    UINT8 = 4
    INT8 = 5
    BOOL = 6
    BFLOAT16 = 7
    FLOAT64 = 8


class PlaceType:
    """reference: paddle_infer.PlaceType enum (kXPU slot = the TPU)."""
    kUNK = -1
    kCPU = 0
    kGPU = 1
    kXPU = 2
    kNPU = 3
    kIPU = 4
    kCUSTOM = 5


class PrecisionType:
    """reference: AnalysisConfig::Precision."""
    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


class PredictorPool:
    """reference: paddle_infer.PredictorPool — N predictors sharing one
    config (the AOT executable cache dedupes compilation)."""

    def __init__(self, config, size=1):
        first = create_predictor(config)
        self._preds = [first] + [first.clone() for _ in range(size - 1)]

    def retrive(self, idx):   # reference spells it this way
        return self._preds[idx]

    retrieve = retrive


class XpuConfig:
    """reference: paddle_infer.XpuConfig — accelerator knob bag; on this
    framework XLA owns device configuration (knobs recorded only)."""

    def __init__(self, **kwargs):
        self.__dict__.update(kwargs)


def get_version():
    """reference: paddle_infer.get_version."""
    return "paddle_tpu-inference 3.0 (XLA AOT serving path)"


def get_num_bytes_of_data_type(dtype):
    sizes = {DataType.FLOAT32: 4, DataType.FLOAT16: 2, DataType.INT64: 8,
             DataType.INT32: 4, DataType.UINT8: 1, DataType.INT8: 1,
             DataType.BOOL: 1, DataType.BFLOAT16: 2, DataType.FLOAT64: 8}
    return sizes.get(dtype, 4)


def get_trt_compile_version():
    """reference: TensorRT probe — always (0,0,0): the XLA executable
    fills the TRT slot here."""
    return (0, 0, 0)


def get_trt_runtime_version():
    return (0, 0, 0)


def _get_phi_kernel_name(op_name):
    """reference: paddle.inference._get_phi_kernel_name — maps a legacy
    op name to its phi kernel; identity here (ops ARE jax fns)."""
    return op_name


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision=None,
                               backend=None, keep_io_types=True,
                               black_list=None, **kwargs):
    """reference: inference/convert_to_mixed_precision — offline pass
    rewriting a saved model to fp16/bf16. The XLA path applies AMP at
    compile time, so this copies the model and records the requested
    precision next to it."""
    import json
    import shutil
    for src, dst in ((model_file, mixed_model_file),
                     (params_file, mixed_params_file)):
        if src and dst and src != dst:
            shutil.copyfile(src, dst)
    with open(str(mixed_model_file) + ".precision.json", "w") as f:
        json.dump({"mixed_precision": str(mixed_precision),
                   "keep_io_types": keep_io_types,
                   "black_list": sorted(black_list or [])}, f)
