"""SLO-aware admission queue for the serving engines.

The PR-1 engine admitted FIFO: one deque, no overtaking. Real serving
traffic is not uniform — interactive requests ride next to batch
summarization, and a TTFT SLO on the former is only meetable if the
scheduler can (a) order admission by priority CLASS, (b) reject
requests whose admission deadline already passed instead of burning
prefill on them, and (c) preempt a low-priority decode slot when a
high-priority request would otherwise miss its deadline (the
disaggregated engine's decode group inherits exactly this queue).

Semantics (shared by ``ServingEngine`` and ``DisaggregatedEngine``):

- **priority classes** are small ints, LOWER = more urgent (0 is the
  most urgent class). Default class is 1 so callers can express both
  "more urgent than default" (0) and "batch" (2+) out of the box.
- **FIFO within a class**: entries carry a monotonically increasing
  submission sequence number; requeued (preempted) entries KEEP their
  original sequence number, so a victim re-enters the line where it
  originally stood instead of at the back.
- **deadline** (``deadline_s``, relative to submit) bounds QUEUE WAIT:
  an entry still queued past its deadline is expired — handed back to
  the engine for rejection accounting — rather than admitted late.
  Entries whose service already STARTED (a preempted decode slot being
  requeued) are never expired: the admission SLO was met; abandoning
  half-generated output would waste the work already done.
- **starvation-freedom** via aging: an entry's EFFECTIVE class drops
  by one for every ``aging_s`` seconds it has waited, so under
  sustained high-priority load the oldest low-class entry eventually
  reaches class 0 and — FIFO within class, earliest sequence first —
  must be the next admission. ``aging_s=None`` disables aging (strict
  priority).

The queue is a plain list with an O(n) best-entry scan: effective
priority is time-dependent, so a static heap would need rebuilding per
pop anyway, and serving queues are tens of entries — determinism and
testability outrank asymptotics here. A ``clock`` callable is injected
for tests.
"""
from __future__ import annotations

import time
from typing import Callable, List, Optional

__all__ = ["AdmissionQueue", "QueueEntry"]


class QueueEntry:
    """One queued request plus its scheduling metadata."""

    __slots__ = ("item", "cls", "seq", "submit_t", "deadline_s",
                 "requeues", "started")

    def __init__(self, item, cls: int, seq: int, submit_t: float,
                 deadline_s: Optional[float], started: bool = False):
        self.item = item
        self.cls = int(cls)
        self.seq = int(seq)
        self.submit_t = float(submit_t)
        self.deadline_s = deadline_s
        self.requeues = 0          # times this entry was put back
        self.started = started     # service began (preempted resume)

    def expired(self, now: float) -> bool:
        """Queued past the admission deadline (started entries never
        expire — their admission SLO was already met)."""
        return (not self.started and self.deadline_s is not None
                and (now - self.submit_t) > self.deadline_s)


class AdmissionQueue:
    """Priority + deadline + aging admission queue (module docstring)."""

    def __init__(self, aging_s: Optional[float] = None,
                 clock: Callable[[], float] = time.perf_counter):
        if aging_s is not None and aging_s <= 0:
            raise ValueError("aging_s must be positive (or None)")
        self.aging_s = aging_s
        self.clock = clock
        self._entries: List[QueueEntry] = []
        self._next_seq = 0

    # -- mutation -----------------------------------------------------
    def push(self, item, cls: int = 1, submit_t: Optional[float] = None,
             deadline_s: Optional[float] = None,
             seq: Optional[int] = None,
             started: bool = False) -> QueueEntry:
        """Enqueue ``item``. ``seq`` lets a requeue keep the original
        line position; fresh pushes take the next sequence number."""
        if seq is None:
            seq = self._next_seq
            self._next_seq += 1
        e = QueueEntry(item, cls, seq,
                       self.clock() if submit_t is None else submit_t,
                       deadline_s, started=started)
        self._entries.append(e)
        return e

    def requeue(self, entry: QueueEntry) -> QueueEntry:
        """Put a previously popped entry back, keeping its class,
        sequence number and submit time (preemption path: the victim
        re-enters the line where it originally stood)."""
        entry.requeues += 1
        entry.started = True
        self._entries.append(entry)
        return entry

    def remove(self, entry: QueueEntry):
        self._entries.remove(entry)

    # -- ordering -----------------------------------------------------
    def effective_class(self, entry: QueueEntry,
                        now: Optional[float] = None) -> int:
        """Class after aging: one promotion per ``aging_s`` waited,
        floored at 0 (class can only improve with waiting)."""
        if self.aging_s is None:
            return entry.cls
        now = self.clock() if now is None else now
        boost = int(max(0.0, now - entry.submit_t) / self.aging_s)
        return max(0, entry.cls - boost)

    def _key(self, entry: QueueEntry, now: float):
        return (self.effective_class(entry, now), entry.seq)

    def best(self, now: Optional[float] = None,
             pred=None) -> Optional[QueueEntry]:
        """The entry next in line: minimum (effective class, seq),
        optionally restricted to entries matching ``pred``."""
        entries = (self._entries if pred is None
                   else [e for e in self._entries if pred(e)])
        if not entries:
            return None
        now = self.clock() if now is None else now
        return min(entries, key=lambda e: self._key(e, now))

    def pop(self, now: Optional[float] = None) -> Optional[QueueEntry]:
        e = self.best(now)
        if e is not None:
            self._entries.remove(e)
        return e

    def pop_expired(self, now: Optional[float] = None
                    ) -> List[QueueEntry]:
        """Remove and return every entry whose admission deadline has
        passed (rejection accounting belongs to the caller)."""
        now = self.clock() if now is None else now
        dead = [e for e in self._entries if e.expired(now)]
        for e in dead:
            self._entries.remove(e)
        return dead

    # -- introspection ------------------------------------------------
    def snapshot(self, limit: int = 16,
                 now: Optional[float] = None) -> List[dict]:
        """Line order (up to ``limit``) for stall dumps."""
        now = self.clock() if now is None else now
        ordered = sorted(self._entries, key=lambda e: self._key(e, now))
        return [{"cls": e.cls,
                 "effective_cls": self.effective_class(e, now),
                 "seq": e.seq, "requeues": e.requeues,
                 "started": e.started,
                 "waited_s": round(now - e.submit_t, 6)}
                for e in ordered[:limit]]

    def __len__(self) -> int:
        return len(self._entries)

    def __bool__(self) -> bool:
        return bool(self._entries)

    def __iter__(self):
        return iter(sorted(self._entries,
                           key=lambda e: self._key(e, self.clock())))
