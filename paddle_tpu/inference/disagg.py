"""Disaggregated prefill/decode serving: split chip groups with
KV-page handoff.

PR 9 sharded the serving programs over a mesh, but prefill chunks and
decode steps still interleave on the SAME chips: every ``step()`` runs
one prefill chunk ahead of the decode dispatch, so one long prompt
stalls every in-flight decode slot behind a multi-hundred-ms chunk —
the classic TPOT-spike failure mode (the per-step sync point makes the
contention visible as inflated ``decode_step_ms``). Disaggregated
serving removes it structurally, the way the paper's reference stack
separates scheduling from execution (fleet executor / predictor split)
and ClusterFusion++ (PAPERS.md) keeps the decode chips on their fused
hot loop uninterrupted:

- a **prefill group** and a **decode group** — disjoint device sets,
  each a :class:`~paddle_tpu.inference.tp.ServingMesh` (tp >= 1) —
  each run their OWN compiled programs over their OWN paged KV pools.
  The prefill group runs only bucketed chunked prefill (plus int8
  calibration and the radix prefix cache); the decode group runs only
  the single jitted decode-step program.
- a finished prefill hands its KV pages to the decode group through a
  jitted **page-handoff** pair: ``extract`` gathers the request's
  pages from the prefill pools into a fixed-width page block (padded
  page indices read the scratch page, so ONE trace covers every
  request size), ``jax.device_put`` moves the block onto the decode
  group's sharding (device-to-device copy over ICI/DCN on real
  multi-chip; the same code path runs on forced-host CPU devices in
  tier-1), and ``insert`` scatters it into the decode pools — donated,
  so the decode pools update in place. **Page-table translation is
  host-side**: each group's ``BlockManager`` owns its own physical
  page numbering, the handoff allocates decode-side pages and writes
  the translated table, and the prefill side releases its pages (the
  radix prefix cache keeps its refcounted copies, so warm admissions
  keep working on the prefill side).
- the handoff is **async and double-buffered** (r16): a transfer's
  extract + device_put are ISSUED in one orchestrator step and its
  donated insert lands at the top of the NEXT step, so the
  device-to-device copy overlaps the prefill chunk and decode step
  dispatched in between instead of serializing ahead of them (at most
  two transfers in flight). The request's resume entry is pushed only
  when its final insert lands, so the decode group never sees
  half-arrived pages and the bit-parity contract is untouched.
- **chunked-prefill handoff** (r16): a multi-chunk prompt streams each
  completed chunk's full pages to the decode group while later chunks
  still run (same extract/put/insert programs, offset page windows),
  so a long prompt's bulk transfer stops serializing behind its last
  chunk in the handoff queue. Chunk boundaries rewrite already-filled
  positions with identical bytes (the gather/forward/scatter round
  trip is idempotent for untouched positions), so partial pages are
  final the moment their chunk completes. Opportunistic: partials ship
  only when the decode pool can already admit the whole request; a
  request that finishes ON the prefill group (EOS at first token)
  after shipping partials queues an abort marker that releases its
  decode-side pages after any in-flight inserts land.
- **SLO-aware admission** (inference/admission.py) is shared with the
  colocated engine: priority classes + per-request deadlines on
  ``submit()``, a priority queue with aging replacing FIFO, and
  preemption/requeue of decode slots under pressure — a victim keeps
  its KV pages and its decode carry, so the resumed decode stream is
  bit-identical to the un-preempted run.

Greedy parity: the prefill group runs the exact prefill math of the
colocated engine and the decode group the exact decode math; the
handoff moves raw page bytes. With single-device groups (or the
``"gather"`` collective placement) greedy output is therefore
BIT-identical to the colocated ``ServingEngine`` — asserted in tier-1
over mixed-arrival streams including the prefix-cache warm path and
int8 pools. Steady state is zero retraces per group: 1 decode program,
<=1 prefill program per bucket, plus the two handoff programs traced
once each.

Observability: both workers share the DisaggregatedEngine's timeline
ring and request-record log, handoff latency/bytes feed a bound flight
recorder (``kv_handoff@xfer``) plus the ``handoff_ms`` histogram, and
``metrics()`` composes the scheduler report (per-class queue wait, SLO
attainment, preemptions) with both groups' full engine metrics.
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..observability import (Observability, TelemetryConfig,
                             TelemetryPlane)
from .generation import GenerationConfig
from .serving import (Request, ServingEngine, _collectives_snapshot,
                      _drain_loop)
from .tp import ServingMesh, normalize_mesh

__all__ = ["DisaggregatedEngine"]

# the engine-level latency set: request-level distributions (fed by
# whichever worker finishes/admits the request — the histogram objects
# are SHARED with both workers' registries) plus what only the
# orchestrator can time (handoff, whole-engine step)
DISAGG_HISTOGRAMS = ("ttft_ms", "tpot_ms", "queue_wait_ms", "e2e_ms",
                     "handoff_ms", "step_ms")
# the sub-set shared by reference with the workers' registries
_SHARED_HISTOGRAMS = ("ttft_ms", "tpot_ms", "queue_wait_ms", "e2e_ms")


class _HandoffJob:
    """One queued transfer: a slice of a request's prefill-side pages
    (``src_pages[offset:]``) bound for the decode group. ``final``
    carries the resume entry; ``abort`` releases the decode-side
    allocation of a request that finished on the prefill group after
    shipping partials."""

    __slots__ = ("req", "src_pages", "offset", "final", "abort")

    def __init__(self, req: Request, src_pages: List[int], offset: int,
                 final: bool, abort: bool = False):
        self.req = req
        self.src_pages = src_pages
        self.offset = int(offset)
        self.final = final
        self.abort = abort


class _PrefillWorker(ServingEngine):
    """The prefill-group half: a ServingEngine that allocates KV pages
    for the PROMPT only and, instead of transitioning a completed
    prefill into a decode slot, vacates the slot (pages stay attached)
    and hands the request to the DisaggregatedEngine's handoff queue.
    Mid-prompt chunks report through ``on_chunk`` (the chunked-prefill
    handoff). Requests that finish during prefill (EOS first token,
    single-token budget) complete here and never touch the decode
    group."""

    def __init__(self, *args, on_complete=None, on_chunk=None, **kw):
        self._on_complete_cb = on_complete
        self._on_chunk_cb = on_chunk
        super().__init__(*args, **kw)

    def _alloc_tokens(self, req: Request) -> int:
        return int(req.prompt.size)     # generation lives elsewhere

    def _on_prefill_chunk(self, slot_id: int):
        if self._on_chunk_cb is not None:
            slot = self._slots[slot_id]
            self._on_chunk_cb(
                slot.req,
                list(self.mgr.tables.get(slot.req.req_id, ())),
                slot.prefill_pos)

    def _on_prefill_complete(self, slot_id: int, first: int):
        slot = self._slots[slot_id]
        req = slot.req
        if (first == req.gen.eos_token_id
                or req.gen.max_new_tokens <= 1):
            self._finish(slot_id)       # done entirely on this group
            self._on_complete_cb(req, None)
            return
        pages = list(self.mgr.tables.get(req.req_id, ()))
        # vacate the slot but KEEP the pages attached — the handoff
        # owns their transfer to the decode group and their release
        self._clear_slot(slot_id)
        self._on_complete_cb(req, pages)


class DisaggregatedEngine:
    """Prefill/decode-disaggregated serving over two chip groups.

    Construction (one of):

    - ``prefill_devices=[...], decode_devices=[...]`` — explicit
      device lists (each becomes a tp=len(list) ServingMesh);
    - ``mesh=ServingMesh(...)`` (or a 1-D jax Mesh, or an int device
      count) + ``prefill_tp=k`` — the mesh's devices split into the
      first ``k`` (prefill) and the rest (decode);
    - neither — all visible devices split at ``prefill_tp``. A
      single-device environment falls back to both groups sharing the
      one device (programs and handoff identical in structure; only
      the physical overlap differs), so audits and catalogs build the
      same program set everywhere.

    ``submit()/step()/drain()/metrics()`` mirror the colocated
    :class:`ServingEngine` contract; ``priority``/``deadline_s`` ride
    per request (inference/admission.py semantics).
    """

    def __init__(self, params, cfg, prefill_devices=None,
                 decode_devices=None, mesh=None, prefill_tp: int = 1,
                 collective: str = "psum",
                 capacity: int = 4, prefill_slots: int = 2,
                 block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 prefill_num_blocks: Optional[int] = None,
                 max_seq_len: Optional[int] = None, cache_dtype=None,
                 prefill_buckets=(32, 128), seed: int = 0,
                 prefix_cache: bool = False, kv_offload=False,
                 observability=False,
                 fused_decode=None, fused_prefill=None,
                 weight_quant=None,
                 aging_s: Optional[float] = None, telemetry=False,
                 clock=None):
        # injectable scheduler clock, threaded through BOTH group
        # workers (serving.py's seam): one fake clock drives every
        # submit_t/deadline/aging decision deterministically for tests
        # and the lifecycle model checker. None = wall clock.
        self._clock = clock if clock is not None else time.perf_counter
        pre_mesh, dec_mesh = self._resolve_groups(
            prefill_devices, decode_devices, mesh, prefill_tp,
            collective)
        # weight quantization: quantize ONCE here so both group
        # workers share the same tree (byte-identical scales on both
        # sides — the handoff's bit-parity contract needs the decode
        # group to continue exactly the prefill group's math); the
        # workers then adopt the carried mode
        from ..quantization.ptq import ensure_quantized
        params, self._weight_quant = ensure_quantized(params,
                                                      weight_quant)
        self.cfg = cfg
        self.counters = {
            "handoffs": 0, "partial_handoffs": 0, "handoff_traces": 0,
            "kv_bytes_transferred": 0, "requests_submitted": 0,
            "drain_truncations": 0,
        }
        # telemetry implies observability (alerts land timeline events
        # and stall dumps, both owned by the harness)
        _tcfg = TelemetryConfig.coerce(telemetry)
        if observability or _tcfg is not None:
            self._obs = (observability
                         if isinstance(observability, Observability)
                         else Observability(histograms=DISAGG_HISTOGRAMS))
            self._obs.registry.adopt_counters(self.counters)
            pre_obs: object = Observability()
            dec_obs: object = Observability()
        else:
            self._obs = None
            pre_obs = dec_obs = False
        self._flight = None
        if self._obs is not None:
            from ..distributed.flight_recorder import FlightRecorder
            rec = FlightRecorder(capacity=4096)
            rec.enabled = True
            self._flight = self._obs.bind_flight_recorder(rec)

        BS = int(block_size)
        msl = int(max_seq_len or cfg.max_position_embeddings)
        if prefill_num_blocks is None:
            # prompt pages for every prefill slot PLUS slack for pages
            # parked in the handoff queue while the decode pool pushes
            # back (vacated prefill slots keep refilling)
            prefill_num_blocks = \
                (int(prefill_slots) + int(capacity)) * (-(-msl // BS)) + 1
        self.prefill = _PrefillWorker(
            params, cfg, capacity=prefill_slots, block_size=BS,
            num_blocks=prefill_num_blocks, max_seq_len=msl,
            cache_dtype=cache_dtype, prefill_buckets=prefill_buckets,
            seed=seed, prefix_cache=prefix_cache, kv_offload=kv_offload,
            observability=pre_obs,
            fused_decode=False, fused_prefill=fused_prefill,
            mesh=pre_mesh, aging_s=aging_s, clock=clock,
            on_complete=self._on_prefilled,
            on_chunk=self._on_prefill_chunk)
        self.decode = ServingEngine(
            params, cfg, capacity=capacity, block_size=BS,
            num_blocks=num_blocks, max_seq_len=msl,
            cache_dtype=cache_dtype, prefill_buckets=prefill_buckets,
            seed=seed + 1, prefix_cache=False, observability=dec_obs,
            fused_decode=fused_decode, fused_prefill=fused_prefill,
            mesh=dec_mesh, aging_s=aging_s, clock=clock)
        if self._obs is not None:
            # one timeline ring + one request-record log for the whole
            # engine: both workers' events (submit/admit/prefill_chunk/
            # first_token/decode_step/preempt/resume/finish) interleave
            # with the orchestrator's handoff events, so one JSONL
            # export describes the full request lifecycle
            self.prefill._obs.timeline = self._obs.timeline
            self.decode._obs.timeline = self._obs.timeline
            self.prefill._obs.request_records = self._obs.request_records
            self.decode._obs.request_records = self._obs.request_records
            self._share_histograms()
        # continuous telemetry plane (r22): the orchestrator rollup
        # plus each group's engine under a `group` label, so a decode-
        # side regression is attributable without un-merging the rollup
        self._telemetry = None
        if _tcfg is not None:
            self._telemetry = TelemetryPlane(
                _tcfg, on_alert=self._telemetry_alert)
            self._telemetry.register("disagg_engine", self.metrics,
                                     counters=self.counters,
                                     skip=("groups",))
            self._telemetry.register(
                "disagg_group", self.prefill.metrics,
                labels={"group": "prefill"},
                counters=self.prefill.counters, skip=("groups",))
            self._telemetry.register(
                "disagg_group", self.decode.metrics,
                labels={"group": "decode"},
                counters=self.decode.counters, skip=("groups",))

        self.block_size = BS
        self.max_seq_len = msl
        self.capacity = int(capacity)
        self.prefill_slots = int(prefill_slots)
        self._quant = self.decode._quant
        # fixed handoff width = the largest prompt's page count; padded
        # entries index scratch page 0 on both sides, so ONE trace of
        # each handoff program covers every request size
        self._xfer_w = -(-msl // BS)
        self._extract_fn = None
        self._insert_fn = None
        self._handoffs: Deque[_HandoffJob] = deque()
        # started transfers whose donated insert lands at the top of
        # the NEXT step (async double-buffering: <= 2 in flight)
        self._inflight: Deque[Dict] = deque()
        self._partial_sent: Dict[int, int] = {}   # req_id -> pages sent
        self._requests: List[Request] = []
        self._hand_stats = [0, 0.0, 0.0]    # count, sum_ms, max_ms
        self._t_first = self._t_last = None
        self._metrics_reset_t = None
        self.last_drain_truncated = False

    # -- group resolution ---------------------------------------------
    @staticmethod
    def _resolve_groups(prefill_devices, decode_devices, mesh,
                        prefill_tp, collective):
        if prefill_devices is not None or decode_devices is not None:
            if not prefill_devices or not decode_devices:
                raise ValueError(
                    "explicit groups need BOTH prefill_devices and "
                    "decode_devices non-empty")
            mk = lambda d: ServingMesh.make(          # noqa: E731
                tp=len(d), collective=collective, devices=list(d))
            return mk(prefill_devices), mk(decode_devices)
        if isinstance(mesh, int):
            mesh = ServingMesh.make(tp=mesh, collective=collective)
        sm = normalize_mesh(mesh)
        if sm is None:
            devs = jax.devices()
            if len(devs) < 2:
                # single-device fallback: both groups share the one
                # device — program structure and the handoff path are
                # identical, so audits/catalogs build everywhere
                one = ServingMesh.make(tp=1, collective=collective,
                                       devices=devs)
                return one, one
            sm = ServingMesh.make(tp=len(devs), collective=collective,
                                  devices=devs)
        return sm.split(prefill_tp)

    # -- public API ---------------------------------------------------
    def submit(self, prompt, gen: Optional[GenerationConfig] = None,
               priority: Optional[int] = None,
               deadline_s: Optional[float] = None) -> Request:
        """Enqueue one request on the prefill group (the decode group
        admits it via KV handoff once its prompt is prefilled)."""
        gen = gen or GenerationConfig()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size >= 1:
            total = int(prompt.size) + int(gen.max_new_tokens)
            need = -(-total // self.decode.block_size)
            if need > self.decode.num_blocks - 1:
                raise ValueError(
                    f"request needs {need} KV pages but the DECODE "
                    f"group's pool only has {self.decode.num_blocks - 1}"
                    "; raise num_blocks")
        req = self.prefill.submit(prompt, gen, priority=priority,
                                  deadline_s=deadline_s)
        self._requests.append(req)
        self.counters["requests_submitted"] += 1
        return req

    def step(self) -> bool:
        """One orchestrator iteration: drain ready handoffs into the
        decode group, then one prefill-group step (admission + one
        chunk) and one decode-group step (resume admission + one decode
        step over all live slots) — the two groups' device work streams
        run concurrently, which is the whole point."""
        obs = self._obs
        t0 = self._clock() if obs is not None else 0.0
        if self._t_first is None:
            self._t_first = self._clock()
        did = self._run_handoffs()
        did = self.prefill.step() or did
        did = self.decode.step() or did
        if did:
            self._t_last = self._clock()
            if obs is not None:
                obs.hist("step_ms").observe(
                    (self._clock() - t0) * 1e3)
        if self._telemetry is not None:
            self._telemetry.on_step()
        return did

    @property
    def idle(self) -> bool:
        return (not self._handoffs and not self._inflight
                and self.prefill.idle and self.decode.idle)

    # -- fleet-router surface (inference/fleet.py) --------------------
    @property
    def queue_depth(self) -> int:
        """Un-admitted work anywhere in the engine: both groups'
        admission queues plus handoffs queued or in flight."""
        return (len(self.prefill._queue) + len(self.decode._queue)
                + len(self._handoffs) + len(self._inflight))

    @property
    def live_slots(self) -> int:
        return self.prefill.live_slots + self.decode.live_slots

    @property
    def prefix_cache_version(self) -> int:
        return self.prefill.prefix_cache_version

    def prefix_summary(self):
        """The radix tree lives on the prefill group (where admission
        happens) — its summary IS this engine's warm-state summary."""
        return self.prefill.prefix_summary()

    def offload_metrics(self) -> Dict:
        return self.prefill.offload_metrics()

    def drain(self, max_steps: Optional[int] = None) -> int:
        """Step until both groups and the handoff queue are empty
        (the shared :func:`_drain_loop` semantics: capped drains record
        truncation; starvation raises after a stall dump)."""
        return _drain_loop(
            self, max_steps,
            starve_reason="disaggregated drain starved: pending work "
                          "cannot progress",
            starve_error="disaggregated engine starved: pending "
                         "requests cannot be admitted or handed off "
                         "(KV pools too small for the in-flight mix?)")

    def _drain_truncated_event(self, n: int):
        if self._obs is not None:
            self._obs.timeline.record(
                "drain_truncated", steps=n,
                handoff_queue_depth=(len(self._handoffs)
                                     + len(self._inflight)))

    # -- handoff ------------------------------------------------------
    def _need_pages(self, req: Request) -> int:
        return -(-(int(req.prompt.size) + int(req.gen.max_new_tokens))
                 // self.decode.block_size)

    def _on_prefill_chunk(self, req: Request, pages: List[int],
                          pos: int):
        """Chunked-prefill handoff: a mid-prompt chunk completed —
        queue the prompt pages it finished (every position < ``pos``
        is final; later chunks rewrite them with identical bytes) as a
        partial transfer. Opportunistic: skipped unless the decode
        pool can already admit the WHOLE request, so a partial can
        never strand a half-transferred prompt against backpressure."""
        dec = self.decode
        done = pos // self.block_size
        sent = self._partial_sent.get(req.req_id, 0)
        if done <= sent:
            return
        if req.req_id not in dec.mgr.tables:
            if len(dec.mgr.free) < self._need_pages(req):
                return
            dec.mgr.allocate(req.req_id, int(req.prompt.size)
                             + int(req.gen.max_new_tokens))
        self._partial_sent[req.req_id] = done
        self._handoffs.append(
            _HandoffJob(req, pages[:done], sent, final=False))

    def _on_prefilled(self, req: Request, pages: Optional[List[int]]):
        sent = self._partial_sent.pop(req.req_id, 0)
        if pages is None:
            # finished on the prefill group. If partials already went
            # across, an abort marker releases the decode-side pages —
            # queued BEHIND them so it lands after their inserts.
            if req.req_id in self.decode.mgr.tables:
                self._handoffs.append(
                    _HandoffJob(req, [], sent, final=False, abort=True))
            return
        self._handoffs.append(_HandoffJob(req, pages, sent, final=True))

    def _next_startable_job(self) -> Optional[int]:
        """Index of the next job the transfer engine may start, or
        None. FIFO, except that a job which allocates NOTHING (abort,
        partial, or a final whose decode table already exists from its
        partials) may overtake a page-blocked head: its pages are
        already held, and completing it is the only way those pages
        ever free — the _admit resume-overtake idiom, without which a
        page-blocked short final ahead of a partial-allocated long
        final deadlocks the engine. An allocating final never
        overtakes (page fairness)."""
        dec = self.decode
        for i, job in enumerate(self._handoffs):
            needs_alloc = (job.final and not job.abort
                           and job.req.req_id not in dec.mgr.tables)
            if not needs_alloc:
                return i
            if i == 0 and (len(dec.mgr.free)
                           >= self._need_pages(job.req)):
                return i
            # page-blocked (or non-head) allocating final: waits
        return None

    def _run_handoffs(self) -> bool:
        """Land the inserts of transfers issued LAST step, then issue
        new ones (double-buffered: at most two in flight). The gap
        between issue and landing is where the device-to-device copy
        overlaps this step's prefill chunk and decode dispatch."""
        did = False
        while self._inflight:
            self._complete_transfer(self._inflight.popleft())
            did = True
        while self._handoffs and len(self._inflight) < 2:
            idx = self._next_startable_job()
            if idx is None:
                break       # decode-pool backpressure: finish frees
            job = self._handoffs[idx]
            del self._handoffs[idx]
            if job.abort:
                self._inflight.append({"job": job})
            else:
                self._inflight.append(self._start_transfer(job))
            did = True
        return did

    def _build_handoff_fns(self):
        """The jitted page-handoff pair. ``extract`` gathers a fixed-
        width block of pages from the prefill pools; ``insert``
        scatters it into the decode pools (donated — the pools update
        in place). Padded index entries point at scratch page 0 on
        both sides: the extra reads copy scratch bytes, the extra
        writes land in a page no live sequence ever reads — so one
        trace each covers every request size (the slot-table padding
        idiom)."""
        counters = self.counters

        def extract(kp, vp, idx):
            counters["handoff_traces"] += 1
            return (jnp.take(kp, idx, axis=1),
                    jnp.take(vp, idx, axis=1))

        def insert(kp, vp, idx, kpag, vpag):
            counters["handoff_traces"] += 1
            return (kp.at[:, idx].set(kpag), vp.at[:, idx].set(vpag))

        return (jax.jit(extract),
                jax.jit(insert, donate_argnums=(0, 1)))

    def _sync_scales(self):
        """Copy the prefill group's one-shot int8 calibration onto the
        decode group (before its decode program first traces, so the
        program closes over the final scale arrays) — the engine-global
        static-scale contract, now spanning two pools."""
        dm = self.decode._mesh
        self.decode._kv_scales = tuple(
            dm.shard(jnp.asarray(np.asarray(s)), dm.scale_spec)
            for s in self.prefill._kv_scales)

    def _start_transfer(self, job: _HandoffJob) -> Dict:
        """Issue one transfer's extract -> device_put (the insert lands
        next step): host-side page-table translation first (decode-side
        allocation, reused across a request's partial windows), then
        the jitted gather off the prefill pools and the async
        device-to-device copy onto the decode group's sharding. A FINAL
        job releases the request's prefill-side pages here — the
        extract already captured their bytes (functional arrays), and
        the radix tree's refcounted shares survive (warm prefix matches
        keep hitting on this group)."""
        pre, dec = self.prefill, self.decode
        req = job.req
        if self._extract_fn is None:
            self._extract_fn, self._insert_fn = self._build_handoff_fns()
        if self._quant and dec._kv_scales is None:
            self._sync_scales()
        t0 = self._clock()
        total = int(req.prompt.size) + int(req.gen.max_new_tokens)
        # decode-side allocation IS the page-table translation: the
        # request's table on this group is a fresh set of physical
        # pages; the first len(src_pages) receive the prompt's KV, the
        # rest are decode headroom. Partial windows extend one table.
        dst_table = dec.mgr.allocate(req.req_id, total)
        src = job.src_pages[job.offset:]
        n = len(src)
        W = self._xfer_w
        src_idx = np.zeros((W,), np.int32)
        dst_idx = np.zeros((W,), np.int32)
        src_idx[:n] = src
        dst_idx[:n] = dst_table[job.offset:job.offset + n]
        cfgv = self.cfg
        L, KV, hd = (cfgv.num_hidden_layers,
                     cfgv.num_key_value_heads, cfgv.head_dim)
        BS = self.block_size
        itemsize = jnp.dtype(pre._k_pools.dtype).itemsize
        nbytes = 2 * L * n * BS * KV * hd * itemsize
        task = None
        if self._flight is not None:
            task = self._flight.begin(
                "kv_handoff", "xfer", (2 * L, n * BS, KV * hd),
                str(jnp.dtype(pre._k_pools.dtype)))
        kpag, vpag = self._extract_fn(pre._k_pools, pre._v_pools,
                                      pre._mesh.replicate(src_idx))
        t1 = self._clock()
        sh = dec._mesh.sharding(dec._mesh.pool_spec)
        kpag = jax.device_put(kpag, sh)
        vpag = jax.device_put(vpag, sh)
        t2 = self._clock()
        if job.final:
            pre.mgr.release(req.req_id)
        return {"job": job, "kpag": kpag, "vpag": vpag,
                "dst_idx": dst_idx, "pages": n, "nbytes": nbytes,
                "task": task, "t0": t0, "t1": t1, "t2": t2}

    def _complete_transfer(self, st: Dict):
        """Land one transfer: the donated insert into the decode pools,
        then (final jobs only) the resume entry into the decode group's
        admission queue — pushed strictly after the insert, so the
        decode group never admits onto half-arrived pages. Abort
        markers release the decode-side allocation instead (their
        request finished on the prefill group)."""
        job = st["job"]
        req = job.req
        dec = self.decode
        if job.abort:
            dec.mgr.release(req.req_id)
            if self._obs is not None:
                self._obs.timeline.record("handoff_abort", req.req_id)
            return
        dec._k_pools, dec._v_pools = self._insert_fn(
            dec._k_pools, dec._v_pools,
            dec._mesh.replicate(st["dst_idx"]), st["kpag"], st["vpag"])
        t3 = self._clock()
        if st["task"] is not None:
            self._flight.end(st["task"])
        self.counters["kv_bytes_transferred"] += st["nbytes"]
        dur_ms = (t3 - st["t0"]) * 1e3
        phase_ms = {
            "extract_ms": round((st["t1"] - st["t0"]) * 1e3, 3),
            "put_ms": round((st["t2"] - st["t1"]) * 1e3, 3),
            "insert_ms": round((t3 - st["t2"]) * 1e3, 3),
        }
        if not job.final:
            self.counters["partial_handoffs"] += 1
            if self._obs is not None:
                self._obs.timeline.record(
                    "handoff_partial", req.req_id, dur_ms=dur_ms,
                    pages=st["pages"], bytes=st["nbytes"], **phase_ms)
            return
        # resume entry for the decode group: carry = (prompt length,
        # first sampled token) — exactly the colocated engine's
        # decode-entry state, so generation continues bit-identically.
        # started=True: the admission SLO was met at prefill admission
        req.resume = (int(req.prompt.size), int(req.tokens[-1]))
        req.qentry = dec._queue.push(req, cls=req.priority,
                                     submit_t=req.submit_t,
                                     started=True)
        self.counters["handoffs"] += 1
        hs = self._hand_stats
        hs[0] += 1
        hs[1] += dur_ms
        hs[2] = max(hs[2], dur_ms)
        if self._obs is not None:
            self._obs.hist("handoff_ms").observe(dur_ms)
            self._obs.timeline.record(
                "handoff", req.req_id, dur_ms=dur_ms,
                pages=st["pages"], bytes=st["nbytes"], **phase_ms)

    # -- reporting ----------------------------------------------------
    def scheduler_snapshot(self) -> Dict:
        return {"handoff_queue_depth": (len(self._handoffs)
                                        + len(self._inflight)),
                "handoff_inflight": len(self._inflight),
                "handoffs_pending": [j.req.req_id
                                     for j in list(self._handoffs)[:16]],
                "prefill": self.prefill.scheduler_snapshot(),
                "decode": self.decode.scheduler_snapshot()}

    def metrics(self) -> Dict:
        c = {k: v for k, v in self.counters.items()
             if k not in ("collective_calls", "collective_bytes")}
        pre_c, dec_c = self.prefill.counters, self.decode.counters
        wall = ((self._t_last - self._t_first)
                if self._t_first is not None
                and self._t_last is not None else 0.0)
        c["wall_time_s"] = round(wall, 6)
        gen_tokens = (pre_c["tokens_generated"]
                      + dec_c["tokens_generated"])
        c["tokens_generated"] = gen_tokens
        c["tokens_per_sec"] = (round(gen_tokens / wall, 3)
                               if wall > 0 else 0.0)
        c["requests_completed"] = (pre_c["requests_completed"]
                                   + dec_c["requests_completed"])
        cut = self._metrics_reset_t
        ttfts = [r.ttft for r in self._requests
                 if r.ttft is not None
                 and (cut is None or (r.first_token_t or 0.0) >= cut)]
        c["ttft_ms_mean"] = (round(float(np.mean(ttfts)) * 1e3, 3)
                             if ttfts else None)
        c["ttft_ms_max"] = (round(float(np.max(ttfts)) * 1e3, 3)
                            if ttfts else None)
        n, s, mx = self._hand_stats
        c["handoff_ms_mean"] = round(s / n, 3) if n else None
        c["handoff_ms_max"] = round(mx, 3) if n else None
        sched = self.prefill._scheduler_metrics()
        sched["preemptions"] = dec_c["preemptions"]
        sched["requeues"] = dec_c["requeues"]
        sched["deadline_expired"] = pre_c["deadline_expired"]
        sched["handoff_queue_depth"] = (len(self._handoffs)
                                        + len(self._inflight))
        c["scheduler"] = sched
        c["groups"] = {"prefill": self.prefill.metrics(),
                       "decode": self.decode.metrics()}
        # decode-variant roofline attribution belongs to the group
        # that runs decode steps (both groups also carry their own
        # under c["groups"])
        c["roofline"] = self.decode._roofline_metrics()
        if self._obs is not None:
            obs = self._obs
            c["latency"] = obs.latency_snapshot()
            c["retrace_warnings"] = (
                len(self.prefill._obs.watchdog.events)
                + len(self.decode._obs.watchdog.events))
            c["stall_dumps"] = (len(obs.stall_dumps)
                                + obs.stall_dumps_suppressed)
            c["timeline_events"] = len(obs.timeline)
            c["timeline_dropped"] = obs.timeline.dropped
            if self._flight is not None:
                c["collectives"] = _collectives_snapshot(self.counters,
                                                         obs)
        if self._telemetry is not None:
            c["telemetry"] = self._telemetry.snapshot()
        return c

    @property
    def telemetry(self) -> Optional[TelemetryPlane]:
        """The continuous telemetry plane, or None when disabled."""
        return self._telemetry

    def _telemetry_alert(self, alert: Dict):
        """Stamp an ``alert`` timeline event; page-severity alerts also
        land a flight-recorder dump with the whole-engine scheduler
        snapshot (both groups + handoff queue)."""
        obs = self._obs
        if obs is None:
            return
        obs.timeline.record(
            "alert", rule=alert.get("rule"),
            severity=alert.get("severity"), metric=alert.get("metric"),
            value=alert.get("value"), threshold=alert.get("threshold"))
        if (alert.get("severity") == "page"
                and self._telemetry.config.page_dumps):
            obs.stall_dump(
                f"telemetry alert: {alert.get('rule')} on "
                f"{alert.get('metric')}", self.scheduler_snapshot(),
                metrics={"alert": alert})

    def reset_metrics(self):
        """Restart the measurement window on the orchestrator AND both
        groups (each group's retrace watchdog arms; the handoff trace
        counter is cumulative like every trace counter)."""
        for k in ("handoffs", "partial_handoffs",
                  "kv_bytes_transferred", "requests_submitted",
                  "drain_truncations"):
            self.counters[k] = 0
        self._hand_stats = [0, 0.0, 0.0]
        self._t_first = self._t_last = None
        self._metrics_reset_t = self._clock()
        self._requests = [r for r in self._requests if not r.done]
        if self._flight is not None:
            self.counters.pop("collective_calls", None)
            self.counters.pop("collective_bytes", None)
        if self._obs is not None:
            self._obs.reset_window()
        self.prefill.reset_metrics()
        self.decode.reset_metrics()
        if self._obs is not None:
            # the workers' reset_window() replaced their histogram
            # objects — re-share the request-level set so both feed the
            # engine-level distributions again
            self._share_histograms()

    def _share_histograms(self):
        """Point both workers' request-level latency histograms at the
        engine-level objects: a request admits on the prefill group and
        finishes on the decode group (or on the prefill group for an
        EOS-at-first-token), and its TTFT/TPOT/queue-wait must land in
        ONE distribution wherever it completes."""
        for name in _SHARED_HISTOGRAMS:
            h = self._obs.registry.histogram(name)
            self.prefill._obs.registry.histograms[name] = h
            self.decode._obs.registry.histograms[name] = h

    @property
    def observability(self) -> Optional[Observability]:
        return self._obs

    def _require_obs(self) -> Observability:
        if self._obs is None:
            raise RuntimeError(
                "observability is disabled for this engine; construct "
                "with DisaggregatedEngine(..., observability=True)")
        return self._obs

    def export_trace(self, path: str) -> str:
        from ..observability.roofline import roofline_chrome_events
        return self._require_obs().export_chrome(
            path, process_name="paddle_tpu disagg serving",
            extra_events=roofline_chrome_events(
                self.decode._roofline_metrics()))

    def write_timeline(self, path: str) -> str:
        return self._require_obs().write_jsonl(
            path, header={"mode": "serving",
                          "disaggregated": True,
                          "capacity": self.capacity,
                          "prefill_slots": self.prefill_slots,
                          "block_size": self.block_size,
                          "roofline":
                              self.decode._roofline_metrics()})

    # -- static program audit -----------------------------------------
    def program_specs(self, register: bool = True):
        """Both groups' programs under disagg names — the decode
        group's decode step, the prefill group's per-bucket prefill
        (plus COW page copier with a prefix cache), and the two handoff
        programs — so the PR-5 audit gate covers the disaggregated
        path next to (not instead of) the colocated programs."""
        from ..analysis import ProgramSpec, REGISTRY
        sds = jax.ShapeDtypeStruct
        specs = []
        for s in self.decode.program_specs(register=False):
            if s.name.startswith("serving_decode"):
                specs.append(dataclasses.replace(
                    s, name="disagg_decode",
                    tags=s.tags + ("disagg",)))
        for s in self.prefill.program_specs(register=False):
            if "prefill" in s.name:
                P = s.name.rsplit("_", 1)[1]
                specs.append(dataclasses.replace(
                    s, name=f"disagg_prefill_{P}",
                    tags=s.tags + ("disagg",)))
            elif "page_copy" in s.name:
                specs.append(dataclasses.replace(
                    s, name="disagg_page_copy",
                    tags=s.tags + ("disagg",)))
            elif "kv_spill" in s.name or "kv_restore" in s.name:
                # the prefill group's host-tier handoff pair
                specs.append(dataclasses.replace(
                    s, name="disagg_" + s.name[len("serving_"):],
                    tags=s.tags + ("disagg",)))
        # fresh jit instances for the handoff pair (auditing must not
        # disturb the live programs' caches)
        ext, ins = self._build_handoff_fns()
        pre_pools = jax.ShapeDtypeStruct(self.prefill._k_pools.shape,
                                         self.prefill._k_pools.dtype)
        dec_pools = jax.ShapeDtypeStruct(self.decode._k_pools.shape,
                                         self.decode._k_pools.dtype)
        W = self._xfer_w
        pages_sd = sds((pre_pools.shape[0], W) + pre_pools.shape[2:],
                       pre_pools.dtype)
        idx_sd = sds((W,), jnp.int32)
        specs.append(ProgramSpec(
            name="disagg_kv_extract", fn=ext,
            args=(pre_pools, pre_pools, idx_sd),
            tags=("serving", "disagg")))
        specs.append(ProgramSpec(
            name="disagg_kv_insert", fn=ins,
            args=(dec_pools, dec_pools, idx_sd, pages_sd, pages_sd),
            donate_argnums=(0, 1), carry={0: 0, 1: 1},
            tags=("serving", "disagg")))
        if register:
            for s in specs:
                REGISTRY.register(s)
        return specs

    def audit(self, register: bool = True):
        """Static audit of every program of both groups (trace-only;
        the trace counters the tier-1 suite pins are snapshotted and
        restored)."""
        from ..analysis import audit_spec as _audit, publish_findings
        import copy
        snaps = []
        for eng in (self.prefill, self.decode):
            snaps.append((eng.counters,
                          {k: copy.deepcopy(eng.counters[k])
                           for k in ("decode_traces", "prefill_traces",
                                     "calibration_traces",
                                     "offload_traces")}))
        h_snap = self.counters["handoff_traces"]
        try:
            reports = [_audit(s)
                       for s in self.program_specs(register=register)]
        finally:
            for counters, snap in snaps:
                counters.update(snap)
            self.counters["handoff_traces"] = h_snap
        publish_findings(reports, counters=self.counters, obs=self._obs)
        return reports
