"""ServingFleet: prefix-aware replica router over N serving engines.

PRs 1-10 built one serving engine per chip group — colocated
(serving.py), tensor-parallel (tp.py), disaggregated (disagg.py). The
north star of serving millions of users needs the layer ABOVE: many
engine replicas behind one router. The naive router (round-robin,
least-loaded) destroys the thing the radix prefix cache spent ten PRs
building — warm KV state is REPLICA-LOCAL, and a warm request routed
to the wrong replica pays a full cold prefill. This module is the
TPU-native analog of the reference's hybrid-orchestration layer
(SURVEY §2.4) applied to serving: route work to where the state
already lives.

- **Tree-summary protocol.** Each replica's radix prefix cache exports
  a page-aligned summary ``{hash(token_prefix): n_tokens}`` plus a
  monotone ``version`` (``ServingEngine.prefix_summary()`` /
  ``prefix_cache_version``); the router caches the summary per replica
  and refreshes only when the version moves. Summaries include SPILLED
  nodes — a prefix living in a replica's host-RAM tier is still warm
  there (it restores on admission), which is exactly why the offload
  tier and the router ship together: warm state stops dying at the HBM
  boundary, and the router keeps finding it.
- **Prefix-aware routing** (``policy="prefix"``, default): hash the
  prompt's page-aligned prefixes longest-first against every replica's
  summary; the longest match wins (ties: least loaded, then lowest
  index). A cold prompt falls back to least-loaded placement with a
  round-robin tie-break so an idle fleet spreads cold work instead of
  piling it on replica 0.
- **Per-replica admission backpressure**: a replica whose un-admitted
  queue is at ``max_queue_depth`` is not a routing candidate while any
  other replica has headroom — a warm request whose home replica is
  saturated DIVERTS to a cold replica (counted, so the warm-hit ratio
  honestly reflects the tradeoff) rather than queueing behind it.
- ``policy="round_robin"`` / ``"least_loaded"`` keep the naive
  placements available as A/B baselines (``bench.py serving_fleet``
  measures the warm-hit gap between them and prefix routing).

Replicas are any mix of engine kinds — colocated ``ServingEngine``
(with or without mesh/prefix cache/offload) and
``DisaggregatedEngine`` expose the same ``submit/step/drain/metrics``
surface plus the router protocol (``queue_depth``, ``live_slots``,
``prefix_summary``). Greedy output is per-request deterministic on
every engine kind (the PR-1..10 parity contracts), so fleet output is
bit-identical to a single colocated engine REGARDLESS of placement —
asserted in tier-1 over mixed-kind fleets.

The router is pure host-side bookkeeping: no device work, no new
programs, zero retraces. ``step()`` round-robins one scheduler
iteration per replica; each replica's device work streams
independently (on real multi-chip fleets each replica owns its chips —
the forced-host CPU tier-1 runs prove structure, not chip perf).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..observability import (Observability, TelemetryConfig,
                             TelemetryPlane)
from .generation import GenerationConfig
from .serving import Request, _drain_loop

__all__ = ["ServingFleet"]

# request-level distributions shared BY REFERENCE with every
# observability-enabled replica (the disagg engine's idiom): a request
# admits and finishes on its replica, but its TTFT/TPOT must land in
# ONE fleet-wide distribution wherever it ran
FLEET_SHARED_HISTOGRAMS = ("ttft_ms", "tpot_ms", "queue_wait_ms",
                           "e2e_ms")
# ...plus what only the router can time
FLEET_HISTOGRAMS = FLEET_SHARED_HISTOGRAMS + ("step_ms",)

_POLICIES = ("prefix", "least_loaded", "round_robin")


def _replica_roofline(engine) -> Dict[str, object]:
    # a DisaggregatedEngine replica prices its decode GROUP's arms;
    # every other engine kind models its own
    if hasattr(engine, "_roofline_metrics"):
        return engine._roofline_metrics()
    return engine.decode._roofline_metrics()


class _Replica:
    """Router-side handle: the engine plus its cached tree summary."""

    __slots__ = ("name", "engine", "bs", "version", "summary",
                 "max_tokens", "routed", "warm_routed")

    def __init__(self, name: str, engine):
        self.name = name
        self.engine = engine
        self.bs = int(engine.block_size)
        self.version = -1            # forces the first refresh
        self.summary: Dict[int, int] = {}
        self.max_tokens = 0          # longest cached path, in tokens
        self.routed = 0
        self.warm_routed = 0

    def refresh(self) -> Dict[int, int]:
        v = self.engine.prefix_cache_version
        if v != self.version:
            self.summary = self.engine.prefix_summary()
            self.max_tokens = max(self.summary.values(), default=0)
            self.version = v
        return self.summary

    @property
    def load(self) -> Tuple[int, int]:
        return (self.engine.queue_depth, self.engine.live_slots)


class ServingFleet:
    """N engine replicas behind one prefix-aware router.

    ``replicas`` is a list of engines or ``(name, engine)`` pairs (a
    bare list names them ``replica0..N-1``). ``submit()`` routes one
    request and returns the replica's :class:`Request`; ``step()``
    runs one scheduler iteration on every replica; ``drain()`` steps
    until the whole fleet is idle. ``metrics()`` reports the routing
    counters (warm/cold/diverted + warm-hit ratio), per-replica queue
    depth/load, the aggregated host-tier spill/restore report, and
    each replica's full engine metrics under ``"replicas"``.
    """

    def __init__(self, replicas, policy: str = "prefix",
                 max_queue_depth: Optional[int] = None,
                 observability=False, telemetry=False):
        if not replicas:
            raise ValueError("ServingFleet needs at least one replica")
        self._replicas: List[_Replica] = []
        for i, r in enumerate(replicas):
            name, eng = (r if isinstance(r, (tuple, list))
                         else (f"replica{i}", r))
            self._replicas.append(_Replica(str(name), eng))
        names = [r.name for r in self._replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        if len({id(r.engine) for r in self._replicas}) != len(names):
            raise ValueError("the same engine object appears twice — "
                             "each replica needs its own engine")
        if policy not in _POLICIES:
            raise ValueError(f"policy must be one of {_POLICIES}, "
                             f"got {policy!r}")
        self.policy = policy
        self.max_queue_depth = (None if max_queue_depth is None
                                else int(max_queue_depth))
        if self.max_queue_depth is not None and self.max_queue_depth < 1:
            raise ValueError("max_queue_depth must be >= 1 (or None)")
        self.counters = {
            "requests_submitted": 0, "routed_warm": 0, "routed_cold": 0,
            "routed_diverted": 0, "fleet_steps": 0,
            "drain_truncations": 0,
        }
        self._rr_next = 0            # round_robin policy cursor
        self._rr_cold = 0            # cold-placement tie-break cursor
        self._requests: List[Request] = []
        self._t_first = self._t_last = None
        self._metrics_reset_t = None
        self.last_drain_truncated = False
        # telemetry implies observability (alerts land timeline events
        # and stall dumps, both owned by the harness)
        _tcfg = TelemetryConfig.coerce(telemetry)
        if observability or _tcfg is not None:
            self._obs = (observability
                         if isinstance(observability, Observability)
                         else Observability(histograms=FLEET_HISTOGRAMS))
            self._obs.registry.adopt_counters(self.counters)
            self._share_histograms()
        else:
            self._obs = None
        # continuous telemetry plane (r22): the fleet rollup plus every
        # replica's engine under a `replica` label — burn-rate and
        # anomaly rules then judge each replica separately, so ONE
        # misbehaving replica pages without drowning in the rollup
        self._telemetry = None
        if _tcfg is not None:
            self._telemetry = TelemetryPlane(
                _tcfg, on_alert=self._telemetry_alert)
            self._telemetry.register("fleet", self.metrics,
                                     counters=self.counters,
                                     skip=("replicas",))
            for rep in self._replicas:
                self._telemetry.register(
                    "fleet_replica", rep.engine.metrics,
                    labels={"replica": rep.name},
                    counters=getattr(rep.engine, "counters", None),
                    skip=("replicas", "groups"))

    def _share_histograms(self):
        """Point every observability-enabled replica's request-level
        latency histograms at the fleet-level objects (replicas without
        observability keep their zero-overhead None harness and simply
        don't feed the fleet distributions). A disaggregated replica
        re-shares onward to its two workers."""
        for rep in self._replicas:
            obs = rep.engine.observability
            if obs is None:
                continue
            for name in FLEET_SHARED_HISTOGRAMS:
                obs.registry.histograms[name] = \
                    self._obs.registry.histogram(name)
            resh = getattr(rep.engine, "_share_histograms", None)
            if resh is not None:
                resh()

    # -- routing ------------------------------------------------------
    def _match_tokens(self, rep: _Replica,
                      toks: Tuple[int, ...]) -> int:
        """Longest page-aligned cached prefix of the prompt (as an
        int tuple) on ``rep``, in tokens. Capped at ``len(prompt) - 1``
        full pages — mirroring admission's cap, so the router never
        scores a match the engine could not use. Hash collisions are
        guarded by the stored token length (a colliding entry of the
        wrong length cannot match)."""
        summ = rep.refresh()
        if not summ:
            return 0
        bs = rep.bs
        # cap the scan at the replica's LONGEST cached path: probing a
        # prefix longer than anything it holds is wasted hashing (the
        # cold-prompt routing hot path would otherwise pay
        # O(len(prompt)^2 / bs) element-hashes per replica)
        top = min((len(toks) - 1) // bs, rep.max_tokens // bs)
        for k in range(top, 0, -1):
            if summ.get(hash(toks[:k * bs])) == k * bs:
                return k * bs
        return 0

    def _route(self, prompt: np.ndarray) -> Tuple[_Replica, int, bool]:
        """Pick a replica: ``(replica, matched_tokens, diverted)``.
        The naive policies still SCORE the chosen replica (summaries
        are cached, the probe is cheap), so their warm_hit_ratio is a
        real measurement of lucky warm landings — the A/B baseline the
        bench banks, not a constant 0."""
        reps = self._replicas
        toks = tuple(int(t) for t in prompt)
        if self.policy == "round_robin":
            r = reps[self._rr_next % len(reps)]
            self._rr_next += 1
            return r, self._match_tokens(r, toks), False
        cap = self.max_queue_depth
        open_ = [i for i, r in enumerate(reps)
                 if cap is None or r.engine.queue_depth < cap]
        if not open_:                 # whole fleet saturated: least
            open_ = list(range(len(reps)))      # loaded still wins
        diverted = False
        if self.policy == "prefix":
            scores = [self._match_tokens(r, toks) for r in reps]
            best = max(scores)
            if best > 0:
                warm_open = [i for i in open_ if scores[i] == best]
                if warm_open:
                    i = min(warm_open,
                            key=lambda j: (reps[j].load, j))
                    return reps[i], best, False
                # the warm home replica(s) are saturated: divert
                # instead of queueing behind them — to the best
                # SHORTER match still open (a partial prefix skip
                # beats a full cold prefill), else to cold capacity
                diverted = True
                warm_any = [i for i in open_ if scores[i] > 0]
                if warm_any:
                    sub = max(scores[i] for i in warm_any)
                    cands = [i for i in warm_any if scores[i] == sub]
                    i = min(cands, key=lambda j: (reps[j].load, j))
                    return reps[i], sub, True
        lo = min(reps[j].load for j in open_)
        cands = [j for j in open_ if reps[j].load == lo]
        i = cands[self._rr_cold % len(cands)]
        self._rr_cold += 1
        matched = (self._match_tokens(reps[i], toks)
                   if self.policy == "least_loaded" else 0)
        return reps[i], matched, diverted

    # -- public API ---------------------------------------------------
    def submit(self, prompt, gen: Optional[GenerationConfig] = None,
               priority: Optional[int] = None,
               deadline_s: Optional[float] = None) -> Request:
        """Route one request onto a replica and enqueue it there.
        Returns the replica engine's :class:`Request` — lifecycle,
        output and SLO semantics are the replica's own."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        rep, matched, diverted = self._route(prompt)
        req = rep.engine.submit(prompt, gen, priority=priority,
                                deadline_s=deadline_s)
        rep.routed += 1
        self.counters["requests_submitted"] += 1
        if matched > 0:
            rep.warm_routed += 1
            self.counters["routed_warm"] += 1
        else:
            self.counters["routed_cold"] += 1
        if diverted:
            self.counters["routed_diverted"] += 1
        self._requests.append(req)
        if self._obs is not None:
            self._obs.timeline.record(
                "route", req.req_id, replica=rep.name,
                matched_tokens=matched,
                **({"diverted": True} if diverted else {}))
        return req

    def step(self) -> bool:
        """One scheduler iteration on every replica (their device work
        streams run independently). Returns True if any replica did
        work."""
        obs = self._obs
        t0 = time.perf_counter() if obs is not None else 0.0
        if self._t_first is None:
            self._t_first = time.perf_counter()
        did = False
        for rep in self._replicas:
            did = rep.engine.step() or did
        self.counters["fleet_steps"] += 1
        if did:
            self._t_last = time.perf_counter()
        if obs is not None:
            now = time.perf_counter()
            if did:
                obs.hist("step_ms").observe((now - t0) * 1e3)
            obs.sample_gauges(now, {
                f"queue_depth[{r.name}]": r.engine.queue_depth
                for r in self._replicas})
        if self._telemetry is not None:
            self._telemetry.on_step()
        return did

    @property
    def idle(self) -> bool:
        return all(r.engine.idle for r in self._replicas)

    def drain(self, max_steps: Optional[int] = None) -> int:
        """Step until every replica is idle (shared ``_drain_loop``
        semantics: capped drains record truncation, fleet-wide
        starvation raises after a stall dump)."""
        return _drain_loop(
            self, max_steps,
            starve_reason="fleet drain starved: no replica can make "
                          "progress",
            starve_error="fleet starved: no replica can admit its "
                         "queued requests (KV pools too small for the "
                         "in-flight mix?)")

    def _drain_truncated_event(self, n: int):
        if self._obs is not None:
            self._obs.timeline.record(
                "drain_truncated", steps=n,
                queue_depths={r.name: r.engine.queue_depth
                              for r in self._replicas})

    # -- reporting ----------------------------------------------------
    def scheduler_snapshot(self) -> Dict:
        return {
            "policy": self.policy,
            "queue_depths": {r.name: r.engine.queue_depth
                             for r in self._replicas},
            "live_slots": {r.name: r.engine.live_slots
                           for r in self._replicas},
            "replicas": {r.name: r.engine.scheduler_snapshot()
                         for r in self._replicas},
        }

    def metrics(self) -> Dict:
        c = self.counters
        rm = {r.name: r.engine.metrics() for r in self._replicas}
        wall = ((self._t_last - self._t_first)
                if self._t_first is not None and self._t_last is not None
                else 0.0)
        gen_tokens = sum(m["tokens_generated"] for m in rm.values())
        routed = c["routed_warm"] + c["routed_cold"]
        cut = self._metrics_reset_t
        ttfts = [r.ttft for r in self._requests
                 if r.ttft is not None
                 and (cut is None or (r.first_token_t or 0.0) >= cut)]
        off: Dict[str, int] = {}
        for r in self._replicas:
            for k, v in r.engine.offload_metrics().items():
                off[k] = off.get(k, 0) + v
        m = {
            "replicas_n": len(self._replicas),
            "requests_submitted": c["requests_submitted"],
            "requests_completed": sum(mm["requests_completed"]
                                      for mm in rm.values()),
            "tokens_generated": gen_tokens,
            "tokens_per_sec": (round(gen_tokens / wall, 3)
                               if wall > 0 else 0.0),
            "wall_time_s": round(wall, 6),
            "fleet_steps": c["fleet_steps"],
            "drain_truncations": c["drain_truncations"],
            "ttft_ms_mean": (round(float(np.mean(ttfts)) * 1e3, 3)
                             if ttfts else None),
            "ttft_ms_max": (round(float(np.max(ttfts)) * 1e3, 3)
                            if ttfts else None),
            "routing": {
                "policy": self.policy,
                "warm": c["routed_warm"],
                "cold": c["routed_cold"],
                "diverted": c["routed_diverted"],
                "warm_hit_ratio": (round(c["routed_warm"] / routed, 4)
                                   if routed else 0.0),
                "per_replica": {
                    r.name: {"routed": r.routed,
                             "warm_routed": r.warm_routed,
                             "queue_depth": r.engine.queue_depth,
                             "live_slots": r.engine.live_slots}
                    for r in self._replicas},
            },
            "offload": off,
            "replicas": rm,
            # per-replica decode-variant roofline attribution (a mixed
            # fleet's replicas price against different dims/quant)
            "roofline": {r.name: _replica_roofline(r.engine)
                         for r in self._replicas},
        }
        if self._obs is not None:
            obs = self._obs
            m["latency"] = obs.latency_snapshot()
            m["gauges"] = obs.gauges_snapshot()
            # replicas own their watchdogs; the fleet report must
            # surface ANY steady-state retrace in the fleet
            m["retrace_warnings"] = sum(
                mm.get("retrace_warnings", 0) for mm in rm.values())
            m["stall_dumps"] = (len(obs.stall_dumps)
                                + obs.stall_dumps_suppressed)
            m["timeline_events"] = len(obs.timeline)
            m["timeline_dropped"] = obs.timeline.dropped
        if self._telemetry is not None:
            m["telemetry"] = self._telemetry.snapshot()
        return m

    @property
    def telemetry(self) -> Optional[TelemetryPlane]:
        """The continuous telemetry plane, or None when disabled."""
        return self._telemetry

    def _telemetry_alert(self, alert: Dict):
        """Stamp an ``alert`` timeline event (replica attribution rides
        in the alert's labels); page-severity alerts also land a
        flight-recorder dump with the fleet scheduler snapshot."""
        obs = self._obs
        if obs is None:
            return
        obs.timeline.record(
            "alert", rule=alert.get("rule"),
            severity=alert.get("severity"), metric=alert.get("metric"),
            replica=(alert.get("labels") or {}).get("replica"),
            value=alert.get("value"), threshold=alert.get("threshold"))
        if (alert.get("severity") == "page"
                and self._telemetry.config.page_dumps):
            obs.stall_dump(
                f"telemetry alert: {alert.get('rule')} on "
                f"{alert.get('metric')}", self.scheduler_snapshot(),
                metrics={"alert": alert})

    def reset_metrics(self):
        """Restart the measurement window on the router AND every
        replica (each replica's retrace watchdog arms)."""
        for k in ("requests_submitted", "routed_warm", "routed_cold",
                  "routed_diverted", "fleet_steps", "drain_truncations"):
            self.counters[k] = 0
        for r in self._replicas:
            r.routed = r.warm_routed = 0
            r.engine.reset_metrics()
        self._requests = [r for r in self._requests if not r.done]
        self._t_first = self._t_last = None
        self._metrics_reset_t = time.perf_counter()
        if self._obs is not None:
            # the replicas' reset_window() replaced their histogram
            # objects — restart the fleet window and re-share so every
            # replica feeds the fleet distributions again
            self._obs.reset_window()
            self._share_histograms()

    # -- observability export -----------------------------------------
    @property
    def observability(self) -> Optional[Observability]:
        return self._obs

    def _require_obs(self) -> Observability:
        if self._obs is None:
            raise RuntimeError(
                "observability is disabled for this fleet; construct "
                "with ServingFleet(..., observability=True)")
        return self._obs

    def export_trace(self, path: str) -> str:
        from ..observability.roofline import roofline_chrome_events
        events = []
        for r in self._replicas:
            report = _replica_roofline(r.engine)
            report = {"variants": {
                f"{r.name}:{k}": v
                for k, v in report["variants"].items()}}
            events.extend(roofline_chrome_events(report))
        return self._require_obs().export_chrome(
            path, process_name="paddle_tpu serving fleet",
            extra_events=events)

    def write_timeline(self, path: str) -> str:
        # the summary tooling reads header["roofline"]["variants"]:
        # report the FIRST replica's arm model there (fleets are
        # homogeneous in practice) and the full per-replica map beside
        roof = {r.name: _replica_roofline(r.engine)
                for r in self._replicas}
        first = self._replicas[0].name if self._replicas else None
        return self._require_obs().write_jsonl(
            path, header={"mode": "serving", "fleet": True,
                          "policy": self.policy,
                          "replicas": [r.name for r in self._replicas],
                          "roofline": roof.get(first),
                          "roofline_replicas": roof})

    # -- static program audit -----------------------------------------
    def program_specs(self, register: bool = True):
        """Every replica's programs, names prefixed ``fleet.<name>.``
        so a mixed fleet's full program set audits side by side. The
        router itself owns no programs."""
        import dataclasses
        specs = []
        for r in self._replicas:
            for s in r.engine.program_specs(register=False):
                specs.append(dataclasses.replace(
                    s, name=f"fleet.{r.name}.{s.name}",
                    tags=s.tags + ("fleet",)))
        if register:
            from ..analysis import REGISTRY
            for s in specs:
                REGISTRY.register(s)
        return specs

    def audit(self, register: bool = True):
        """Static audit of every replica's programs (trace-only; each
        replica's pinned trace counters snapshot/restore)."""
        from ..analysis import audit_spec as _audit, publish_findings
        reports = []
        for r in self._replicas:
            reports.extend(r.engine.audit(register=False))
        if register:
            self.program_specs(register=True)
        publish_findings(reports, counters=self.counters, obs=self._obs)
        return reports
