"""Autoregressive generation with a KV cache.

TPU-native redesign of the reference's fused-transformer decode path
(paddle/phi/kernels/fusion/gpu/fused_multi_transformer_kernel.cu +
masked_multihead_attention — per-step CUDA kernels over a growing cache):
here prefill and decode are two jitted programs with static shapes; the
decode loop is a ``lax.scan`` over steps carrying the cache, so the whole
generation runs as ONE XLA program — no per-token host round trips.

Cache layout: [L, B, T_max, KV, hd] stacked on the layer axis to match the
model's scanned layer params (models/llama.py).
"""
from __future__ import annotations

import dataclasses
import functools
import math
import os
from dataclasses import dataclass
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..models import llama as _llama
from ..ops.rope import build_rope_cache, apply_rope


@dataclass
class GenerationConfig:
    """reference: python/paddle/... generation knobs of
    paddlenlp-style generate(); the sampling surface of the serving path."""

    max_new_tokens: int = 64
    temperature: float = 1.0
    top_k: int = 0            # 0 = disabled
    top_p: float = 1.0        # 1.0 = disabled
    eos_token_id: int = -1    # -1 = never stop early
    greedy: bool = False
    # serving-scheduler knobs (ServingEngine/DisaggregatedEngine
    # submit() defaults; ignored by the static generate paths):
    # priority CLASS, lower = more urgent; deadline_s bounds queue
    # wait — a request still queued past it is rejected, not admitted
    # late (inference/admission.py)
    priority: int = 1
    deadline_s: Optional[float] = None


def _mm(h, w):
    """``h @ w`` where ``w`` may be a quantized weight leaf
    (``{"qw8"|"qw4": q, "scale": s}`` — quantization/ptq.py): quantized
    leaves DEQUANTIZE-THEN-MATMUL at the activation dtype, the
    priority-0 fallback contract every unfused matmul site shares (so
    the unfused route is bit-identical to that composition by
    construction)."""
    from ..quantization.quanters import maybe_dequantize
    return h @ maybe_dequantize(w, h.dtype)


def _wq_mode(params):
    """The weight-quant mode a param tree carries (None/"int8"/"int4"),
    read off the tree STRUCTURE — static at trace time, so dispatch
    metas and program-cache route keys can carry it."""
    from ..quantization.ptq import weight_quant_mode
    return weight_quant_mode(params)


def _repeat_kv(x, n):
    """[B, T, KV, hd] -> [B, T, KV*n, hd] (dense-cache GQA expansion)."""
    if n == 1:
        return x
    b, t, kv, hd = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, t, kv, n, hd)) \
        .reshape(b, t, kv * n, hd)


def init_cache(cfg: _llama.LlamaConfig, batch: int, max_len: int,
               dtype=None):
    dtype = dtype or cfg.dtype
    L, KV, hd = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                 cfg.head_dim)
    shape = (L, batch, max_len, KV, hd)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def _cached_layer(lp, x, sin, cos, cfg, kc, vc, pos):
    """Decoder block over S new tokens at absolute position ``pos``,
    reading/writing the cache. kc/vc: [B, T, KV, hd]."""
    from ..ops import rms_norm as fused_rms_norm, swiglu as fused_swiglu

    H, KV, hd = (cfg.num_attention_heads, cfg.num_key_value_heads,
                 cfg.head_dim)
    b, s, _ = x.shape
    T = kc.shape[1]
    h = fused_rms_norm(x, lp["input_norm"].astype(x.dtype),
                       cfg.rms_norm_eps)
    q = _mm(h, lp["q_proj"]).reshape(b, s, H, hd)
    k = _mm(h, lp["k_proj"]).reshape(b, s, KV, hd)
    v = _mm(h, lp["v_proj"]).reshape(b, s, KV, hd)
    q = apply_rope(q, sin, cos)
    k = apply_rope(k, sin, cos)
    kc = jax.lax.dynamic_update_slice(kc, k.astype(kc.dtype), (0, pos, 0, 0))
    vc = jax.lax.dynamic_update_slice(vc, v.astype(vc.dtype), (0, pos, 0, 0))

    rep = H // KV
    kk = _repeat_kv(kc, rep)    # [B, T, H, hd]
    vv = _repeat_kv(vc, rep)
    scale = 1.0 / math.sqrt(hd)
    scores = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32),
                        kk.astype(jnp.float32)) * scale
    # causal over absolute positions: query i at pos+i sees keys <= pos+i
    t_idx = jnp.arange(T)[None, None, None, :]
    q_idx = pos + jnp.arange(s)[None, None, :, None]
    scores = jnp.where(t_idx <= q_idx, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    attn = jnp.einsum("bhst,bthd->bshd", probs, vv.astype(jnp.float32))
    attn = attn.astype(x.dtype).reshape(b, s, H * hd)
    x = x + _mm(attn, lp["o_proj"])
    h = fused_rms_norm(x, lp["post_norm"].astype(x.dtype), cfg.rms_norm_eps)
    ff = fused_swiglu(_mm(h, lp["gate_proj"]), _mm(h, lp["up_proj"]))
    x = x + _mm(ff, lp["down_proj"])
    return x, kc, vc


def cached_forward(params: Dict, tokens, cfg: _llama.LlamaConfig,
                   k_cache, v_cache, pos):
    """Forward over S tokens starting at absolute position ``pos``.
    Returns (logits [B, S, V], k_cache, v_cache)."""
    x = jnp.take(params["embed_tokens"], tokens, axis=0)
    T = k_cache.shape[2]
    sin_full, cos_full = build_rope_cache(T, cfg.head_dim,
                                          base=cfg.rope_theta)
    s = tokens.shape[1]
    sin = jax.lax.dynamic_slice_in_dim(sin_full, pos, s, axis=0)
    cos = jax.lax.dynamic_slice_in_dim(cos_full, pos, s, axis=0)

    def scan_fn(carry, xs):
        lp, kc, vc = xs
        x, kc, vc = _cached_layer(lp, carry, sin, cos, cfg, kc, vc, pos)
        return x, (kc, vc)

    from ..ops import rms_norm as fused_rms_norm
    x, (k_cache, v_cache) = jax.lax.scan(
        scan_fn, x, (params["layers"], k_cache, v_cache))
    x = fused_rms_norm(x, params["final_norm"].astype(x.dtype),
                       cfg.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed_tokens"].T
    return x @ head, k_cache, v_cache


def sample_token(logits, key, gen: GenerationConfig):
    """[B, V] → [B] next tokens. Greedy / temperature / top-k / top-p."""
    logits = logits.astype(jnp.float32)
    if gen.greedy or gen.temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / jnp.maximum(gen.temperature, 1e-6)
    if gen.top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -gen.top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if gen.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep smallest set with cumulative prob >= top_p (always keep top-1)
        cutoff_idx = jnp.sum(cum < gen.top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None],
                                     axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1)


_RUN_CACHE: Dict = {}
_PAGED_CACHE: Dict = {}
_KEY_CACHE: Dict = {}


def _cache_get(cache: Dict, key):
    """LRU read: re-insert on hit so dict order tracks recency — with
    plain FIFO eviction the hottest serving shape can be the oldest
    entry and get evicted on every insertion (a ~1s retrace per
    request, exactly what these caches exist to prevent)."""
    hit = cache.get(key)
    if hit is not None:
        del cache[key]
        cache[key] = hit
    return hit


def _key_for(seed: int):
    """One 8-byte h2d per distinct seed, not per call (the axon tunnel
    charges ~1s per blocking transfer)."""
    k = _cache_get(_KEY_CACHE, seed)
    if k is None:
        if len(_KEY_CACHE) > 64:
            _KEY_CACHE.pop(next(iter(_KEY_CACHE)))
        k = _KEY_CACHE[seed] = jax.random.key(seed)
    return k


def generate(params: Dict, input_ids, cfg: _llama.LlamaConfig,
             gen: Optional[GenerationConfig] = None,
             seed: int = 0) -> jax.Array:
    """Greedy/sampling generation. input_ids [B, S_in] → [B, S_in + N].

    One jitted program: prefill, then a lax.scan of N decode steps. The
    reference's serving loop launches per-token kernels; on TPU the whole
    loop compiles once and the cache is donated between steps.
    """
    gen = gen or GenerationConfig()
    B, S = input_ids.shape
    T = S + gen.max_new_tokens

    # the compiled runner is cached per (model-config field values,
    # geometry, sampling knobs): defining + jitting `run` fresh on every
    # call forced a full retrace per generate() (fresh function
    # identity), ~1s of host time per serving request on top of the
    # tunnel roundtrips. Value-keying keeps a mutated cfg from serving
    # stale traced constants
    ck = (dataclasses.astuple(cfg), B, S, dataclasses.astuple(gen))
    cached = _cache_get(_RUN_CACHE, ck)
    if cached is not None:
        return cached(params, input_ids, _key_for(seed))

    @partial(jax.jit, static_argnums=())
    def run(params, input_ids, key):
        k_cache, v_cache = init_cache(cfg, B, T)
        logits, k_cache, v_cache = cached_forward(
            params, input_ids, cfg, k_cache, v_cache, 0)
        first = sample_token(logits[:, -1], key, gen)
        done0 = (first == gen.eos_token_id)

        def step(carry, i):
            tok, kc, vc, key, done = carry
            key, sub = jax.random.split(key)
            logits, kc, vc = cached_forward(
                params, tok[:, None], cfg, kc, vc, S + i)
            nxt = sample_token(logits[:, -1], sub, gen)
            nxt = jnp.where(done, gen.eos_token_id, nxt)
            done = done | (nxt == gen.eos_token_id)
            return (nxt, kc, vc, key, done), tok

        # step i feeds carry token and emits it as ys[i]; with carry
        # starting at `first`, ys == [first, g1, …, g_{N-1}] — exactly the
        # N generated tokens (the final carry token is the N+1-th, unused)
        _, toks = jax.lax.scan(
            step, (first, k_cache, v_cache, key, done0),
            jnp.arange(gen.max_new_tokens))
        return jnp.concatenate([input_ids, toks.transpose(1, 0)], axis=1)

    if len(_RUN_CACHE) > 16:    # bound: evict the oldest runner only —
        # clearing all would re-trace every hot serving shape
        _RUN_CACHE.pop(next(iter(_RUN_CACHE)))
    _RUN_CACHE[ck] = run
    return run(params, input_ids, _key_for(seed))


# ---------------------------------------------------------------------------
# Paged-KV serving path
# ---------------------------------------------------------------------------
def _fused_mode(fused_decode):
    """Normalize a ``fused_decode`` knob: None reads the global flag
    (default ON — "on where supported": auto-dispatch still falls back
    to the unfused composition off-TPU / for unsupported shapes)."""
    from ..core.flags import GLOBAL_FLAGS
    from ..ops.pallas import fused_decode_block  # noqa: F401 — defines flag
    if fused_decode is None:
        fused_decode = bool(GLOBAL_FLAGS.get("fused_decode"))
    if fused_decode is False:
        return False
    if fused_decode is True:
        return "auto"
    if fused_decode in ("auto", "pallas", "ref", "block"):
        return fused_decode
    raise ValueError(f"fused_decode must be bool|auto|pallas|ref|block, "
                     f"got {fused_decode!r}")


def _fused_prefill_mode(fused_prefill):
    """Normalize a ``fused_prefill`` knob: None reads the global flag
    (default ON — "on where supported": dispatch still falls back to
    the verbatim unfused chunk off-TPU / for unsupported shapes)."""
    from ..core.flags import GLOBAL_FLAGS
    from ..ops.pallas import fused_prefill_block  # noqa: F401 — flag
    if fused_prefill is None:
        fused_prefill = bool(GLOBAL_FLAGS.get("fused_prefill"))
    if fused_prefill is False:
        return False
    if fused_prefill is True:
        return "auto"
    if fused_prefill in ("auto", "pallas", "ref"):
        return fused_prefill
    raise ValueError(f"fused_prefill must be bool|auto|pallas|ref, "
                     f"got {fused_prefill!r}")


def _prefill_route(mode):
    """The trace-time inputs (beyond the jit signature) that can
    reshape a fused-prefill chunk program: the registry's force-pin
    stack (consulted by dispatch in "auto" mode), the VMEM budget
    (reshapes supports() and the tile candidate lists) and the
    interpret override — every program cache holding a fused-prefill
    trace must fold this in (the ``_PAGED_CACHE`` route contract)."""
    if not mode:
        return ()
    from ..ops.pallas._util import interpret_mode
    from ..ops.pallas.fused_decode_block import _vmem_budget
    from ..ops.pallas.registry import KERNELS
    pins = KERNELS.forced_state() if mode in ("auto", True) else ()
    return (pins, _vmem_budget(), bool(interpret_mode()))


def _fused_prefill_forward(params, toks, cfg, k_pools, v_pools, table,
                           wtable, pos0, n_valid, kv_scales=None,
                           mode="auto"):
    """One request's prefill chunk through the fused prefill-block
    kernels, pool-direct (ops/pallas/fused_prefill_block.py).

    toks: [P] int32 bucket-padded chunk tokens (``n_valid`` real);
    pools [L, N, BS, KV, hd]; table/wtable [MB] — the request's READ
    table and prefix-cache WRITE table. Per layer: ONE fused attention
    kernel (RMSNorm + QKV + RoPE + flash attention over the paged
    history + the chunk's own K/V + o_proj + residual), the chunk's
    K/V scattered into the pools through the write table
    (``write_chunk_to_pool[_quant]`` — only the chunk's own positions,
    not the whole dense view), and ONE fused MLP kernel. Returns
    (logits [P, V], k_pools, v_pools). Callers guard with
    :func:`fused_prefill_block.prefill_fused_selected` — when dispatch
    does not pick BOTH Pallas kernels they run the verbatim unfused
    chunk instead (the bit-identical fallback contract).
    """
    from ..ops import rms_norm as fused_rms_norm
    from ..ops.paged_attention import (write_chunk_to_pool,
                                       write_chunk_to_pool_quant)
    from ..ops.pallas.fused_prefill_block import (prefill_meta,
                                                  resolve_prefill_blocks)

    P = toks.shape[0]
    BS = k_pools.shape[2]
    MB = table.shape[0]
    meta = prefill_meta(cfg, P, BS, MB, k_pools.dtype,
                        kv_scales is not None,
                        weight_dtype=_wq_mode(params))
    attn_fn, mlp_fn, _ = resolve_prefill_blocks(meta, mode)
    x = jnp.take(params["embed_tokens"], toks, axis=0)       # [P, D]
    sin_full, cos_full = build_rope_cache(MB * BS, cfg.head_dim,
                                          base=cfg.rope_theta)
    pos0 = jnp.asarray(pos0, jnp.int32)
    n_valid = jnp.asarray(n_valid, jnp.int32)
    sin = jax.lax.dynamic_slice_in_dim(sin_full, pos0, P, axis=0)
    cos = jax.lax.dynamic_slice_in_dim(cos_full, pos0, P, axis=0)
    wtable = jnp.asarray(wtable, jnp.int32)

    def layer(x, xs):
        if kv_scales is None:
            lp, kp, vp = xs
            scales = None
        else:
            lp, kp, vp, ksc, vsc = xs
            scales = (ksc, vsc)
        x, k_new, v_new = attn_fn(
            x, lp["input_norm"].astype(x.dtype), lp["q_proj"],
            lp["k_proj"], lp["v_proj"], lp["o_proj"], sin, cos, kp, vp,
            table, pos0, n_valid, scales, cfg.rms_norm_eps)
        if scales is None:
            kp, vp = write_chunk_to_pool(kp, vp, wtable, pos0, n_valid,
                                         k_new, v_new)
        else:
            kp, vp = write_chunk_to_pool_quant(
                kp, vp, wtable, pos0, n_valid, k_new, v_new, ksc, vsc)
        x = mlp_fn(x, lp["post_norm"].astype(x.dtype), lp["gate_proj"],
                   lp["up_proj"], lp["down_proj"], cfg.rms_norm_eps)
        return x, (kp, vp)

    scan_xs = (params["layers"], k_pools, v_pools) if kv_scales is None \
        else (params["layers"], k_pools, v_pools) + tuple(kv_scales)
    x, (k_pools, v_pools) = jax.lax.scan(layer, x, scan_xs)
    x = fused_rms_norm(x[None], params["final_norm"].astype(x.dtype),
                       cfg.rms_norm_eps)[0]
    head = params.get("lm_head")
    if head is None:
        head = params["embed_tokens"].T
    return x @ head, k_pools, v_pools


def _mesh_route(sm):
    """The mesh's contribution to a program-cache key: axis name, tp
    degree, collective placement and the device identities (two meshes
    over different chips must not share a compiled program)."""
    if sm is None:
        return ()
    return (sm.axis, sm.tp, sm.collective,
            tuple(int(d.id) for d in sm.mesh.devices.flat))


def _paged_chunk_runner(cfg, gen, quant=False, fused=False, sm=None,
                        wq=None):
    """Jitted n-step decode scan, cached per (cfg values, gen values) —
    a fresh jit per generate_paged call would re-trace the whole L-layer
    scan every serving request. ``sm``: an optional ServingMesh — the
    scan body then runs the tensor-parallel decode step under shard_map
    (inference/tp.py), still ONE jitted program per chunk size.
    ``wq``: the weight-quant mode ("int8"/"int4"/None) — it rides in
    the param tree's STRUCTURE (the jit signature would retrace
    anyway), but it also reshapes kernel dispatch at trace time, so it
    keys this cache explicitly (the ``_PAGED_CACHE`` route contract —
    a flipped quant mode must retrace, never replay)."""
    from ..core.flags import GLOBAL_FLAGS
    # the kernel-route flags are traced INTO the compiled scan, so they
    # must key the cache — an A/B flip (bench_paged_decode) would
    # otherwise silently reuse the first-compiled path. Same for the
    # registry's force pins: in "auto" mode dispatch consults the
    # thread-local pin at trace time, so a program traced inside a
    # KERNELS.force(...) block must not be replayed for unpinned calls
    if fused:
        from ..ops.pallas.fused_decode_block import (_vmem_budget,
                                                     scoped_vmem_budget)
        from ..ops.pallas.registry import KERNELS
        from ..ops.pallas._util import interpret_mode
        # every trace-time input that can reshape the program: the pin
        # stack (consulted by dispatch in "auto" mode only), the VMEM
        # budget (reshapes the supports predicates AND the fused MLP's
        # block_f candidate list, which forced "pallas" mode still
        # reads), the scoped envelope (reshapes the single-launch
        # kernel's combined-window predicate + block_f pairs) and the
        # interpret override (flips pallas variants off in "auto",
        # flips interpret compilation in forced modes)
        pins = (KERNELS.forced_state() if fused in ("auto", True)
                else ())
        route = (pins, _vmem_budget(), scoped_vmem_budget(),
                 bool(interpret_mode()))
    else:
        route = ()
    ck = (dataclasses.astuple(cfg), dataclasses.astuple(gen),
          bool(GLOBAL_FLAGS.get("use_paged_kernel")), bool(quant),
          fused, route, _mesh_route(sm), wq)
    cached = _cache_get(_PAGED_CACHE, ck)
    if cached is not None:
        return cached
    if sm is None:
        step = _paged_decode_step if not fused else functools.partial(
            _fused_decode_step, mode=fused)
    else:
        def step(params, tok, cfg_, kp, vp, block_tables, seq_lens,
                 kv_scales=None):
            # one shard_map per decode step inside the scan body (the
            # ONE wiring, shared with the engine's decode program):
            # per-shard forward, sampling on the replicated logits
            # outside — shard_map'd random ops and typed keys disagree
            # across jax versions, and logits are replicated anyway
            extra = tuple(kv_scales) if kv_scales is not None else ()
            return sm.sharded_decode_fn(
                cfg_, fused, quant=kv_scales is not None)(
                params, tok, seq_lens, block_tables, kp, vp, *extra)

    @functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(5, 6))
    def chunk_fn(n, params, tok, key, done, k_pools, v_pools, seq_lens,
                 block_tables, kv_scales=None):
        def body(carry, _):
            tok, key, done, seq_lens, kp, vp = carry
            logits, kp, vp = step(
                params, tok, cfg, kp, vp, block_tables, seq_lens,
                kv_scales=kv_scales)
            key, sub = jax.random.split(key)
            nxt = sample_token(logits, sub, gen)
            nxt = jnp.where(done, gen.eos_token_id, nxt)
            done = done | (nxt == gen.eos_token_id)
            return (nxt, key, done, seq_lens + 1, kp, vp), nxt

        carry, toks = jax.lax.scan(
            body, (tok, key, done, seq_lens, k_pools, v_pools), None,
            length=n)
        tok, key, done, seq_lens, k_pools, v_pools = carry
        return toks, tok, key, done, seq_lens, k_pools, v_pools

    if len(_PAGED_CACHE) > 16:
        _PAGED_CACHE.pop(next(iter(_PAGED_CACHE)))
    _PAGED_CACHE[ck] = chunk_fn
    return chunk_fn


def _paged_decode_step(params, tok, cfg, k_pools, v_pools, block_tables,
                       seq_lens, kv_scales=None):
    """One decode token per sequence over paged pools.

    tok: [B] int32 current tokens; k_pools/v_pools: [L, N, BS, KV, hd];
    block_tables: [B, MB]; seq_lens: [B] lengths INCLUDING the current
    token's position (i.e. the new token is written at seq_lens, and
    attention runs over seq_lens+1 tokens).
    ``kv_scales``: (k_scale [L, KV], v_scale [L, KV]) when the pools are
    int8 (static per-head cache quantization — reference block_attn.h
    int8 cache mode): halves KV HBM, the attention math stays fp32.
    Returns (logits [B, V], k_pools, v_pools).
    """
    from ..ops import rms_norm as fused_rms_norm, swiglu as fused_swiglu
    from ..ops.paged_attention import (paged_attention_decode,
                                      paged_attention_decode_quant,
                                      write_to_pool, write_to_pool_quant)

    H, KV, hd = (cfg.num_attention_heads, cfg.num_key_value_heads,
                 cfg.head_dim)
    B = tok.shape[0]
    x = jnp.take(params["embed_tokens"], tok, axis=0)  # [B, D]
    pos_ids = seq_lens[:, None]  # [B, 1] rope position per sequence
    # one rope table for all layers/steps (XLA hoists it as a constant)
    sin, cos = build_rope_cache(cfg.max_position_embeddings,
                                cfg.head_dim, base=cfg.rope_theta)

    def layer(x, xs):
        if kv_scales is None:
            lp, kp, vp = xs
        else:
            lp, kp, vp, ksc, vsc = xs
        h = fused_rms_norm(x[:, None], lp["input_norm"].astype(x.dtype),
                           cfg.rms_norm_eps)[:, 0]
        q = _mm(h, lp["q_proj"]).reshape(B, 1, H, hd)
        k = _mm(h, lp["k_proj"]).reshape(B, 1, KV, hd)
        v = _mm(h, lp["v_proj"]).reshape(B, 1, KV, hd)
        q = apply_rope(q, sin, cos, position_ids=pos_ids)
        k = apply_rope(k, sin, cos, position_ids=pos_ids)
        if kv_scales is None:
            kp, vp = write_to_pool(kp, vp, block_tables, seq_lens,
                                   k[:, 0].astype(kp.dtype),
                                   v[:, 0].astype(vp.dtype))
            attn = paged_attention_decode(q[:, 0], kp, vp, block_tables,
                                          seq_lens + 1)
        else:
            kp, vp = write_to_pool_quant(kp, vp, block_tables, seq_lens,
                                         k[:, 0], v[:, 0], ksc, vsc)
            attn = paged_attention_decode_quant(
                q[:, 0], kp, vp, block_tables, seq_lens + 1, ksc, vsc)
        x = x + _mm(attn.reshape(B, H * hd).astype(x.dtype),
                    lp["o_proj"])
        h = fused_rms_norm(x[:, None], lp["post_norm"].astype(x.dtype),
                           cfg.rms_norm_eps)[:, 0]
        ff = fused_swiglu(_mm(h, lp["gate_proj"]), _mm(h, lp["up_proj"]))
        x = x + _mm(ff, lp["down_proj"])
        return x, (kp, vp)

    scan_xs = (params["layers"], k_pools, v_pools) if kv_scales is None \
        else (params["layers"], k_pools, v_pools) + tuple(kv_scales)
    x, (k_pools, v_pools) = jax.lax.scan(layer, x, scan_xs)
    x = fused_rms_norm(x[:, None], params["final_norm"].astype(x.dtype),
                       cfg.rms_norm_eps)[:, 0]
    head = params.get("lm_head")
    if head is None:
        head = params["embed_tokens"].T
    return x @ head, k_pools, v_pools


def _fused_decode_step(params, tok, cfg, k_pools, v_pools, block_tables,
                       seq_lens, kv_scales=None, mode="auto"):
    """``_paged_decode_step`` through the fused decode-block kernels.

    Per block, instead of ~6 separate programs: either ONE single-launch
    megakernel for the whole block (attn + MLP, the residual handoff in
    VMEM — where ``decode_block_fused`` dispatches, or mode="block"
    forces it) with the pool append in between left exactly where it is
    today, or the two-stage route: ONE fused attention kernel (RMSNorm
    + QKV + RoPE + paged attention incl. the new token + o_proj +
    residual), the pool append for the new token's K/V, and ONE fused
    MLP kernel (RMSNorm + SwiGLU + residual). Variant choice (Pallas
    megakernel(s) vs the bit-identical unfused composition) comes from
    the kernel registry at trace time; ``mode`` forwards to
    :func:`paddle_tpu.ops.pallas.fused_decode_block
    .resolve_decode_step`. Signature and carried state match
    ``_paged_decode_step`` exactly, so callers swap freely.
    """
    from ..ops import rms_norm as fused_rms_norm
    from ..ops.paged_attention import write_to_pool, write_to_pool_quant
    from ..ops.pallas.fused_decode_block import (decode_meta,
                                                 resolve_decode_step)

    B = tok.shape[0]
    meta = decode_meta(cfg, B=B, BS=k_pools.shape[2],
                       MB=block_tables.shape[1],
                       pool_dtype=k_pools.dtype,
                       quant=kv_scales is not None,
                       weight_dtype=_wq_mode(params))
    block_fn, attn_fn, mlp_fn, _ = resolve_decode_step(meta, mode)
    x = jnp.take(params["embed_tokens"], tok, axis=0)        # [B, D]
    sin, cos = build_rope_cache(cfg.max_position_embeddings,
                                cfg.head_dim, base=cfg.rope_theta)

    def layer(x, xs):
        if kv_scales is None:
            lp, kp, vp = xs
            scales = None
        else:
            lp, kp, vp, ksc, vsc = xs
            scales = (ksc, vsc)
        if block_fn is not None:
            # one launch per block; the pool write stays with the
            # caller (the megakernel's MLP phase reads no pool state,
            # so writing after it is the same math as between stages)
            x, k_new, v_new = block_fn(
                x, lp["input_norm"].astype(x.dtype), lp["q_proj"],
                lp["k_proj"], lp["v_proj"], lp["o_proj"],
                lp["post_norm"].astype(x.dtype), lp["gate_proj"],
                lp["up_proj"], lp["down_proj"], sin, cos, kp, vp,
                block_tables, seq_lens, scales, cfg.rms_norm_eps)
        else:
            x, k_new, v_new = attn_fn(
                x, lp["input_norm"].astype(x.dtype), lp["q_proj"],
                lp["k_proj"], lp["v_proj"], lp["o_proj"], sin, cos, kp,
                vp, block_tables, seq_lens, scales, cfg.rms_norm_eps)
        if scales is None:
            kp, vp = write_to_pool(kp, vp, block_tables, seq_lens,
                                   k_new.astype(kp.dtype),
                                   v_new.astype(vp.dtype))
        else:
            kp, vp = write_to_pool_quant(kp, vp, block_tables, seq_lens,
                                         k_new, v_new, ksc, vsc)
        if block_fn is None:
            x = mlp_fn(x, lp["post_norm"].astype(x.dtype),
                       lp["gate_proj"], lp["up_proj"], lp["down_proj"],
                       cfg.rms_norm_eps)
        return x, (kp, vp)

    scan_xs = (params["layers"], k_pools, v_pools) if kv_scales is None \
        else (params["layers"], k_pools, v_pools) + tuple(kv_scales)
    x, (k_pools, v_pools) = jax.lax.scan(layer, x, scan_xs)
    x = fused_rms_norm(x[:, None], params["final_norm"].astype(x.dtype),
                       cfg.rms_norm_eps)[:, 0]
    head = params.get("lm_head")
    if head is None:
        head = params["embed_tokens"].T
    return x @ head, k_pools, v_pools


def _decode_variant_name(cfg, B, BS, MB, pool_dtype, quant, fused,
                         wq=None, tp=1):
    """The kernel variant one decode step's trace would select — a
    single attribution string for the decode_step timeline events
    (mirroring the prefill chunk's ``variant`` stamp): "pallas_block"
    (single-launch megakernel), "pallas_fused" (two-stage megakernels)
    or "unfused" (the building-block composition)."""
    if not fused:
        return "unfused"
    from ..ops.pallas.fused_decode_block import (decode_meta,
                                                 resolve_decode_step)
    meta = decode_meta(cfg, B=B, BS=BS, MB=MB, pool_dtype=pool_dtype,
                       quant=quant, tp=tp, weight_dtype=wq)
    block_fn, _, _, names = resolve_decode_step(meta, fused)
    return names["block"] if block_fn is not None else names["attn"]


_FUSED_PREFILL_CACHE: Dict = {}


def _suffix_prefill_runner(cfg, P, MB, mode):
    """Jitted pool-direct fused suffix prefill for the prefix-store
    path: one sequence's un-cached suffix (exact length ``P`` — no
    bucket padding here, so ``n_valid == P``) through
    :func:`_fused_prefill_forward`, pools donated so the persistent
    store's pools update in place. Cached per (cfg values, suffix
    length, table width, mode, prefill route)."""
    ck = (dataclasses.astuple(cfg), P, MB, mode, _prefill_route(mode))
    cached = _cache_get(_FUSED_PREFILL_CACHE, ck)
    if cached is not None:
        return cached

    @functools.partial(jax.jit, donate_argnums=(4, 5))
    def run(params, toks, pos0, table, k_pools, v_pools, wtable):
        logits, k_pools, v_pools = _fused_prefill_forward(
            params, toks, cfg, k_pools, v_pools, table, wtable, pos0,
            jnp.int32(P), kv_scales=None, mode=mode)
        return logits[P - 1], k_pools, v_pools

    if len(_FUSED_PREFILL_CACHE) > 16:
        _FUSED_PREFILL_CACHE.pop(next(iter(_FUSED_PREFILL_CACHE)))
    _FUSED_PREFILL_CACHE[ck] = run
    return run


_TP_PREFILL_CACHE: Dict = {}


def _tp_prefill_runner(cfg, sm, B, S, T):
    """Jitted tensor-parallel prefill for generate_paged: builds the
    LOCAL dense cache inside the per-shard body (KV_loc heads) and runs
    the tensor-parallel ``cached_forward`` mirror. Cached per
    (cfg values, geometry, mesh route) like the chunk runner."""
    import dataclasses as _dc
    from ..core.jax_compat import shard_map_norep
    from .tp import _tp_cached_forward

    ck = (_dc.astuple(cfg), B, S, T, _mesh_route(sm))
    cached = _cache_get(_TP_PREFILL_CACHE, ck)
    if cached is not None:
        return cached
    L, KV, hd = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                 cfg.head_dim)
    KV_loc = KV // sm.tp
    rep = sm.replicated
    cache_spec = sm.pool_spec      # [L, B, T, KV, hd]: axis 3 again

    def fwd(params, toks):
        shape = (L, B, T, KV_loc, hd)
        kc, vc = jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype)
        return _tp_cached_forward(params, toks, cfg, kc, vc, 0,
                                  axis=sm.axis,
                                  collective=sm.collective)

    fn = jax.jit(shard_map_norep(fwd, sm.mesh,
                                 (sm.param_specs(cfg), rep),
                                 (rep, cache_spec, cache_spec)))
    if len(_TP_PREFILL_CACHE) > 16:
        _TP_PREFILL_CACHE.pop(next(iter(_TP_PREFILL_CACHE)))
    _TP_PREFILL_CACHE[ck] = fn
    return fn


def generate_paged(params: Dict, input_ids, cfg: _llama.LlamaConfig,
                   gen: Optional[GenerationConfig] = None,
                   block_size: int = 16, seed: int = 0,
                   cache_dtype=None, prefix_cache=None,
                   observability=None, fused_decode=None, mesh=None,
                   fused_prefill=None, weight_quant=None):
    """vLLM-style serving loop over a paged KV cache.

    ``cache_dtype="int8"``: static per-head cache quantization
    (reference block_attn.h int8 cache mode) — KV pools take half the
    HBM, so the same footprint serves 2x the batch; scales calibrate
    from the prefill KV.

    ``prefix_cache``: opt-in ``PagedKVCacheStore``
    (inference/prefix_cache.py) whose pools/radix tree persist across
    calls — each sequence longest-prefix-matches its prompt against
    previously generated sequences and prefills only the un-cached
    suffix. bf16/f32 caches only (the per-call int8 recalibration is
    incompatible with pages that outlive the call, so int8 cleanly opts
    out here; the ServingEngine's static-scale int8 mode does share).

    Prefill runs through the dense-cache path, the dense cache is repacked
    into block pools, then each decode step is one jitted program using
    the Pallas paged-attention kernel (block-table-driven page streaming).
    The host owns page allocation (BlockManager) between steps — the
    reference's AnalysisPredictor does the same bookkeeping around
    block_multihead_attention.

    ``observability``: an optional ``paddle_tpu.observability
    .Observability`` harness. When given, the call records host-side
    phase timings (prefill dispatch, per-chunk decode dispatch) into
    its timeline/histograms and samples pool gauges — purely
    observational: no extra device syncs, identical outputs.

    ``fused_decode``: route each decode block through the fused
    decode-block kernels (ops/pallas/fused_decode_block.py). None reads
    FLAGS_fused_decode (default ON); dispatch picks the Pallas
    megakernels where supported and the bit-identical unfused
    composition elsewhere. "pallas"/"ref" force a variant.

    ``fused_prefill``: route the PREFIX-STORE suffix prefill through
    the fused prefill-block kernels (ops/pallas/fused_prefill_block.py)
    where dispatch supports them — the suffix runs pool-direct (no
    dense gather/scatter) with the warm prefix pages read as paged
    history. None reads FLAGS_fused_prefill (default ON); the unfused
    chunk composition is the bit-identical fallback everywhere
    dispatch rejects. The COLD path's one-shot dense prefill (which
    repacks into pools afterwards) is not a chunked program and is
    unaffected by this knob.

    ``mesh``: a ``ServingMesh`` (or 1-D jax Mesh / int tp) — prefill
    and every decode chunk run tensor-parallel over the head axis
    (inference/tp.py): pools and projections shard, the residual
    stream and logits stay replicated, still ONE jitted program per
    chunk size. collective="gather" is bit-identical to mesh=None;
    the default "psum" placement is roundoff-parity (documented).

    ``weight_quant``: "int8"/"int4" — per-channel weight quantization
    on the decode + prefill hot paths (quantization/ptq.py). A plain
    fp tree is quantized in ONE shot on the way in (host-side absmax);
    an already-quantized tree (``ptq.quantize_weights``, e.g. with
    activation-aware clipping) rides as-is and None adopts its mode.
    Where the fused kernels dispatch, int8/int4 tiles stream through
    VMEM and dequantize in-register; everywhere else the unfused route
    is dequantize-then-matmul by construction.
    """
    import time as _time

    import numpy as np
    from ..ops.paged_attention import BlockManager
    from ..quantization.ptq import ensure_quantized
    from .tp import normalize_mesh

    gen = gen or GenerationConfig()
    if observability is True:      # mirror ServingEngine's normalization
        from ..observability import Observability
        observability = Observability()
    fused = _fused_mode(fused_decode)
    sm = normalize_mesh(mesh)
    params, wq_mode = ensure_quantized(params, weight_quant)
    if wq_mode is not None and sm is not None:
        raise ValueError(
            "generate_paged(weight_quant=...) does not take a mesh: "
            "sharding quantized weight trees (packed int4 + per-channel"
            " scales) over tp > 1 is named headroom — run quantized "
            "serving single-device, or use ServingEngine with tp=1 "
            "groups")
    if sm is not None:
        ok, reason = sm.supports(cfg)
        if not ok:
            raise ValueError(f"generate_paged(mesh=...): {reason}")
        if prefix_cache is not None:
            raise NotImplementedError(
                "generate_paged(prefix_cache=...) does not take a mesh:"
                " the persistent store owns single-device pools that "
                "outlive the call. Use ServingEngine(mesh=..., "
                "prefix_cache=True) for sharded prefix sharing")
    if prefix_cache is not None:
        return _generate_paged_prefix(
            params, input_ids, cfg, gen, block_size, seed, cache_dtype,
            prefix_cache, observability, fused=fused,
            fused_prefill=_fused_prefill_mode(fused_prefill),
            wq=wq_mode)
    obs = observability or None
    B, S = input_ids.shape
    T = S + gen.max_new_tokens
    if T > cfg.max_position_embeddings:
        raise ValueError(
            f"prompt+max_new_tokens = {T} exceeds max_position_embeddings "
            f"= {cfg.max_position_embeddings} (rope table bound)")
    L, KV, hd = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                 cfg.head_dim)
    BS = block_size
    MB = -(-T // BS)
    num_blocks = B * MB + 1

    # prefill with the dense cache, then repack into pools
    t0 = _time.perf_counter() if obs is not None else 0.0
    if sm is None:
        k_cache, v_cache = init_cache(cfg, B, T)
        logits, k_cache, v_cache = cached_forward(
            params, input_ids, cfg, k_cache, v_cache, 0)
    else:
        # the dense cache is built LOCAL inside the sharded program;
        # the repack below then runs eagerly on the sharded arrays
        # (page axis unsharded — no collectives)
        params = sm.shard(params, sm.param_specs(cfg))
        logits, k_cache, v_cache = _tp_prefill_runner(cfg, sm, B, S, T)(
            params, jnp.asarray(input_ids))
    if obs is not None:
        # host dispatch time (device completes async; forcing it here
        # would add a sync the serving path is asserted not to have)
        dur = (_time.perf_counter() - t0) * 1e3
        obs.hist("prefill_chunk_ms").observe(dur)
        obs.timeline.record("prefill_chunk", dur_ms=dur, pos0=0,
                            n=int(B * S), bucket=int(S))

    mgr = BlockManager(num_blocks, BS, MB)
    for sid in range(B):
        # allocate the whole generation upfront: the jitted step uses a
        # static table, and unallocated slots would default to page 0 and
        # collide across sequences
        mgr.allocate(sid, T)
    tables = mgr.table_array(range(B))

    pool_shape = (L, num_blocks, BS, KV, hd)
    k_pools = jnp.zeros(pool_shape, k_cache.dtype)
    v_pools = jnp.zeros(pool_shape, v_cache.dtype)
    if sm is not None:
        k_pools = sm.shard(k_pools, sm.pool_spec)
        v_pools = sm.shard(v_pools, sm.pool_spec)
    # dense [L, B, T, KV, hd] -> pages
    pad = MB * BS - T
    kc = jnp.pad(k_cache, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
    vc = jnp.pad(v_cache, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
    kc = kc.reshape(L, B, MB, BS, KV, hd)
    vc = vc.reshape(L, B, MB, BS, KV, hd)
    flat_tables = jnp.asarray(tables.reshape(-1), jnp.int32)
    k_pools = k_pools.at[:, flat_tables].set(
        kc.reshape(L, B * MB, BS, KV, hd))
    v_pools = v_pools.at[:, flat_tables].set(
        vc.reshape(L, B * MB, BS, KV, hd))

    kv_scales = None
    if cache_dtype in ("int8", jnp.int8):
        # static per-layer-per-head scales from the prefill KV (the
        # reference's static cachekv-quant calibration point); pools
        # shrink 2x and decode dequants per head in the gather consumer
        from ..ops.paged_attention import quantize_pools
        k_pools, v_pools, k_sc, v_sc = jax.vmap(quantize_pools)(
            k_pools, v_pools)
        kv_scales = (k_sc, v_sc)
    elif cache_dtype not in (None, "bfloat16", "float32",
                             jnp.bfloat16, jnp.float32):
        raise ValueError(f"cache_dtype must be bfloat16|float32|int8, "
                         f"got {cache_dtype!r}")

    # Chunked decode: pages for the whole generation are allocated
    # upfront (static tables), so no host bookkeeping is needed between
    # steps — run chunk_size decode steps as ONE jitted lax.scan
    # (sampling included) per host dispatch. The previous per-token host
    # loop paid eager sampling ops plus a BLOCKING np.asarray d2h per
    # token — ~1s/token through the axon tunnel. Between chunks the host
    # can still reclaim finished sequences (the vLLM-style scheduling
    # point the reference's AnalysisPredictor has). The jitted chunk
    # runner is cached per (config values, sampling knobs) like
    # generate()'s — shapes and the static n key jit's own cache.
    chunk_fn = _paged_chunk_runner(cfg, gen, quant=kv_scales is not None,
                                   fused=fused, sm=sm, wq=wq_mode)

    key = _key_for(seed)
    tok = sample_token(logits[:, -1], key, gen)
    done = tok == gen.eos_token_id
    chunks = [tok[:, None]]
    seq_lens = jnp.full((B,), S, jnp.int32)
    bt = jnp.asarray(tables, jnp.int32)
    chunk = max(1, int(os.environ.get("PADDLE_TPU_DECODE_CHUNK", "32")))
    left = gen.max_new_tokens - 1
    if obs is not None:
        obs.sample_gauges(_time.perf_counter(), {
            "pages_free": len(mgr.free),
            "pages_in_use": num_blocks - len(mgr.free)})
        dv = _decode_variant_name(cfg, B, BS, MB, k_pools.dtype,
                                  kv_scales is not None, fused,
                                  wq=wq_mode,
                                  tp=(sm.tp if sm is not None else 1))
    while left > 0:
        n = min(chunk, left)
        t0 = _time.perf_counter() if obs is not None else 0.0
        toks, tok, key, done, seq_lens, k_pools, v_pools = chunk_fn(
            n, params, tok, key, done, k_pools, v_pools, seq_lens, bt,
            kv_scales)
        if obs is not None:
            dur = (_time.perf_counter() - t0) * 1e3
            obs.hist("decode_step_ms").observe(dur / n)
            obs.timeline.record("decode_step", dur_ms=dur,
                                live_slots=B, tokens=int(n * B),
                                decode_variant=dv)
        chunks.append(toks.transpose(1, 0))  # [n, B] -> [B, n]
        left -= n
    toks = jnp.concatenate(chunks, axis=1)
    return jnp.concatenate([input_ids, toks], axis=1)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _scatter_prefill_pages(kp, vp, wtable, kc, vc):
    """Scatter one sequence's dense prefill view back into the pools
    through its WRITE table. Donation keeps the pools in place — an
    eager ``.at[].set`` here would materialize two whole-pool copies
    per sequence per call."""
    L, _, BS, KV, hd = kp.shape
    MB = wtable.shape[0]
    kc = kc.reshape(L, MB, BS, KV, hd).astype(kp.dtype)
    vc = vc.reshape(L, MB, BS, KV, hd).astype(vp.dtype)
    return kp.at[:, wtable].set(kc), vp.at[:, wtable].set(vc)


def _generate_paged_prefix(params, input_ids, cfg, gen, block_size,
                           seed, cache_dtype, store,
                           observability=None, fused=False,
                           fused_prefill=False, wq=None):
    """``generate_paged`` over a persistent ``PagedKVCacheStore``.

    Admission longest-prefix-matches each prompt against the store's
    radix tree (full pages shared in place, partial tail via COW fork)
    and prefills only the un-cached suffix — one ``cached_forward``
    over a dense gathered view per sequence, because each sequence has
    its own start position. The scatter back to the pools goes through
    a write table whose shared entries are redirected to the scratch
    page, so shared pages are never written. Decode reuses the cold
    path's jitted chunk runner unchanged; finished sequences are
    indexed back into the tree (trimmed at the first EOS) instead of
    freed."""
    import numpy as np

    if cache_dtype not in (None, "bfloat16", "float32",
                           jnp.bfloat16, jnp.float32):
        raise ValueError(
            "generate_paged(prefix_cache=...) supports bf16/f32 caches "
            f"only, got cache_dtype={cache_dtype!r}: the int8 path "
            "recalibrates per call, which cannot share pages that "
            "outlive the call (use ServingEngine's static-scale int8)")
    if int(block_size) != store.block_size:
        raise ValueError(
            f"block_size {block_size} != prefix store block_size "
            f"{store.block_size}")
    B, S = input_ids.shape
    T = S + gen.max_new_tokens
    if T > cfg.max_position_embeddings:
        raise ValueError(
            f"prompt+max_new_tokens = {T} exceeds max_position_embeddings "
            f"= {cfg.max_position_embeddings} (rope table bound)")
    L, KV, hd = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                 cfg.head_dim)
    BS = store.block_size
    MB = -(-T // BS)
    mgr, cache = store.mgr, store.cache
    prompts = np.asarray(input_ids, np.int32)

    seq_ids, matched_ns, shared_ns = [], [], []
    tables = np.zeros((B, MB), np.int32)
    for b in range(B):
        sid = store.next_seq_id
        store.next_seq_id += 1
        got = cache.acquire(prompts[b], S - 1, MB)
        if got is None:
            for done_sid in seq_ids:
                mgr.release(done_sid)
            raise RuntimeError(
                f"prefix store pool exhausted: batch needs up to "
                f"{B * MB} pages, store has {store.num_blocks - 1}")
        pages, matched, shared = got
        mgr.attach(sid, pages, owned=True)
        t = mgr.allocate(sid, T)
        tables[b, :len(t)] = t
        seq_ids.append(sid)
        matched_ns.append(matched)
        shared_ns.append(shared)

    import time as _time

    obs = observability or None
    if obs is not None:
        obs.sample_gauges(_time.perf_counter(), {
            "pages_free": len(mgr.free),
            "pages_in_use": store.num_blocks - len(mgr.free),
            "prefix_tree_pages": cache.cached_pages})

    # suffix prefill, one sequence at a time (per-sequence pos0).
    # With ``fused_prefill`` and dispatch selecting the Pallas pair,
    # the suffix runs POOL-DIRECT (the warm prefix pages are the paged
    # history, the suffix K/V scatter through the write table) —
    # otherwise the verbatim gather/cached_forward/scatter composition.
    from ..ops.pallas.fused_prefill_block import (prefill_fused_selected,
                                                  prefill_meta)
    logits_last = []
    for b in range(B):
        M = matched_ns[b]
        wt = tables[b].copy()
        wt[:shared_ns[b]] = 0              # never write a shared page
        if obs is not None:
            t0 = _time.perf_counter()
        use_fused = fused_prefill and prefill_fused_selected(
            prefill_meta(cfg, S - M, BS, MB, store.k_pools.dtype,
                         False, weight_dtype=wq), fused_prefill)
        if use_fused:
            run = _suffix_prefill_runner(cfg, S - M, MB, fused_prefill)
            lg_last, store.k_pools, store.v_pools = run(
                params, jnp.asarray(prompts[b, M:]),
                jnp.asarray(M, jnp.int32),
                jnp.asarray(tables[b], jnp.int32),
                store.k_pools, store.v_pools,
                jnp.asarray(wt, jnp.int32))
            logits_last.append(lg_last[None])
        else:
            tb = jnp.asarray(tables[b], jnp.int32)
            kc = jnp.take(store.k_pools, tb, axis=1) \
                .reshape(L, 1, MB * BS, KV, hd)
            vc = jnp.take(store.v_pools, tb, axis=1) \
                .reshape(L, 1, MB * BS, KV, hd)
            lg, kc, vc = cached_forward(
                params, jnp.asarray(prompts[b:b + 1, M:]), cfg, kc, vc,
                M)
            store.k_pools, store.v_pools = _scatter_prefill_pages(
                store.k_pools, store.v_pools,
                jnp.asarray(wt, jnp.int32), kc, vc)
            logits_last.append(lg[:, -1])
        if obs is not None:
            dur = (_time.perf_counter() - t0) * 1e3
            obs.hist("prefill_chunk_ms").observe(dur)
            obs.timeline.record("prefill_chunk", req_id=seq_ids[b],
                                dur_ms=dur, pos0=M, n=int(S - M),
                                matched_tokens=M,
                                variant=("pallas" if use_fused
                                         else "ref"))

    key = _key_for(seed)
    tok = sample_token(jnp.concatenate(logits_last, axis=0), key, gen)
    done = tok == gen.eos_token_id
    chunks = [tok[:, None]]
    seq_lens = jnp.full((B,), S, jnp.int32)
    bt = jnp.asarray(tables, jnp.int32)
    chunk_fn = _paged_chunk_runner(cfg, gen, quant=False, fused=fused,
                                   wq=wq)
    k_pools, v_pools = store.k_pools, store.v_pools
    chunk = max(1, int(os.environ.get("PADDLE_TPU_DECODE_CHUNK", "32")))
    left = gen.max_new_tokens - 1
    if obs is not None:
        dv = _decode_variant_name(cfg, B, BS, MB, k_pools.dtype, False,
                                  fused, wq=wq)
    while left > 0:
        n = min(chunk, left)
        if obs is not None:
            t0 = _time.perf_counter()
        toks, tok, key, done, seq_lens, k_pools, v_pools = chunk_fn(
            n, params, tok, key, done, k_pools, v_pools, seq_lens, bt,
            None)
        if obs is not None:
            dur = (_time.perf_counter() - t0) * 1e3
            obs.hist("decode_step_ms").observe(dur / n)
            obs.timeline.record("decode_step", dur_ms=dur,
                                live_slots=B, tokens=int(n * B),
                                decode_variant=dv)
        chunks.append(toks.transpose(1, 0))
        left -= n
    store.k_pools, store.v_pools = k_pools, v_pools
    out = jnp.concatenate(chunks, axis=1)            # [B, N]

    out_np = np.asarray(out)
    for b in range(B):
        # KV is valid for prompt + N-1 generated tokens (the last one's
        # KV was never written); forced-eos padding after the first EOS
        # is not meaningful traffic, so the index stops there
        valid = gen.max_new_tokens - 1
        if gen.eos_token_id >= 0:
            hits = np.nonzero(out_np[b] == gen.eos_token_id)[0]
            if hits.size:
                valid = min(valid, int(hits[0]) + 1)
        seq = np.concatenate([prompts[b], out_np[b, :valid]])
        cache.insert(seq, list(mgr.tables.get(seq_ids[b], ())))
        mgr.release(seq_ids[b])
    return jnp.concatenate([jnp.asarray(input_ids), out], axis=1)
