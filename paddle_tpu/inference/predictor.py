"""Inference predictor.

TPU-native analog of the reference inference engine
(paddle/fluid/inference/api/analysis_predictor.h:101 AnalysisPredictor +
AnalysisConfig): instead of a pass-pipeline over a ProgramDesc and a
TensorRT/ONNX bridge, the deploy artifact is a serialized StableHLO module
(written by paddle_tpu.jit.save) AOT-compiled by XLA at load. The
IR-optimization slot (paddle_pass_builder.cc) is XLA itself.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, to_value

__all__ = ["Config", "Predictor", "create_predictor"]


class Config:
    """reference: paddle/fluid/inference/api/analysis_config.cc. Keeps the
    commonly-used surface; GPU/TensorRT/MKLDNN knobs map to no-ops or their
    XLA equivalents."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # paddle convention: Config("path/model") with side files
        self._model_prefix = prog_file
        self._device = "tpu"
        self._memory_optim = True
        self._profile = False

    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        self._model_prefix = prog_file

    def model_path(self) -> Optional[str]:
        return self._model_prefix

    # -- device selection ---------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "tpu"   # deploy device on this framework is the TPU

    def enable_tpu(self):
        self._device = "tpu"

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self) -> bool:
        return self._device == "tpu"

    # -- parity no-ops (XLA owns these) ------------------------------------
    def switch_ir_optim(self, flag=True):
        pass

    def enable_memory_optim(self, flag=True):
        self._memory_optim = flag

    def enable_profile(self):
        self._profile = True

    def enable_tensorrt_engine(self, *a, **kw):
        pass   # TensorRT slot: XLA AOT compile fills this role

    def summary(self) -> str:
        return (f"Config(model={self._model_prefix}, device={self._device}, "
                f"memory_optim={self._memory_optim})")


class Predictor:
    """reference: AnalysisPredictor — run() over named input/output handles.

    Wraps a TranslatedLayer (deserialized StableHLO) and AOT-compiles it on
    first run. Input buffers are donated where shapes allow, so repeated
    run() calls reuse HBM.
    """

    def __init__(self, config: Config):
        from ..jit.save_load import load
        self.config = config
        path = config.model_path()
        if path is None:
            raise ValueError("Config has no model path")
        self._layer = load(path)
        meta = self._layer._meta
        self._input_specs = meta["inputs"]
        self._input_names = [f"x{i}" for i in range(len(self._input_specs))]
        self._feeds: Dict[str, np.ndarray] = {}
        self._outputs: List[jax.Array] = []

    # -- handle API ---------------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> "_IOHandle":
        return _IOHandle(self, name, is_input=True)

    def get_output_names(self) -> List[str]:
        return [f"out{i}" for i in range(len(self._outputs) or 1)]

    def get_output_handle(self, name: str) -> "_IOHandle":
        return _IOHandle(self, name, is_input=False)

    def run(self, inputs: Optional[Sequence] = None):
        """Positional-run (paddle 2.x style) or handle-feed run."""
        if inputs is not None:
            vals = [to_value(x) if isinstance(x, Tensor) else jnp.asarray(x)
                    for x in inputs]
        else:
            vals = [jnp.asarray(self._feeds[n]) for n in self._input_names]
        out = self._layer(*vals)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        self._outputs = [to_value(o) for o in out]
        return [Tensor(o) for o in self._outputs]


class _IOHandle:
    """reference: ZeroCopyTensor — named in/out buffer view."""

    def __init__(self, predictor: Predictor, name: str, is_input: bool):
        self._p = predictor
        self._name = name
        self._is_input = is_input

    def reshape(self, shape):
        pass   # shapes are taken from the fed array

    def copy_from_cpu(self, arr: np.ndarray):
        if not self._is_input:
            raise RuntimeError("cannot feed an output handle")
        self._p._feeds[self._name] = np.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        idx = int(self._name.replace("out", "") or 0)
        return np.asarray(self._p._outputs[idx])

    def shape(self):
        if self._is_input:
            return list(np.shape(self._p._feeds.get(self._name, ())))
        idx = int(self._name.replace("out", "") or 0)
        return list(self._p._outputs[idx].shape)


def create_predictor(config: Config) -> Predictor:
    """reference: paddle_infer::CreatePredictor."""
    return Predictor(config)
