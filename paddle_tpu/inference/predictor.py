"""Inference predictor.

TPU-native analog of the reference inference engine
(paddle/fluid/inference/api/analysis_predictor.h:101 AnalysisPredictor +
AnalysisConfig): instead of a pass-pipeline over a ProgramDesc and a
TensorRT/ONNX bridge, the deploy artifact is a serialized StableHLO module
(written by paddle_tpu.jit.save) AOT-compiled by XLA at load. The
IR-optimization slot (paddle_pass_builder.cc) is XLA itself.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, to_value

__all__ = ["Config", "Predictor", "create_predictor"]


class Config:
    """reference: paddle/fluid/inference/api/analysis_config.cc. Keeps the
    commonly-used surface; GPU/TensorRT/MKLDNN knobs map to no-ops or their
    XLA equivalents."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # paddle convention: Config("path/model") with side files
        self._model_prefix = prog_file
        self._device = "tpu"
        self._memory_optim = True
        self._profile = False
        # AnalysisConfig::SetOptimCacheDir analog: where serialized XLA
        # executables live. None = "<model>.xcache" next to the model.
        self._optim_cache_dir: Optional[str] = None
        self._aot_cache = True

    def set_optim_cache_dir(self, opt_cache_dir: str):
        """reference: analysis_config.cc SetOptimCacheDir — here the cache
        holds serialized XLA executables, so a process restart skips
        compilation entirely."""
        self._optim_cache_dir = opt_cache_dir

    def enable_aot_executable_cache(self, flag=True):
        self._aot_cache = flag

    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        self._model_prefix = prog_file

    def model_path(self) -> Optional[str]:
        return self._model_prefix

    # -- device selection ---------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "tpu"   # deploy device on this framework is the TPU

    def enable_tpu(self):
        self._device = "tpu"

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self) -> bool:
        return self._device == "tpu"

    # -- parity no-ops (XLA owns these) ------------------------------------
    # The reference's AnalysisConfig drives a hand-built pass pipeline
    # (paddle/fluid/inference/api/paddle_pass_builder.cc): IR fusion
    # passes, TensorRT subgraph capture, memory reuse planning. Under
    # this framework the whole model is ONE XLA program, and XLA's own
    # pipeline does those jobs (fusion, layout assignment, buffer
    # sharing, AOT executable caching) — so these knobs have nothing to
    # configure. They warn once instead of silently no-oping so ported
    # serving code gets a signal.
    @staticmethod
    def _warn_noop(knob, why):
        import warnings
        warnings.warn(
            f"inference.Config.{knob} has no effect on paddle_tpu: {why}",
            stacklevel=3)

    def switch_ir_optim(self, flag=True):
        self._warn_noop("switch_ir_optim",
                        "XLA always runs its optimization pipeline")

    def enable_memory_optim(self, flag=True):
        self._memory_optim = flag
        self._warn_noop("enable_memory_optim",
                        "XLA buffer assignment plans memory reuse")

    def enable_profile(self):
        self._profile = True

    def enable_tensorrt_engine(self, *a, **kw):
        self._warn_noop("enable_tensorrt_engine",
                        "the XLA AOT-compiled executable fills this role")

    def summary(self) -> str:
        return (f"Config(model={self._model_prefix}, device={self._device}, "
                f"memory_optim={self._memory_optim})")


class Predictor:
    """reference: AnalysisPredictor — run() over named input/output handles.

    Wraps a TranslatedLayer (deserialized StableHLO) and AOT-compiles it on
    first run. Input buffers are donated where shapes allow, so repeated
    run() calls reuse HBM.
    """

    def __init__(self, config: Config):
        from ..jit.save_load import load
        self.config = config
        path = config.model_path()
        if path is None:
            raise ValueError("Config has no model path")
        self._layer = load(path)
        meta = self._layer._meta
        self._input_specs = meta["inputs"]
        self._input_names = [f"x{i}" for i in range(len(self._input_specs))]
        self._feeds: Dict[str, np.ndarray] = {}
        self._outputs: List[jax.Array] = []
        self._exec_cache: Dict[tuple, object] = {}
        self._cache_dir = None
        if config._aot_cache:
            self._cache_dir = (config._optim_cache_dir
                               or path + ".xcache")
        # model identity for the cache key: a stale executable from an
        # older export must never be reused
        self._model_fingerprint = self._fingerprint(path)
        import hashlib
        self._model_path_key = hashlib.sha256(
            os.path.abspath(path).encode()).hexdigest()[:16]
        # observability: True when the LAST run() executed a deserialized
        # executable (restart-no-recompile verified by tests)
        self.last_run_from_cache = False

    def clone(self):
        """reference: AnalysisPredictor::Clone — a new predictor over the
        same model/config (the on-disk AOT executable cache is shared, so
        clones skip recompilation)."""
        return Predictor(self.config)

    @staticmethod
    def _fingerprint(path: str) -> str:
        import hashlib
        h = hashlib.sha256()
        for suffix in (".exported", ".pdiparams"):
            try:
                with open(path + suffix, "rb") as f:
                    h.update(f.read())
            except OSError:
                pass
        return h.hexdigest()[:16]

    @staticmethod
    def _sig(vals) -> tuple:
        return tuple((tuple(v.shape), str(v.dtype)) for v in vals)

    def _cache_file(self, sig) -> Optional[str]:
        if self._cache_dir is None:
            return None
        import hashlib
        dev = jax.devices()[0]
        # compilation configuration is part of the key: an executable
        # compiled under different XLA/JAX options must not be reused
        # (jax's own persistent cache hashes compile options the same way)
        compile_cfg = (os.environ.get("XLA_FLAGS", ""),
                       bool(jax.config.jax_enable_x64),
                       str(jax.config.jax_default_matmul_precision))
        key = hashlib.sha256(repr((
            jax.__version__, dev.platform,
            getattr(dev, "device_kind", ""), jax.device_count(),
            compile_cfg, sig)).encode()).hexdigest()[:32]
        # per-model-path subdirectory: two Predictors sharing one
        # set_optim_cache_dir must not evict each other's executables;
        # the content fingerprint stays in the filename so a re-export
        # at the same path is identifiable as stale
        return os.path.join(self._cache_dir, self._model_path_key,
                            f"{self._model_fingerprint}-{key}.pdexec")

    def _prune_stale(self):
        """Drop THIS model path's entries from previous exports (their
        content fingerprint no longer matches); best-effort, on cache
        miss. Other models' subdirectories are never touched. Legacy
        flat-layout entries with this model's fingerprint (pre-subdir
        cache versions) are cleaned up too."""
        sub = os.path.join(self._cache_dir, self._model_path_key)
        try:
            for name in os.listdir(sub):
                if name.endswith(".pdexec") and \
                        not name.startswith(self._model_fingerprint + "-"):
                    os.remove(os.path.join(sub, name))
        except OSError:
            pass
        try:
            for name in os.listdir(self._cache_dir):
                if name.endswith(".pdexec") and \
                        name.startswith(self._model_fingerprint + "-"):
                    os.remove(os.path.join(self._cache_dir, name))
        except OSError:
            pass

    def _invalidate(self, sig):
        self._exec_cache.pop(sig, None)
        fpath = self._cache_file(sig)
        if fpath:
            try:
                os.remove(fpath)
            except OSError:
                pass

    def _compile(self, vals):
        layer = self._layer

        def call(params, buffers, *xs):
            return layer._exported.call(params, buffers, *xs)

        return jax.jit(call).lower(layer._params, layer._buffers,
                                   *vals).compile()

    def _executable(self, vals):
        """AOT executable for this input signature: in-memory cache, then
        the serialized on-disk cache (restart skips compilation; reference
        analysis_predictor.h:101 keeps the optimized program the same
        way), then a fresh XLA compile that repopulates both."""
        sig = self._sig(vals)
        hit = self._exec_cache.get(sig)
        if hit is not None:
            return hit
        fpath = self._cache_file(sig)
        if fpath and os.path.exists(fpath):
            try:
                import pickle
                from jax.experimental import serialize_executable as se
                with open(fpath, "rb") as f:
                    ser, in_tree, out_tree = pickle.load(f)
                exe = se.deserialize_and_load(ser, in_tree, out_tree)
                self._exec_cache[sig] = (exe, True)
                return exe, True
            except Exception:
                pass  # stale/foreign cache entry: recompile below
        exe = self._compile(vals)
        if fpath:
            try:
                import pickle
                from jax.experimental import serialize_executable as se
                os.makedirs(os.path.dirname(fpath), exist_ok=True)
                self._prune_stale()
                tmp = fpath + f".tmp{os.getpid()}"
                with open(tmp, "wb") as f:
                    pickle.dump(se.serialize(exe), f)
                os.replace(tmp, fpath)
            except Exception:
                pass  # caching is best-effort; serving must not break
        self._exec_cache[sig] = (exe, False)
        return exe, False

    # -- handle API ---------------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> "_IOHandle":
        return _IOHandle(self, name, is_input=True)

    def get_output_names(self) -> List[str]:
        return [f"out{i}" for i in range(len(self._outputs) or 1)]

    def get_output_handle(self, name: str) -> "_IOHandle":
        return _IOHandle(self, name, is_input=False)

    def run(self, inputs: Optional[Sequence] = None):
        """Positional-run (paddle 2.x style) or handle-feed run."""
        if inputs is not None:
            vals = [to_value(x) if isinstance(x, Tensor) else jnp.asarray(x)
                    for x in inputs]
        else:
            vals = [jnp.asarray(self._feeds[n]) for n in self._input_names]
        exe, from_cache = self._executable(vals)
        try:
            out = exe(self._layer._params, self._layer._buffers, *vals)
            if from_cache:
                # dispatch is async: force any runtime failure of the
                # deserialized executable to surface INSIDE this try so
                # the recovery below can actually run
                jax.block_until_ready(out)
        except Exception:
            if not from_cache:
                raise
            # a deserialized executable can be incompatible with the live
            # device topology (e.g. different chip count) in ways only
            # execution reveals — recompile fresh and overwrite the entry
            self._invalidate(self._sig(vals))
            exe, from_cache = self._executable(vals)
            out = exe(self._layer._params, self._layer._buffers, *vals)
        self.last_run_from_cache = from_cache
        if not isinstance(out, (tuple, list)):
            out = (out,)
        self._outputs = [to_value(o) for o in jax.tree_util.tree_leaves(out)]
        return [Tensor(o) for o in self._outputs]


class _IOHandle:
    """reference: ZeroCopyTensor — named in/out buffer view."""

    def __init__(self, predictor: Predictor, name: str, is_input: bool):
        self._p = predictor
        self._name = name
        self._is_input = is_input

    def reshape(self, shape):
        pass   # shapes are taken from the fed array

    def copy_from_cpu(self, arr: np.ndarray):
        if not self._is_input:
            raise RuntimeError("cannot feed an output handle")
        self._p._feeds[self._name] = np.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        idx = int(self._name.replace("out", "") or 0)
        return np.asarray(self._p._outputs[idx])

    def shape(self):
        if self._is_input:
            return list(np.shape(self._p._feeds.get(self._name, ())))
        idx = int(self._name.replace("out", "") or 0)
        return list(self._p._outputs[idx].shape)


def create_predictor(config: Config) -> Predictor:
    """reference: paddle_infer::CreatePredictor."""
    return Predictor(config)
