"""Radix prefix cache: copy-on-write KV page sharing across requests.

Real serving traffic (system prompts, few-shot templates, multi-turn
chat) repeats long token prefixes, yet the continuous-batching engine
prefilled every request from scratch. This module turns the paged
``BlockManager`` from a per-request allocator into a CROSS-REQUEST
cache: a radix tree indexes token-id prefixes at PAGE granularity, and
each tree node owns one ref-counted physical page in the existing KV
pools.

Design (the TPU analog of vLLM's automatic prefix caching / SGLang's
RadixAttention, applied to the pools of ``ops.paged_attention``):

- FULL pages (``block_size`` tokens) are shared IN PLACE: a longest-
  prefix match at admission appends the matched physical pages directly
  to the request's block table (incref), and the request prefills only
  its un-cached suffix. Because matching full pages is page-aligned and
  capped at ``len(prompt) - 1`` tokens, every position a request ever
  writes (suffix prefill + decode appends) lands in a page it owns.
- The PARTIALLY-FILLED TAIL page of a cached sequence is never shared
  in place: it is handed out only as a COPY-ON-WRITE fork (fresh page +
  device copy), so a divergent continuation writes its own copy and can
  never corrupt the cached original.
- KV pages are position-causal (the KV at position i depends only on
  tokens <= i), so any PREFIX of a cached page's valid tokens is also
  valid — a tail node with j tokens serves any request matching the
  first c <= j of them.
- EVICTION is LRU over refcount-1 leaves (tree-only pages; a page
  shared with any live request has refcount >= 2 and is pinned),
  cascading upward as parents become leaves. It runs on demand through
  ``BlockManager.reclaim`` when the free list is dry, so a full pool
  degrades to per-request allocation instead of failing admission.
- HOST-RAM OFFLOAD TIER (``spill_pages``/``restore_pages`` supplied by
  the pool owner): instead of destroying warm pages, eviction SPILLS
  their bytes to host memory in fixed-width multi-page WINDOWS (one
  jitted window extract followed by ``device_put`` onto the host
  memory space — pinned where the backend offers it) and the nodes
  stay in the tree with ``page=None``. A later prefix hit on spilled
  nodes RESTORES the pages through the same machinery in the opposite
  direction (``device_put`` back + one donated window insert, whose
  device copy overlaps the suffix prefill chunk issued next),
  byte-identical to what was spilled —
  effective prefix-cache capacity becomes HBM + host RAM. A finished
  request whose pages re-cover a spilled node re-adopts its device
  pages directly (no device copy). ``host_budget_pages`` bounds the
  tier; past it the LRU childless spilled node is dropped for real.

The cache is pure host-side bookkeeping: the only device work it ever
issues is the one-page COW copy and the spill/restore pair (three
jitted programs, traced once each). Decode and prefill programs are
unchanged in shape and count — cache hits cause zero retraces.
"""
from __future__ import annotations

import heapq
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ops.paged_attention import BlockManager

__all__ = ["PrefixCache", "PagedKVCacheStore", "host_put"]

# resolved on first host_put PER PLATFORM (a process can host mixed
# TPU + CPU engines): platform -> memory kind ("" = numpy fallback)
_HOST_MEMORY_KIND: Dict[str, str] = {}


def host_put(x):
    """Move an array's bytes into HOST memory via ``jax.device_put`` —
    ``pinned_host`` where the backend offers it (TPU), the backend's
    unpinned host space otherwise (CPU PjRt), plain numpy as the last
    resort. The bytes are preserved exactly (raw copy, no cast), which
    is what makes the spill/restore byte-identity contract provable."""
    import jax
    dev = next(iter(x.devices()))
    kind = _HOST_MEMORY_KIND.get(dev.platform)
    if kind is None:
        for kind in ("pinned_host", "unpinned_host"):
            try:
                y = jax.device_put(
                    x, jax.sharding.SingleDeviceSharding(
                        dev, memory_kind=kind))
                _HOST_MEMORY_KIND[dev.platform] = kind
                return y
            except (ValueError, NotImplementedError):
                continue
        _HOST_MEMORY_KIND[dev.platform] = kind = ""
    if kind:
        try:
            return jax.device_put(
                x, jax.sharding.SingleDeviceSharding(
                    dev, memory_kind=kind))
        except (ValueError, NotImplementedError):
            pass    # degrade mid-eviction rather than crash admission
    return np.asarray(x)


class _Node:
    """One radix-tree node owning ONE physical KV page.

    ``tokens`` (a tuple of 1..block_size ids) are the tokens whose KV
    the page holds. A node with ``len(tokens) == block_size`` is a full
    page: shareable in place and extendable with children. A shorter
    node is a partial tail: leaf-only, handed out via COW fork, and
    upgradeable in place when a later insert extends it.

    With the offload tier a node is either RESIDENT (``page`` set,
    ``host`` None) or SPILLED (``page`` None, ``host`` holding the
    page's bytes in host memory); spilled nodes stay matchable and
    restore on demand."""

    __slots__ = ("tokens", "page", "children", "parent", "last_used",
                 "host")

    def __init__(self, tokens: Tuple[int, ...], page: Optional[int],
                 parent: Optional["_Node"]):
        self.tokens = tokens
        self.page = page
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.parent = parent
        self.last_used = 0
        self.host = None


def _common(a: Sequence[int], b: Sequence[int]) -> int:
    n = min(len(a), len(b))
    i = 0
    while i < n and a[i] == b[i]:
        i += 1
    return i


class PrefixCache:
    """Radix index over one ``BlockManager``'s pages.

    ``copy_page(src, dst)`` is supplied by the pool owner (ServingEngine
    or PagedKVCacheStore) and device-copies one physical page — the COW
    primitive. The cache installs itself as the manager's ``reclaim``
    callback so allocation pressure drives eviction.

    ``spill_pages(pages) -> payloads`` / ``restore_pages(payloads,
    dsts)`` (both supplied, or neither) enable the host-RAM offload
    tier: eviction spills instead of dropping, and a prefix hit on a
    spilled node restores before sharing — both move whole batches so
    the owner can window the transfers. ``host_budget_pages`` caps the
    tier (None = unbounded); past it the LRU childless spilled node
    dies."""

    def __init__(self, mgr: BlockManager, block_size: int,
                 copy_page: Callable[[int, int], None],
                 host_budget_pages: Optional[int] = None,
                 spill_pages: Optional[Callable] = None,
                 restore_pages: Optional[Callable] = None):
        if (spill_pages is None) != (restore_pages is None):
            raise ValueError("spill_pages and restore_pages come as a "
                             "pair: a tier that can spill but not "
                             "restore would silently drop warm KV")
        self.mgr = mgr
        self.bs = int(block_size)
        self.copy_page = copy_page
        # the batched offload pair (r17): spill_pages(pages) -> one
        # opaque per-page payload each; restore_pages(payloads, dsts)
        # — the pool owner moves whole batches in fixed-width
        # multi-page windows (serving.py's windowed handoff programs)
        self._spill_batch = spill_pages
        self._restore_batch = restore_pages
        self.host_budget = (None if host_budget_pages is None
                            else int(host_budget_pages))
        self.root = _Node((), None, None)
        self._tick = 0
        self._host_pages = 0
        # bumped on every structural change (insert/evict/spill/
        # restore/drop): the fleet router's tree-summary staleness check
        self.version = 0
        self.stats = {"hits": 0, "misses": 0, "tokens_skipped": 0,
                      "shared_pages": 0, "cow_forks": 0,
                      "evicted_pages": 0, "inserted_pages": 0,
                      "spilled_pages": 0, "restored_pages": 0,
                      "readopted_pages": 0, "host_evicted_pages": 0}
        mgr.reclaim = self.evict

    # -- introspection ------------------------------------------------
    def _walk(self):
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            yield n
            stack.extend(n.children.values())

    @property
    def cached_pages(self) -> int:
        """Device-RESIDENT tree pages (spilled nodes hold no page)."""
        return sum(1 for n in self._walk() if n.page is not None)

    @property
    def host_pages(self) -> int:
        """Pages currently living in the host tier."""
        return self._host_pages

    def evictable_count(self) -> int:
        """Pages reclaimable right now: nodes whose whole subtree is
        unpinned (refcount 1, i.e. tree-only — eviction is leaf-first,
        so a pinned descendant blocks its ancestors; a spilled node
        holds no page and pins nothing)."""
        def walk(n: _Node) -> Tuple[int, bool]:
            cnt, free_sub = 0, True
            for ch in n.children.values():
                c, f = walk(ch)
                cnt += c
                free_sub = free_sub and f
            if n is self.root:
                return cnt, False
            if n.page is None:
                return cnt, free_sub
            if free_sub and int(self.mgr.refcount[n.page]) == 1:
                return cnt + 1, True
            return cnt, False
        return walk(self.root)[0]

    def metrics(self) -> Dict:
        m = dict(self.stats)
        m["cached_pages"] = self.cached_pages
        m["evictable_pages"] = self.evictable_count()
        m["host_pages"] = self._host_pages
        return m

    def check(self, raise_on_violation: bool = True):
        """Structural + accounting invariant sweep over the radix tree
        and its BlockManager — the single definition shared by the
        lifecycle model checker (analysis/lifecycle.py) and the
        engines' opt-in per-step self-check
        (``PADDLE_TPU_CHECK_INVARIANTS=1``). Returns violation strings
        (empty = clean); raises when ``raise_on_violation``. Runs the
        manager's own check first, then the cross-structure checks only
        the tree can do:

        - node structure: ``parent.children`` keyed by the child's
          EXACT token tuple (the upgrade-in-place rekey contract),
          parent back-pointers consistent, token runs 1..block_size,
          partial tails (< block_size tokens) leaf-only;
        - residency: every non-root node is resident (page set, host
          None) XOR spilled (page None, host payload kept matchable);
          resident pages valid, never free-listed, refcount >= 1 (the
          tree's own reference), and distinct across nodes;
        - host tier: ``host_pages`` equals the spilled-node count,
          never exceeds the budget, and the accounting identity
          spilled == restored + readopted + host_evicted + host_pages
          stays closed;
        - refcount EQUALITY: every page's refcount equals its table
          references plus its tree references — no reference is ever
          leaked or double-counted anywhere in the serving stack.
        """
        problems = self.mgr.check(raise_on_violation=False)
        tree_refs: Dict[int, int] = {}
        n_spilled = 0
        for parent in [self.root] + list(self._walk()):
            for key, ch in parent.children.items():
                if key != ch.tokens:
                    problems.append(
                        f"child keyed {key} but holds tokens "
                        f"{ch.tokens} (rekey bug: keyed delete misses)")
                if ch.parent is not parent:
                    problems.append(
                        f"node {ch.tokens} parent pointer broken")
        for nd in self._walk():
            nt = len(nd.tokens)
            if not (1 <= nt <= self.bs):
                problems.append(
                    f"node has {nt} tokens (must be 1..{self.bs})")
            if nt < self.bs and nd.children:
                problems.append(
                    f"partial tail {nd.tokens} has children (partials "
                    "are COW-only leaves)")
            if (nd.page is None) == (nd.host is None):
                problems.append(
                    f"node {nd.tokens} is neither cleanly resident nor "
                    f"spilled (page={nd.page}, host set="
                    f"{nd.host is not None})")
            if nd.page is not None:
                if not (0 <= nd.page < self.mgr.num_blocks):
                    problems.append(
                        f"node {nd.tokens} holds invalid page {nd.page}")
                    continue
                tree_refs[nd.page] = tree_refs.get(nd.page, 0) + 1
                if tree_refs[nd.page] > 1:
                    problems.append(
                        f"page {nd.page} owned by two tree nodes")
                if int(self.mgr.refcount[nd.page]) < 1:
                    problems.append(
                        f"resident node {nd.tokens} page {nd.page} has "
                        f"refcount {int(self.mgr.refcount[nd.page])}")
            elif nd.host is not None:
                n_spilled += 1
        if n_spilled != self._host_pages:
            problems.append(
                f"host_pages counter {self._host_pages} != "
                f"{n_spilled} spilled nodes in the tree")
        if self.host_budget is not None \
                and self._host_pages > self.host_budget:
            problems.append(
                f"host tier over budget: {self._host_pages} > "
                f"{self.host_budget}")
        st = self.stats
        if st["spilled_pages"] != (st["restored_pages"]
                                   + st["readopted_pages"]
                                   + st["host_evicted_pages"]
                                   + self._host_pages):
            problems.append(
                "offload accounting broken: spilled "
                f"{st['spilled_pages']} != restored "
                f"{st['restored_pages']} + readopted "
                f"{st['readopted_pages']} + host_evicted "
                f"{st['host_evicted_pages']} + host {self._host_pages}")
        table_refs = np.zeros(self.mgr.num_blocks, np.int64)
        for table in self.mgr.tables.values():
            for p in table:
                if 0 <= p < self.mgr.num_blocks:
                    table_refs[p] += 1
        for p in range(self.mgr.num_blocks):
            expect = int(table_refs[p]) + tree_refs.get(p, 0)
            if int(self.mgr.refcount[p]) != expect:
                problems.append(
                    f"page {p} refcount {int(self.mgr.refcount[p])} != "
                    f"{int(table_refs[p])} table + "
                    f"{tree_refs.get(p, 0)} tree references")
        if problems and raise_on_violation:
            raise RuntimeError(
                "PrefixCache.check failed:\n  " + "\n  ".join(problems))
        return problems

    def summary(self) -> Dict[int, int]:
        """The fleet router's tree summary: ``{prefix_hash: n_tokens}``
        for every page-aligned cached path (resident AND spilled — a
        spilled node is still a warm hit; it restores on acquire).
        Hashes are over the token-id tuple from the root, so a router
        can test "does this replica hold the first k pages of this
        prompt" without holding the tree itself; ``version`` tells it
        when a cached summary went stale."""
        out: Dict[int, int] = {}

        def walk(node: _Node, toks: Tuple[int, ...]):
            for ch in node.children.values():
                if len(ch.tokens) != self.bs:
                    continue        # partial tails: page-aligned only
                t = toks + ch.tokens
                out[hash(t)] = len(t)
                walk(ch, t)
        walk(self.root, ())
        return out

    # -- lookup -------------------------------------------------------
    def _touch(self, node: Optional[_Node]):
        self._tick += 1
        while node is not None and node is not self.root:
            node.last_used = self._tick
            node = node.parent

    def match(self, tokens: Sequence[int]
              ) -> Tuple[List[_Node], Optional[_Node], int]:
        """Longest cached prefix of ``tokens``: (full_nodes, tail_node,
        tail_len). ``full_nodes`` are whole-page in-place matches;
        ``tail_len`` leading tokens of ``tail_node`` are additionally
        usable through a COW fork. Read-only (no refcount changes)."""
        toks = [int(t) for t in tokens]
        node, pos, full = self.root, 0, []
        while pos < len(toks):
            rem = toks[pos:pos + self.bs]
            best, best_c = None, 0
            for ch in node.children.values():
                c = _common(ch.tokens, rem)
                if c > best_c:
                    best, best_c = ch, c
            if best is None or best_c == 0:
                break
            if best_c == self.bs:          # whole page matched in place
                full.append(best)
                node = best
                pos += self.bs
                continue
            return full, best, best_c      # partial: COW-fork territory
        return full, None, 0

    # -- admission ----------------------------------------------------
    def acquire(self, tokens: Sequence[int], limit: int,
                total_pages: int):
        """Admission-side lookup with backpressure: match at most
        ``limit`` tokens (callers pass ``len(prompt) - 1`` so at least
        one suffix token always prefills and produces logits), pin the
        matched full pages, and check that free + evictable pages cover
        the request's remaining ``total_pages`` need. Returns ``None``
        (wait; nothing mutated) when they do not, else
        ``(pages, matched_tokens, n_shared)`` where every returned page
        carries exactly one reference owned by the caller — full pages
        a fresh share, the COW fork its allocation.

        Matched SPILLED nodes count toward the page need (each restore
        consumes one fresh pool page) and are restored — device_put
        back + single-page insert — only after the backpressure check
        passes, root-first and pinned as they land so a later restore's
        reclaim can never spill them straight back."""
        toks = [int(t) for t in tokens][:max(int(limit), 0)]
        full, tail, tail_len = self.match(toks)
        will_fork = tail is not None and tail_len > 0
        resident = [nd for nd in full if nd.page is not None]
        n_restore = len(full) - len(resident)
        if will_fork and tail.page is None:
            n_restore += 1
        # pin the matched RESIDENT path — including a resident fork
        # SOURCE — before counting evictables, so the backpressure
        # check can never count a page the allocation below will find
        # pinned (that mismatch would crash allocation instead of
        # waiting)
        for nd in resident:
            self.mgr.incref(nd.page)
        if will_fork and tail.page is not None:
            self.mgr.incref(tail.page)
        # fork + fresh suffix pages + one pool page per restore
        needed = total_pages - len(full) + n_restore
        if len(self.mgr.free) < needed and \
                len(self.mgr.free) + self.evictable_count() < needed:
            if will_fork and tail.page is not None:
                self.mgr.decref(tail.page)
            for nd in resident:
                self.mgr.decref(nd.page)
            return None
        spilled = [nd for nd in full if nd.page is None]
        fork_spilled = will_fork and tail.page is None
        if spilled or fork_spilled:
            # ONE batched restore, root-first (list order); the pin
            # below lands before any later caller's reclaim can run
            batch = spilled + ([tail] if fork_spilled else [])
            self._restore_nodes(batch)
            for nd in spilled:
                self.mgr.incref(nd.page)    # the caller's reference
            if fork_spilled:
                self.mgr.incref(tail.page)  # the fork-source pin
        pages = [nd.page for nd in full]
        matched = len(full) * self.bs
        if will_fork:
            dst = self.mgr.fork(tail.page)   # src ALSO pinned above, so
            self.copy_page(tail.page, dst)   # the pin spans the copy
            self.mgr.decref(tail.page)       # drop the outer pin
            pages.append(dst)
            matched += tail_len
            self.stats["cow_forks"] += 1
            self._touch(tail)
        elif full:
            self._touch(full[-1])
        self.stats["hits" if matched else "misses"] += 1
        self.stats["tokens_skipped"] += matched
        self.stats["shared_pages"] += len(full)
        return pages, matched, len(full)

    def _restore_nodes(self, nodes: List[_Node]):
        """Bring spilled nodes back on device: fresh pool pages (rc 1
        — the tree's reference) + ONE batched ``restore_pages``
        transfer when the owner supplied it (fixed-width multi-page
        windows whose donated insert is dispatched, not synced — the
        device copy overlaps the suffix prefill chunk issued next),
        the per-page callback otherwise. The allocations may reclaim;
        matched resident pages are pinned by then, restoring nodes
        hold no page, and the freshly-allocated destinations are not
        in the tree — so the reclaim can never touch the batch."""
        dsts = [self.mgr.alloc_page() for _ in nodes]
        self._restore_batch([nd.host for nd in nodes], dsts)
        for nd, dst in zip(nodes, dsts):
            nd.page = dst
            nd.host = None
            self._host_pages -= 1
            self.stats["restored_pages"] += 1
            self.version += 1

    # -- insertion ----------------------------------------------------
    def insert(self, tokens: Sequence[int], pages: Sequence[int]):
        """Index a finished sequence's pages under its token ids
        (``tokens`` must cover exactly the positions with valid KV).
        Walks the tree page by page: already-cached pages are left for
        the caller's ``release`` to drop, novel pages are adopted
        (incref — they survive the release), and a partial tail node is
        upgraded in place when the new page extends its tokens."""
        toks = [int(t) for t in tokens]
        node = self.root
        for i in range(0, len(toks), self.bs):
            pi = i // self.bs
            pt = tuple(toks[i:i + self.bs])
            if pi >= len(pages) or not pt:
                break
            page = pages[pi]
            best, best_c = None, 0
            for ch in node.children.values():
                c = _common(ch.tokens, pt)
                if c > best_c:
                    best, best_c = ch, c
            if best is not None and best_c == len(best.tokens) == len(pt):
                if best.page is None:
                    # a finished request re-covered a SPILLED node:
                    # re-adopt its device page directly — cheaper than
                    # a device restore, same bytes by position-causality
                    self.mgr.incref(page)
                    best.page = page
                    best.host = None
                    self._host_pages -= 1
                    self.stats["readopted_pages"] += 1
                    self.version += 1
                node = best                  # exact: already cached
                self._touch(node)
                continue
            if best is not None and best_c == len(best.tokens) < len(pt):
                # ours extends a partial tail: upgrade its page in place
                # (partial nodes are COW-only => refcount 1, no children).
                # parent.children is keyed by the node's tokens, so the
                # entry must be rekeyed or eviction's keyed delete misses
                old = best.page
                self.mgr.incref(page)
                del node.children[best.tokens]
                best.tokens = pt
                best.page = page
                if best.host is not None:
                    # a spilled tail re-materialized by the caller's
                    # longer page: the host copy is superseded —
                    # counted as a re-adoption so the tier's page
                    # accounting (spilled == restored + readopted +
                    # host_evicted + host_pages) stays closed
                    best.host = None
                    self._host_pages -= 1
                    self.stats["readopted_pages"] += 1
                node.children[pt] = best
                if old is not None:
                    self.mgr.decref(old)
                self.stats["inserted_pages"] += 1
                self.version += 1
                node = best
                self._touch(node)
                continue
            if best is not None and best_c == len(pt) <= len(best.tokens):
                self._touch(best)            # cached covers ours: drop
                break                        # (< bs tokens => last page)
            # novel or divergent-within-page: adopt as a sibling node
            self.mgr.incref(page)
            ch = _Node(pt, page, node)
            node.children[pt] = ch
            self.stats["inserted_pages"] += 1
            self.version += 1
            node = ch
            self._touch(node)

    # -- eviction -----------------------------------------------------
    def evict(self, n_pages: int) -> int:
        """Reclaim up to ``n_pages`` refcount-1 pages for the
        allocator, LRU-first. Without the offload tier the victim's
        node is dropped from the tree; with it the node SPILLS — bytes
        to host memory, node kept matchable. Pages shared with a live
        request (refcount >= 2) are never touched. Installed as the
        BlockManager's ``reclaim`` hook."""
        if self._spill_batch is not None:
            return self._evict_spill(n_pages)
        return self._evict_drop(n_pages)

    def _evict_drop(self, n_pages: int) -> int:
        """LRU-evict refcount-1 leaf pages, cascading to parents as
        they become childless (the pre-offload behavior)."""
        heap = [(nd.last_used, id(nd), nd) for nd in self._walk()
                if not nd.children
                and int(self.mgr.refcount[nd.page]) == 1]
        heapq.heapify(heap)
        freed = 0
        while heap and freed < n_pages:
            _, _, nd = heapq.heappop(heap)
            if nd.children or nd.parent is None:
                continue                      # stale heap entry
            if int(self.mgr.refcount[nd.page]) != 1:
                continue                      # pinned since collection
            parent = nd.parent
            del parent.children[nd.tokens]
            nd.parent = None
            self.mgr.decref(nd.page)          # 1 -> 0: back to the pool
            freed += 1
            self.stats["evicted_pages"] += 1
            self.version += 1
            if (parent is not self.root and not parent.children
                    and int(self.mgr.refcount[parent.page]) == 1):
                heapq.heappush(
                    heap, (parent.last_used, id(parent), parent))
        return freed

    def _evict_spill(self, n_pages: int) -> int:
        """Offload-tier eviction: spill the LRU resident leaf-of-the-
        resident-subtree (rc-1, no resident descendant — children spill
        before parents, so hot shared ancestors stay on device longest)
        to host memory; the node stays in the tree with ``page=None``
        and restores on the next prefix hit."""
        freed = 0
        while freed < n_pages:
            cands = self._resident_leaves()
            if not cands:
                break
            cands.sort(key=lambda nd: (nd.last_used, id(nd)))
            batch = cands[:n_pages - freed]
            self._spill_nodes(batch)    # one call spills the whole
            freed += len(batch)         # LRU layer (windowed transfer)
            # loop: spilling a layer of leaves may expose their parents
        return freed

    def _resident_leaves(self) -> List[_Node]:
        """Resident rc-1 nodes with no resident descendant — the
        spillable frontier."""
        out: List[_Node] = []

        def walk(n: _Node) -> bool:
            any_res = False
            for ch in n.children.values():
                any_res = walk(ch) or any_res
            res = n is not self.root and n.page is not None
            if (res and not any_res
                    and int(self.mgr.refcount[n.page]) == 1):
                out.append(n)
            return res or any_res
        walk(self.root)
        return out

    def _spill_nodes(self, nodes: List[_Node]):
        """Spill a batch of victim nodes: one batched transfer through
        the owner's ``spill_pages`` (fixed-width multi-page windows)."""
        pages = [nd.page for nd in nodes]
        payloads = self._spill_batch(pages)
        for nd, payload in zip(nodes, payloads):
            nd.host = payload
            self.mgr.decref(nd.page)    # 1 -> 0: back to the pool
            nd.page = None
            self._host_pages += 1
            self.stats["spilled_pages"] += 1
            self.version += 1
        self._enforce_host_budget()

    def _enforce_host_budget(self):
        """Past the host budget the LRU CHILDLESS spilled node dies for
        real (dropping a mid-tree node would orphan the descendants'
        token paths; leaf-first spill order makes the oldest spilled
        nodes childless in practice)."""
        while (self.host_budget is not None
               and self._host_pages > self.host_budget):
            cands = [nd for nd in self._walk()
                     if nd.page is None and nd.host is not None
                     and not nd.children]
            if not cands:
                break
            nd = min(cands, key=lambda n: (n.last_used, id(n)))
            del nd.parent.children[nd.tokens]
            nd.parent = None
            nd.host = None
            self._host_pages -= 1
            self.stats["host_evicted_pages"] += 1
            self.stats["evicted_pages"] += 1
            self.version += 1


def make_page_copier():
    """One jitted program copying physical page ``src`` -> ``dst`` in
    both pools ([L, N, BS, KV, hd]); donation keeps it in place. Pass
    src/dst as traced int32 scalars so distinct pages share the trace."""
    import jax

    def cp(kp, vp, src, dst):
        return (kp.at[:, dst].set(kp[:, src]),
                vp.at[:, dst].set(vp[:, src]))
    return jax.jit(cp, donate_argnums=(0, 1))


class PagedKVCacheStore:
    """Persistent pools + BlockManager + PrefixCache backing
    ``generate_paged(prefix_cache=...)``.

    ``generate_paged`` normally builds fresh pools per call, so nothing
    can be reused across calls; this store owns the pools instead and
    survives between calls, letting a later call skip prefill for any
    prompt prefix a previous call already computed. bf16/f32 only: the
    int8 path re-quantizes whole pools with per-call scales, which is
    incompatible with pages that outlive the call (the ServingEngine's
    int8 mode, with its engine-global static scales, does participate).
    """

    _SCRATCH_SEQ = -1

    def __init__(self, cfg, block_size: int = 16, num_blocks: int = 256,
                 dtype=None):
        import jax.numpy as jnp
        self.cfg = cfg
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        L, KV, hd = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                     cfg.head_dim)
        shape = (L, self.num_blocks, self.block_size, KV, hd)
        self.k_pools = jnp.zeros(shape, dtype or cfg.dtype)
        self.v_pools = jnp.zeros(shape, dtype or cfg.dtype)
        self.mgr = BlockManager(self.num_blocks, self.block_size,
                                self.num_blocks)
        # page 0 is scratch: padded block-table entries default there
        scratch = self.mgr.allocate(self._SCRATCH_SEQ, 1)
        assert scratch == [0], "scratch must be page 0"
        self._copy_fn = make_page_copier()
        self.cache = PrefixCache(self.mgr, self.block_size,
                                 copy_page=self._copy_page)
        self.next_seq_id = 0

    def _copy_page(self, src: int, dst: int):
        import jax.numpy as jnp
        self.k_pools, self.v_pools = self._copy_fn(
            self.k_pools, self.v_pools, jnp.asarray(src, jnp.int32),
            jnp.asarray(dst, jnp.int32))

    def metrics(self) -> Dict:
        m = self.cache.metrics()
        m["free_pages"] = len(self.mgr.free)
        return m
