"""Continuous-batching serving engine over the paged-KV cache.

``generate_paged`` runs STATIC batches: every prompt prefills together
and the whole batch drains at the pace of its slowest request, so real
mixed-arrival traffic leaves decode slots idle and queues new requests
behind the entire batch (head-of-line blocking). This module is the
scheduler the paged building blocks (``ops.paged_attention``'s pools +
``BlockManager``) were missing — vLLM-style continuous batching, the
TPU analog of the reference's AnalysisPredictor serving loop around
``fusion/block_multihead_attention``:

- a fixed-capacity SLOT TABLE: every decode step is ONE jitted program
  over all ``capacity`` slots. Inactive slots are padded — seq_len 0,
  block table pointing at the reserved scratch page — so admission and
  completion never change shapes: steady state is zero retraces.
- BUCKETED CHUNKED PREFILL: a new request's prompt runs through
  per-bucket jitted programs in bounded chunks (each at most the
  largest bucket), interleaved with in-flight decode steps. Each chunk
  gathers the request's pages into a dense view, runs the same
  ``cached_forward`` math as ``generate``'s prefill, and scatters the
  updated pages back — at most one trace per bucket, ever.
- SLOT RECYCLING: a finished request releases its KV pages back to the
  ``BlockManager`` and its slot is immediately re-admitted from the
  queue at the next step.
- int8 cache (``cache_dtype="int8"``): pools store int8 with static
  per-layer-per-head scales calibrated once from the first admitted
  prompt (the same calibration point as ``generate_paged``); prefill
  dequants pages into the chunk's dense view and requantizes on the way
  out (idempotent for untouched positions, same scale), decode runs the
  quantized gather path.
- RADIX PREFIX CACHE (``prefix_cache=True``): finished requests return
  their KV pages to a radix tree (inference/prefix_cache.py) instead of
  freeing them; admission longest-prefix-matches the prompt so a warm
  request appends the shared pages to its block table and prefills only
  its un-cached suffix. Prefill programs take a separate WRITE table
  whose shared-prefix entries are redirected to the scratch page, so a
  shared page is never written by construction; the partially-filled
  tail page is handed out only as a copy-on-write fork. The tree evicts
  LRU refcount-1 pages on allocator pressure. Programs keep the exact
  shapes of the cold path: cache hits cause zero retraces, and because
  the engine's int8 scales are engine-global and static, the int8 cache
  participates in sharing unchanged.

- TENSOR PARALLELISM (``mesh=ServingMesh(...)``, inference/tp.py): the
  paged KV pools, the QKV/o-proj/MLP weights and the per-slot attention
  computation shard along the HEAD axis of a named 1-D mesh via
  shard_map; the decode step stays ONE jitted program (sampling runs on
  the replicated logits), bucketed prefill stays <=1 trace per bucket,
  and the page tables stay host-global so BlockManager/prefix-cache
  logic is identical. Collective placement and the greedy-parity
  contract (bit-identical for collective="gather", roundoff for the
  default "psum") are documented in inference/tp.py.

Host/device split: the decode carry (tokens, seq_lens, key, pools)
stays device-resident between steps; host mirrors are re-uploaded only
when admission state changes. The per-step device->host read of the
sampled tokens is the scheduling point where the host detects EOS /
length-done and recycles slots.
"""
from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core.jax_compat import shard_map_norep
from ..observability import Observability, TelemetryConfig, TelemetryPlane
from ..ops.paged_attention import (BlockManager, dequant_cache,
                                   quant_cache)
from .admission import AdmissionQueue
from .generation import (GenerationConfig, _fused_decode_step,
                         _fused_mode, _fused_prefill_forward,
                         _fused_prefill_mode, _paged_decode_step,
                         _prefill_route, cached_forward, init_cache)

__all__ = ["Request", "ServingEngine"]

_SCRATCH_SEQ = -1      # BlockManager key owning the reserved page 0


def _sample_slots(logits, key, temps):
    """[C, V] logits -> [C] next tokens. ``temps[i] <= 0`` selects
    greedy for that slot; otherwise temperature sampling — per-request
    sampling rides as a traced array, so mixing greedy and sampled
    requests in one batch costs no retrace."""
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1)
    sampled = jax.random.categorical(
        key, logits / jnp.maximum(temps, 1e-6)[:, None], axis=-1)
    return jnp.where(temps <= 0.0, greedy, sampled).astype(jnp.int32)


def _collectives_snapshot(counters: Dict, obs: Observability) -> Dict:
    """The structured ``metrics()["collectives"]`` sub-dict (the
    Trainer.metrics contract): per-(op, axis) call/byte counters from
    the adopted dict + latency histograms from the bound recorder.
    ONE definition shared by ServingEngine and DisaggregatedEngine."""
    return {"calls": dict(counters.get("collective_calls", {})),
            "bytes": dict(counters.get("collective_bytes", {})),
            "latency_ms": {
                name[len("collective_"):-len("_ms")]: h.snapshot()
                for name, h in sorted(obs.registry.histograms.items())
                if name.startswith("collective_")
                and name.endswith("_ms")}}


def _drain_loop(eng, max_steps: Optional[int], starve_reason: str,
                starve_error: str) -> int:
    """The shared drain loop (ServingEngine and DisaggregatedEngine):
    step until idle; a capped drain records truncation; a step that
    can run nothing while work is pending raises, after a stall dump —
    unless the engine went idle during that step (e.g. its only
    remaining request deadline-expired), which is a clean finish."""
    n = 0
    eng.last_drain_truncated = False
    while not eng.idle:
        if not eng.step():
            if eng.idle:
                break       # the last step only expired/cleaned up
            dump = ""
            if eng._obs is not None:
                dump = eng._obs.stall_dump(starve_reason,
                                           eng.scheduler_snapshot(),
                                           metrics=eng.metrics())
            raise RuntimeError(
                starve_error + (f"; stall dump: {dump}" if dump else ""))
        n += 1
        if max_steps is not None and n >= max_steps:
            if not eng.idle:
                eng.last_drain_truncated = True
                eng.counters["drain_truncations"] += 1
                eng._drain_truncated_event(n)
            break
    return n


@dataclass
class Request:
    """One serving request and its lifecycle record."""
    req_id: int
    prompt: np.ndarray                       # [S] int32
    gen: GenerationConfig
    submit_t: float = 0.0
    priority: int = 1                        # class, LOWER = more urgent
    deadline_s: Optional[float] = None       # admission SLO (vs submit)
    tokens: List[int] = field(default_factory=list)   # generated ids
    ttft: Optional[float] = None             # sec, first token - submit
    admit_t: Optional[float] = None          # absolute, perf_counter
    first_token_t: Optional[float] = None    # absolute, perf_counter
    finish_t: Optional[float] = None
    done: bool = False
    expired: bool = False                    # deadline passed in queue
    preemptions: int = 0
    # (seq_len, last sampled token): set when the request holds valid
    # KV pages but no slot — a preempted decode slot awaiting requeue,
    # or a disaggregated handoff entering the decode group. Admission
    # re-enters decode directly from this carry; because the values are
    # exactly the ones the vacated slot held, the resumed decode is
    # bit-identical to the un-preempted run.
    resume: Optional[Tuple[int, int]] = None
    # the request's live admission-queue entry (engine bookkeeping):
    # set at push, reused by preemption's requeue so the victim keeps
    # its original line position and requeue count
    qentry: Optional[object] = field(default=None, repr=False)

    @property
    def output_ids(self) -> np.ndarray:
        return np.concatenate([np.asarray(self.prompt, np.int32),
                               np.asarray(self.tokens, np.int32)])


class _Slot:
    __slots__ = ("req", "phase", "seq_len", "prefill_pos")

    def __init__(self):
        self.req: Optional[Request] = None
        self.phase = "idle"          # idle | prefill | decode
        self.seq_len = 0             # tokens cached in the pools
        self.prefill_pos = 0         # next prompt position to prefill


class ServingEngine:
    """Continuous-batching engine over a shared paged KV pool.

    ``submit()`` enqueues a request; ``step()`` runs one scheduler
    iteration (admit -> one prefill chunk -> one decode step over all
    live slots); ``drain()`` steps until idle. ``metrics()`` reports
    tokens/s, TTFT, decode-slot utilization and compile/trace counts.

    ``observability=True`` (or an ``Observability`` instance) threads
    the metrics/tracing harness through the scheduler: per-request
    lifecycle events in a bounded ring buffer, TTFT/TPOT/queue-wait
    p50/p95/p99 histograms, per-step allocator + prefix-cache gauges,
    a retrace watchdog armed by ``reset_metrics()``, and flight-
    recorder stall dumps on ``drain()`` starvation or a blown
    ``step_deadline_s``. ``export_trace(path)`` writes a chrome trace,
    ``write_timeline(path)`` the structured per-phase JSONL. All hooks
    are host-side timestamps — greedy output, program shapes and the
    single per-step device sync are unchanged.
    """

    def __init__(self, params: Dict, cfg, capacity: int = 4,
                 block_size: int = 16, num_blocks: Optional[int] = None,
                 max_seq_len: Optional[int] = None, cache_dtype=None,
                 prefill_buckets=(32, 128), seed: int = 0,
                 prefix_cache: bool = False, kv_offload=False,
                 observability=False, fused_decode=None, mesh=None,
                 fused_prefill=None, weight_quant=None,
                 aging_s: Optional[float] = None, telemetry=False,
                 clock=None):
        # tensor parallelism (inference/tp.py): a ServingMesh shards
        # the KV pools, projections and per-slot attention along the
        # head axis; programs wrap in shard_map. None = single device.
        # Accepts a ServingMesh, a 1-D jax Mesh, or an int tp degree.
        from ..quantization.ptq import ensure_quantized
        from .tp import normalize_mesh
        # injectable scheduler clock (the admission queue's idiom, now
        # engine-wide): every scheduling timestamp — submit_t, expiry,
        # aging, admit/finish times — reads THIS callable, so tests and
        # the lifecycle model checker (analysis/lifecycle.py) can drive
        # admission deadlines and aging deterministically. None = wall
        # clock (time.perf_counter), behavior unchanged.
        self._clock = clock if clock is not None else time.perf_counter
        # opt-in per-step structural self-check: the lifecycle model
        # checker's manager+cache invariant set (BlockManager.check /
        # PrefixCache.check) asserted after every step. Off by default
        # (it walks the tree and the page pool each step).
        import os as _os_env
        self._check_inv = _os_env.environ.get(
            "PADDLE_TPU_CHECK_INVARIANTS", "") == "1"
        # weight quantization (quantization/ptq.py): "int8"/"int4"
        # quantizes a plain fp tree in ONE shot (host-side per-channel
        # absmax — the int8-KV first-prompt idiom, pointed at weights);
        # an already-quantized tree (e.g. activation-aware PTQ) rides
        # as-is and None adopts its mode. The mode is STRUCTURE of the
        # param tree, so every traced program keys on it for free and
        # kernel dispatch sees it via the weight_dtype meta key.
        params, self._wq = ensure_quantized(params, weight_quant)
        self._mesh = normalize_mesh(mesh)
        if self._wq and self._mesh is not None and self._mesh.tp > 1:
            raise ValueError(
                f"ServingEngine(weight_quant={self._wq!r}) cannot shard"
                f" over tp={self._mesh.tp} > 1: packed-int4 rows and "
                "per-channel scale trees need per-shard packing specs "
                "(named headroom) — run quantized serving single-device"
                " or on tp=1 groups")
        if self._mesh is not None:
            ok, reason = self._mesh.supports(cfg)
            if not ok:
                # clean rejection, same reason-string contract as the
                # kernel registry's supports() predicates
                raise ValueError(f"ServingEngine(mesh=...): {reason}")
            if self._mesh.collective == "gather" \
                    and _fused_mode(fused_decode) == "pallas":
                # an explicit pin must never silently no-op (the PR-7
                # rms_norm precedent): the gather placement runs the
                # exact unfused composition BY CONTRACT (bit-parity is
                # defined by the single-device op sequence)
                raise ValueError(
                    'fused_decode="pallas" cannot be honored under '
                    'collective="gather" — that placement runs the '
                    "exact unfused composition (its bit-parity "
                    'contract); use collective="psum" or drop the pin')
            if _fused_mode(fused_decode) == "block":
                # same never-silently-no-op rule: the single-launch
                # block kernel is single-device (its supports() rejects
                # tp != 1, and the sharded decode body runs the
                # per-stage kernels)
                raise ValueError(
                    'fused_decode="block" is single-device: the '
                    "single-launch decode-block kernel runs outside "
                    "shard_map — drop the mesh or the pin")
            params = self._mesh.shard(
                params, self._mesh.param_specs(cfg, params))
        self.params = params
        self.cfg = cfg
        # decode-block kernel routing: False = the pre-fusion unfused
        # step; "auto" (default, via FLAGS_fused_decode) = fused step
        # with registry dispatch (Pallas megakernels where supported,
        # bit-identical composition elsewhere); "pallas"/"ref" force a
        # variant (tests, audit catalog)
        self._fused = _fused_mode(fused_decode)
        # prefill-chunk kernel routing, mirroring fused_decode: False =
        # always the verbatim gather/cached_forward/scatter chunk;
        # "auto" (default, FLAGS_fused_prefill) = pool-direct fused
        # chunk where the registry supports BOTH prefill-block kernels,
        # the verbatim chunk elsewhere (bit-identical by construction);
        # "pallas"/"ref" force. Tensor-parallel engines (tp > 1) and
        # the "gather" placement keep the unfused chunk — gather's
        # bit-parity contract IS the single-device op sequence, and the
        # sharded prefill body is not fused yet.
        self._fused_prefill = _fused_prefill_mode(fused_prefill)
        self._prefill_mesh_ok = self._mesh is None or (
            self._mesh.tp == 1 and self._mesh.collective != "gather")
        if self._fused_prefill == "pallas" and not self._prefill_mesh_ok:
            # an explicit pin must never silently no-op (the PR-7
            # rms_norm precedent)
            raise ValueError(
                'fused_prefill="pallas" cannot be honored on this mesh'
                " — tensor-parallel (tp > 1) and gather-placement "
                "prefill run the unfused chunk by contract; use "
                'collective="psum" with tp=1 or drop the pin')
        # registry dispatch outcome captured when a fused prefill
        # program traces; None until then (see _make_prefill_fn_fused)
        self._prefill_variant = None
        # route actually built per (bucket, kernel-route) program-cache
        # key ("pallas" | "ref"), for the timeline's variant
        # attribution (tools/trace_summary.py) — keyed exactly like
        # _prefill_fns so a route change cannot stale the attribution
        self._prefill_kind: Dict[tuple, str] = {}
        # registry dispatch outcome captured when the decode program
        # traces (see _make_decode_fn); None until the first trace
        self._decode_variant = None
        self.capacity = int(capacity)
        self.block_size = int(block_size)
        self.max_seq_len = int(max_seq_len
                               or cfg.max_position_embeddings)
        if self.max_seq_len > cfg.max_position_embeddings:
            raise ValueError(
                f"max_seq_len {self.max_seq_len} exceeds the rope table "
                f"bound max_position_embeddings "
                f"= {cfg.max_position_embeddings}")
        self.buckets = tuple(sorted({int(b) for b in prefill_buckets}))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError("prefill_buckets must be positive")
        BS = self.block_size
        # the chunk's dense view is MB*BS wide; the last chunk may pad
        # past max_seq_len by up to a bucket, so the table gets the slack
        # (table width only — the physical pool is shared and unchanged)
        self.max_blocks = -(-(self.max_seq_len + self.buckets[-1]) // BS)
        if num_blocks is None:
            num_blocks = self.capacity * (-(-self.max_seq_len // BS)) + 1
        self.num_blocks = int(num_blocks)

        if cache_dtype in ("int8", jnp.int8):
            self._quant = True
        elif cache_dtype in (None, "bfloat16", "float32",
                             jnp.bfloat16, jnp.float32):
            self._quant = False
        else:
            raise ValueError(f"cache_dtype must be bfloat16|float32|int8,"
                             f" got {cache_dtype!r}")
        L, KV, hd = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                     cfg.head_dim)
        pool_dtype = jnp.int8 if self._quant else cfg.dtype
        shape = (L, self.num_blocks, BS, KV, hd)
        self._k_pools = jnp.zeros(shape, pool_dtype)
        self._v_pools = jnp.zeros(shape, pool_dtype)
        if self._mesh is not None:
            # pools shard their head-dim CONTENTS; page indices stay
            # host-global, so BlockManager/prefix-cache logic below is
            # identical with or without a mesh
            self._k_pools = self._mesh.shard(self._k_pools,
                                             self._mesh.pool_spec)
            self._v_pools = self._mesh.shard(self._v_pools,
                                             self._mesh.pool_spec)
        self._kv_scales = None       # (k [L,KV], v [L,KV]) once calibrated

        self.mgr = BlockManager(self.num_blocks, BS, self.max_blocks)
        # reserve physical page 0 as scratch: padded table entries (and
        # inactive decode slots) default there, so their writes land in
        # a page no live sequence ever reads
        scratch = self.mgr.allocate(_SCRATCH_SEQ, 1)
        assert scratch == [0], "scratch must be page 0 (tables pad with 0)"

        self._pcache = None
        # host-RAM KV offload tier (prefix_cache.py): kv_offload=True
        # (or an int host-page budget) makes eviction SPILL refcount-1
        # radix pages to host memory instead of dropping them, and a
        # prefix hit on a spilled node restore them — effective
        # prefix-cache capacity becomes HBM + host RAM
        self._kv_offload = bool(kv_offload)
        self._offload_extract_fn = None
        self._offload_insert_fn = None
        # spill/restore move in fixed-width multi-page WINDOWS (one
        # jitted gather + one host transfer per window instead of a
        # program per page; padded index entries point at scratch page
        # 0 — the disagg handoff idiom)
        import os as _os
        self._offload_window = max(1, int(_os.environ.get(
            "PADDLE_TPU_OFFLOAD_WINDOW", "8")))
        L_, KV_, hd_ = (cfg.num_hidden_layers,
                        cfg.num_key_value_heads, cfg.head_dim)
        # one physical page across BOTH pools, in bytes (the spill/
        # restore byte counters)
        self._page_nbytes = int(2 * L_ * BS * KV_ * hd_
                                * jnp.dtype(pool_dtype).itemsize)
        if kv_offload and not prefix_cache:
            raise ValueError(
                "kv_offload requires prefix_cache=True: the host tier "
                "spills radix-tree pages, not per-request tables")
        if prefix_cache:
            from .prefix_cache import PrefixCache, make_page_copier
            self._copy_fn = make_page_copier()
            budget = (int(kv_offload)
                      if kv_offload and kv_offload is not True else None)
            self._pcache = PrefixCache(
                self.mgr, BS, copy_page=self._copy_page,
                spill_pages=self._spill_pages if kv_offload else None,
                restore_pages=(self._restore_pages if kv_offload
                               else None),
                host_budget_pages=budget)

        C, MB = self.capacity, self.max_blocks
        self._slots = [_Slot() for _ in range(C)]
        # SLO-aware admission (inference/admission.py): priority
        # classes with FIFO tie-break, per-request admission deadlines,
        # aging for starvation-freedom. Default submissions (one class,
        # no deadline, no aging) pop in exact FIFO order — the PR-1
        # contract unchanged.
        self._queue = AdmissionQueue(aging_s=aging_s,
                                     clock=self._clock)
        # per-class queue-wait running stats + SLO attainment counters,
        # updated O(1) at admit/expire so metrics() never scans the
        # request list per class: cls -> [admitted, wait_ms_sum,
        # wait_ms_max]; slo = [with-deadline seen, attained]
        self._sched_cls: Dict[int, List[float]] = {}
        self._slo = [0, 0]
        self._requests: List[Request] = []
        self._next_id = 0
        self._slot_tables = np.zeros((C, MB), np.int32)  # true tables
        # prefill WRITE tables: identical to the true tables except that
        # shared-prefix entries point at scratch page 0 — the prefill
        # scatter can then never write a page another request (or the
        # tree) reads, whatever the chunk computes
        self._slot_wtables = np.zeros((C, MB), np.int32)
        # decode-program inputs (host mirrors). Mid-prefill slots keep
        # table 0 / seq 0 here: their decode write must hit scratch, not
        # their half-written prompt pages.
        self._h_tok = np.zeros((C,), np.int32)
        self._h_seq = np.zeros((C,), np.int32)
        self._h_tables = np.zeros((C, MB), np.int32)
        self._h_temps = np.zeros((C,), np.float32)
        self._dirty = True
        self._d_tok = self._d_seq = None
        self._d_tables = self._d_temps = None
        self._d_key = jax.random.key(seed)
        if self._mesh is not None:
            # donated carried state must live replicated ON the mesh:
            # donating a buffer the jit would first have to reshard
            # silently voids the donation (and warns) every step
            self._d_key = self._mesh.replicate(self._d_key)

        self._decode_fn = None
        self._prefill_fns: Dict[int, object] = {}
        self._calib_fn = None
        self._calib_bucket = None
        # *_traces counters increment inside the traced python bodies,
        # which only run when XLA (re)traces — they count compilations,
        # not calls. The tier-1 suite pins steady state to 1 decode
        # program + <=1 per prefill bucket over a 30-request stream.
        self.counters = {
            "decode_traces": 0, "prefill_traces": {},
            "calibration_traces": 0, "decode_steps": 0,
            "prefill_chunks": 0, "prefill_tokens": 0,
            # bucket-pad rows fed to prefill chunks (the compute the
            # RAGGED fused-prefill kernels skip; the unfused chunk
            # pays it — the serving_prefill bench's pad-FLOPs counter)
            "prefill_pad_tokens": 0,
            "live_slot_steps": 0,
            "tokens_generated": 0, "requests_submitted": 0,
            "requests_completed": 0, "drain_truncations": 0,
            "preemptions": 0, "requeues": 0, "deadline_expired": 0,
            # host-tier handoff pair: trace counter (spill extract +
            # restore insert, <=1 each — they trace lazily on the first
            # spill) and the bytes moved each direction
            "offload_traces": 0, "kv_spill_bytes": 0,
            "kv_restore_bytes": 0,
        }
        self._t_first = None
        self._t_last = None
        self._metrics_reset_t = None   # TTFTs from before this are warmup
        self.last_drain_truncated = False
        # observability: None when disabled — every hook below is a
        # single `is not None` check, so the disabled hot loop allocates
        # NO event objects and issues NO extra device syncs (the per-
        # step d2h token read in _run_decode stays the only sync point).
        # telemetry implies observability: the plane's alerts land
        # timeline events and stall dumps, both owned by the harness.
        _tcfg = TelemetryConfig.coerce(telemetry)
        if observability or _tcfg is not None:
            self._obs = (observability
                         if isinstance(observability, Observability)
                         else Observability())
            self._obs.registry.adopt_counters(self.counters)
            if self._kv_offload:
                # handoff_ms-style distributions for the host tier
                self._obs.ensure_histograms(("spill_ms", "restore_ms"))
        else:
            self._obs = None
        # serving-collective instrumentation: a mesh'd engine with
        # observability on binds an engine-scoped flight recorder and
        # replays the DECLARED per-step collective inventory around
        # each dispatched program — host-observed spans (the engine's
        # one-sync-per-step philosophy), byte counters exact because
        # the shapes are static. metrics() surfaces them under
        # "collectives" exactly like Trainer.metrics().
        self._flight = None
        self._coll_decode = ()
        self._coll_prefill: Dict[int, tuple] = {}
        if self._mesh is not None and self._obs is not None:
            from ..distributed.flight_recorder import FlightRecorder
            rec = FlightRecorder(capacity=4096)
            rec.enabled = True
            self._flight = self._obs.bind_flight_recorder(rec)
            self._coll_decode = tuple(self._mesh.collective_inventory(
                cfg, B=self.capacity))
        # continuous telemetry plane (r22): samples this engine's
        # metrics() on a step cadence into bounded time-series with
        # burn-rate/anomaly alerting. None when disabled — the hot loop
        # pays one `is not None` check, nothing else.
        self._telemetry = None
        if _tcfg is not None:
            self._telemetry = TelemetryPlane(
                _tcfg, on_alert=self._telemetry_alert)
            self._telemetry.register("serving_engine", self.metrics,
                                     counters=self.counters)

    def _record_collectives(self, inventory):
        """Open one CommTask per declared collective class; returns the
        tasks for :meth:`_end_collectives` after the program's sync."""
        if self._flight is None or not inventory:
            return None
        return [self._flight.begin(op, ax, shape, dt)
                for op, ax, shape, dt in inventory]

    def _end_collectives(self, tasks):
        if tasks:
            for t in tasks:
                self._flight.end(t)

    def _upload(self, x):
        """Host mirror -> device, committed replicated on the mesh when
        tensor-parallel (so donated carried state never reshards)."""
        if self._mesh is not None:
            return self._mesh.replicate(np.ascontiguousarray(x))
        return jnp.asarray(x)

    def _copy_page(self, src: int, dst: int):
        """COW primitive for the prefix cache: device-copy one physical
        page in both pools (one jitted program, traced once — src/dst
        ride as int32 scalars)."""
        self._k_pools, self._v_pools = self._copy_fn(
            self._k_pools, self._v_pools, jnp.asarray(src, jnp.int32),
            jnp.asarray(dst, jnp.int32))

    # -- host-RAM KV offload tier -------------------------------------
    def _make_offload_fns(self):
        """The host-tier handoff pair — the PR-10 extract/device_put/
        insert machinery pointed inward, WINDOWED (r17): ``extract``
        gathers a fixed-width block of ``_offload_window`` physical
        pages from both pools in one program, ``insert`` scatters a
        restored window back (donated, so the pools update in place).
        Padded index entries point at scratch page 0 on both sides
        (the disagg fixed-width idiom), so one trace each covers every
        batch size, ever."""
        counters = self.counters

        def extract(kp, vp, idx):
            counters["offload_traces"] += 1
            return kp[:, idx], vp[:, idx]

        def insert(kp, vp, idx, kpag, vpag):
            counters["offload_traces"] += 1
            return (kp.at[:, idx].set(kpag), vp.at[:, idx].set(vpag))

        return (jax.jit(extract), jax.jit(insert, donate_argnums=(0, 1)))

    def _spill_pages(self, pages):
        """PrefixCache batch-spill callback: the pages' raw bytes ->
        host memory in fixed-width windows — ONE jitted gather + ONE
        host transfer per pool per window replaces the per-page
        programs. The window leaves the device through ``host_put``
        (pinned host memory where the backend offers it — the fast d2h
        path the per-page tier used), then splits into per-page numpy
        payloads so the host tier's per-page budget accounting stays
        exact; only :meth:`_restore_pages` reads them."""
        from .prefix_cache import host_put
        if self._offload_extract_fn is None:
            (self._offload_extract_fn,
             self._offload_insert_fn) = self._make_offload_fns()
        W = self._offload_window
        t0 = self._clock()
        payloads = []
        for w0 in range(0, len(pages), W):
            win = list(pages[w0:w0 + W])
            idx = np.zeros((W,), np.int32)
            idx[:len(win)] = win
            kw, vw = self._offload_extract_fn(
                self._k_pools, self._v_pools, jnp.asarray(idx))
            kw, vw = host_put(kw), host_put(vw)   # pinned d2h per pool
            kw_np, vw_np = np.asarray(kw), np.asarray(vw)
            for j in range(len(win)):
                payloads.append((np.ascontiguousarray(kw_np[:, j]),
                                 np.ascontiguousarray(vw_np[:, j])))
        self.counters["kv_spill_bytes"] += self._page_nbytes * len(pages)
        if self._obs is not None and pages:
            dur = (self._clock() - t0) * 1e3
            per = dur / len(pages)
            for _ in pages:      # one observation per PAGE (the
                self._obs.hist("spill_ms").observe(per)   # count
            self._obs.timeline.record(   # contract: count == pages)
                "kv_spill", pages=[int(p) for p in pages],
                bytes=self._page_nbytes * len(pages),
                dur_ms=round(dur, 3))
        return payloads

    def _restore_pages(self, payloads, dsts):
        """PrefixCache batch-restore callback: device_put the spilled
        windows back and scatter them into the destination pages with
        the donated window insert — byte-identical to what was
        spilled. The insert is DISPATCHED, never synced: the
        device-side copy overlaps the suffix prefill chunk the caller
        issues next (which consumes the updated pools) instead of
        completing before it."""
        if self._offload_insert_fn is None:
            (self._offload_extract_fn,
             self._offload_insert_fn) = self._make_offload_fns()
        W = self._offload_window
        ps = self._k_pools.shape           # [L, N, BS, KV, hd]
        t0 = self._clock()
        for w0 in range(0, len(dsts), W):
            win_p = payloads[w0:w0 + W]
            win_d = list(dsts[w0:w0 + W])
            idx = np.zeros((W,), np.int32)
            idx[:len(win_d)] = win_d
            kw = np.zeros((ps[0], W) + ps[2:], self._k_pools.dtype)
            vw = np.zeros_like(kw)
            for j, (kpg, vpg) in enumerate(win_p):
                kw[:, j] = kpg
                vw[:, j] = vpg
            if self._mesh is not None:
                kw = self._mesh.replicate(kw)
                vw = self._mesh.replicate(vw)
            else:
                dev = next(iter(self._k_pools.devices()))
                kw = jax.device_put(kw, dev)
                vw = jax.device_put(vw, dev)
            self._k_pools, self._v_pools = self._offload_insert_fn(
                self._k_pools, self._v_pools, jnp.asarray(idx), kw, vw)
        self.counters["kv_restore_bytes"] += \
            self._page_nbytes * len(dsts)
        if self._obs is not None and dsts:
            dur = (self._clock() - t0) * 1e3
            per = dur / len(dsts)
            for _ in dsts:
                self._obs.hist("restore_ms").observe(per)
            self._obs.timeline.record(
                "kv_restore", pages=[int(d) for d in dsts],
                bytes=self._page_nbytes * len(dsts),
                dur_ms=round(dur, 3))

    # -- public API ---------------------------------------------------
    def _alloc_tokens(self, req: Request) -> int:
        """Token span this engine allocates KV pages for. The colocated
        engine holds the whole request (prompt + generation); the
        disaggregated prefill worker overrides to prompt-only — its
        pages hand off to the decode group before generation."""
        return int(req.prompt.size) + int(req.gen.max_new_tokens)

    def submit(self, prompt, gen: Optional[GenerationConfig] = None,
               priority: Optional[int] = None,
               deadline_s: Optional[float] = None) -> Request:
        """Enqueue one request. Admission happens inside ``step()``
        when a slot and enough KV pages are free, ordered by priority
        class (LOWER = more urgent; FIFO within a class, aging per the
        engine's ``aging_s``). ``deadline_s`` bounds queue wait: a
        request still queued past its deadline is rejected (marked
        ``expired``), never admitted late. ``priority``/``deadline_s``
        default from ``gen``."""
        gen = gen or GenerationConfig()
        if gen.top_k > 0 or gen.top_p < 1.0:
            raise NotImplementedError(
                "ServingEngine: per-request top-k/top-p would bake the "
                "knob values into the traced decode program (a retrace "
                "per distinct config); greedy/temperature ride as traced"
                " arrays. Use generate()/generate_paged for top-k/top-p")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        total = int(prompt.size) + int(gen.max_new_tokens)
        if total > self.max_seq_len:
            raise ValueError(
                f"prompt+max_new_tokens = {total} exceeds engine "
                f"max_seq_len = {self.max_seq_len}")
        if priority is None:
            priority = getattr(gen, "priority", 1)
        if deadline_s is None:
            deadline_s = getattr(gen, "deadline_s", None)
        req = Request(self._next_id, prompt, gen,
                      submit_t=self._clock(),
                      priority=int(priority), deadline_s=deadline_s)
        need = -(-self._alloc_tokens(req) // self.block_size)
        if need > self.num_blocks - 1:          # minus the scratch page
            raise ValueError(
                f"request needs {need} KV pages but the pool only has "
                f"{self.num_blocks - 1}; raise num_blocks")
        self._next_id += 1
        req.qentry = self._queue.push(req, cls=req.priority,
                                      submit_t=req.submit_t,
                                      deadline_s=deadline_s)
        self._requests.append(req)
        self.counters["requests_submitted"] += 1
        if self._obs is not None:
            self._obs.timeline.record(
                "submit", req.req_id, prompt_tokens=int(prompt.size),
                max_new_tokens=int(gen.max_new_tokens),
                priority=req.priority,
                **({"deadline_s": deadline_s}
                   if deadline_s is not None else {}))
        return req

    def step(self) -> bool:
        """One scheduler iteration: admit from the queue, run one
        prefill chunk (if an admission is in flight), then one decode
        step over all live slots. Returns True if any work ran —
        including deadline expiries, which shrink the queue and so
        count as scheduler progress (a drain() whose last step only
        expires a request must finish cleanly, not report starvation)."""
        obs = self._obs
        t0 = self._clock() if obs is not None else 0.0
        if self._t_first is None:
            self._t_first = self._clock()
        expired = self._admit()
        did = self._run_prefill()
        did = self._run_decode() or did
        if did:
            self._t_last = self._clock()
        if obs is not None:
            self._observe_step(t0, did)
        if self._telemetry is not None:
            self._telemetry.on_step()
        if self._check_inv:
            # PADDLE_TPU_CHECK_INVARIANTS=1: assert the lifecycle
            # checker's manager+cache invariant set after every step
            self.mgr.check()
            if self._pcache is not None:
                self._pcache.check()
        return did or expired > 0

    def _observe_step(self, t0: float, did: bool):
        """Post-step observability: gauges, watchdog, step deadline.
        Pure host bookkeeping — reads only host mirrors, never the
        device."""
        obs = self._obs
        now = self._clock()
        free = len(self.mgr.free)
        vals = {
            "pages_free": free,
            "pages_in_use": self.num_blocks - free,
            "kv_refcount_total": int(self.mgr.refcount.sum()),
            "queue_depth": len(self._queue),
            "live_slots": sum(1 for s in self._slots
                              if s.phase != "idle"),
        }
        if self._slo[0]:
            vals["slo_attainment"] = self._slo[1] / self._slo[0]
        if self._pcache is not None:
            st = self._pcache.stats
            looked = st["hits"] + st["misses"]
            vals["prefix_tree_pages"] = self._pcache.cached_pages
            vals["prefix_hit_ratio"] = (round(st["hits"] / looked, 4)
                                        if looked else 0.0)
            if self._kv_offload:
                vals["prefix_host_pages"] = self._pcache.host_pages
        obs.sample_gauges(now, vals)
        if obs.watchdog.check(self.counters):
            obs.timeline.record("retrace",
                                events=len(obs.watchdog.events))
        if did:
            dur = now - t0
            obs.hist("step_ms").observe(dur * 1e3)
            if obs.step_deadline_s is not None \
                    and dur > obs.step_deadline_s:
                obs.stall_dump(
                    f"step took {dur * 1e3:.1f} ms "
                    f"(deadline {obs.step_deadline_s * 1e3:.1f} ms)",
                    self.scheduler_snapshot())

    def _resolve_variant(self) -> Dict:
        from ..ops.pallas.fused_decode_block import (decode_meta,
                                                     resolve_decode_step)
        from ..ops.pallas.fused_decode_block import decode_meta_dims
        sm = self._mesh
        if sm is not None and sm.collective == "gather":
            # the gather placement's bit-parity contract IS the
            # single-device op sequence — it always runs the exact
            # composition, whatever the fused knob says
            return {"mode": str(self._fused), "block": "composed",
                    "attn": "unfused", "mlp": "unfused"}
        cfg, tp = self.cfg, (1 if sm is None else sm.tp)
        if tp == 1:
            meta = decode_meta(cfg, B=self.capacity,
                               BS=self.block_size, MB=self.max_blocks,
                               pool_dtype=self._k_pools.dtype,
                               quant=self._quant,
                               weight_dtype=self._wq)
        else:
            # dispatch consults the PER-SHARD shape class: local head
            # and intermediate counts, tp riding in the meta — the
            # same dims _tp_decode_step derives inside shard_map
            meta = decode_meta_dims(
                self.capacity, cfg.hidden_size,
                cfg.num_attention_heads // tp,
                cfg.num_key_value_heads // tp, cfg.head_dim,
                cfg.intermediate_size // tp, self.block_size,
                self.max_blocks, cfg.dtype, self._k_pools.dtype,
                self._quant, tp=tp, weight_dtype=self._wq)
        _, _, _, names = resolve_decode_step(meta, self._fused)
        return {"mode": str(self._fused), **names}

    @property
    def decode_variant(self) -> Dict:
        """Which decode-block implementation this engine's decode
        program runs: ``{"mode": ..., "block": ..., "attn": ...,
        "mlp": ...}`` — "block" is the single-launch megakernel's slot
        ("pallas_block" when it serves the step, "composed" when the
        two-stage route does). Captured when the decode program TRACES
        (dispatch is consulted at trace time), so later env changes —
        the VMEM budget, a ``KERNELS.force`` pin around a ``metrics()``
        call — cannot make the report drift from the compiled program.
        Before the first decode step it reports what dispatch would
        pick now."""
        if not self._fused:
            return {"mode": "unfused", "block": "composed",
                    "attn": "unfused", "mlp": "unfused"}
        if self._decode_variant is not None:
            return dict(self._decode_variant)
        return self._resolve_variant()

    @property
    def weight_quant_variant(self) -> Dict:
        """Which weight-dtype class the engine's programs run:
        ``{"mode": "off"}`` for plain fp weights, else ``{"mode":
        "int8"|"int4", "weight_dtype": ..., "attn": ..., "mlp": ...}``
        with the decode-block variants that serve the quantized tree.
        Derives from :attr:`decode_variant`, which is snapshotted when
        the decode program TRACES — a trace-time report of compiled
        reality, never live dispatch (the ``decode_variant``
        contract)."""
        if not self._wq:
            return {"mode": "off"}
        v = self.decode_variant
        return {"mode": self._wq, "weight_dtype": self._wq,
                "block": v["block"], "attn": v["attn"],
                "mlp": v["mlp"]}

    def _active_arm(self) -> str:
        """Which roofline arm the live decode step runs: the
        single-launch block kernel, the two-kernel fused composition,
        or the unfused reference."""
        v = self.decode_variant
        if v.get("block") == "pallas_block":
            return "pallas_block"
        if str(v.get("attn", "")).startswith("pallas"):
            return "pallas_fused"
        return "unfused"

    def _roofline_metrics(self) -> Dict:
        """Per-decode-variant modeled HBM bytes/step + the
        bandwidth-bound step-time floor (``observability/roofline``'s
        closed-form arm model × layers + the lm-head read), with the
        achieved-bandwidth fraction filled for the ACTIVE arm when a
        measured ``decode_step_ms`` distribution exists. Pure host
        arithmetic on the engine's static dims, computed on demand —
        the disabled-observability hot path still allocates nothing."""
        import jax.numpy as jnp

        from ..observability.roofline import (decode_roofline,
                                              decode_step_bytes)

        cfg = self.cfg
        tp = 1 if self._mesh is None else self._mesh.tp
        act = jnp.dtype(cfg.dtype).itemsize
        pool = jnp.dtype(self._k_pools.dtype).itemsize
        wbytes = {"int8": 1.0, "int4": 0.5}.get(self._wq or "",
                                                float(act))
        L = cfg.num_hidden_layers
        per_layer = decode_step_bytes(
            self.capacity, cfg.hidden_size,
            cfg.num_attention_heads // tp,
            cfg.num_key_value_heads // tp, cfg.head_dim,
            cfg.intermediate_size // tp, self.block_size,
            self.max_blocks, act_itemsize=act, weight_itemsize=wbytes,
            pool_itemsize=pool)
        head = cfg.vocab_size * cfg.hidden_size * act
        step_bytes = {k: int(v * L + head)
                      for k, v in per_layer.items()}
        active = self._active_arm()
        measured = {}
        if self._obs is not None:
            snap = self._obs.registry.histogram(
                "decode_step_ms").snapshot()
            if snap["count"]:
                measured[active] = snap["mean"] * 1e3
        r = decode_roofline(step_bytes, measured_us=measured)
        r["active"] = active
        r["layers"] = L
        return r

    @property
    def idle(self) -> bool:
        return not self._queue and all(
            s.phase == "idle" for s in self._slots)

    # -- fleet-router surface (inference/fleet.py) --------------------
    @property
    def queue_depth(self) -> int:
        """Requests submitted but not yet admitted — the router's
        admission-backpressure signal."""
        return len(self._queue)

    @property
    def live_slots(self) -> int:
        return sum(1 for s in self._slots if s.phase != "idle")

    @property
    def prefix_cache_version(self) -> int:
        """Monotone radix-tree version (0 without a prefix cache) —
        the router refreshes its cached tree summary when this moves."""
        return 0 if self._pcache is None else self._pcache.version

    def prefix_summary(self) -> Dict[int, int]:
        """The router's tree summary: ``{prefix_hash: n_tokens}`` for
        every page-aligned cached path (empty without a prefix
        cache)."""
        return {} if self._pcache is None else self._pcache.summary()

    def drain(self, max_steps: Optional[int] = None) -> int:
        """Step until queue and slots are empty; returns step count.

        Hitting ``max_steps`` with work still pending is recorded —
        ``last_drain_truncated`` is set and the ``drain_truncations``
        counter increments — so a capped drain is distinguishable from
        a clean one at the call site. Starvation (a step that can run
        nothing while requests are queued) raises, after writing a
        flight-recorder stall dump when observability is on."""
        return _drain_loop(
            self, max_steps,
            starve_reason="drain starved: queued requests cannot be "
                          "admitted",
            starve_error="engine starved: queued requests cannot be "
                         "admitted (KV pool too small for the "
                         "in-flight mix?)")

    def _drain_truncated_event(self, n: int):
        if self._obs is not None:
            self._obs.timeline.record(
                "drain_truncated", steps=n,
                queue_depth=len(self._queue),
                live_slots=sum(1 for s in self._slots
                               if s.phase != "idle"))

    def scheduler_snapshot(self) -> Dict:
        """Host-side scheduler state for stall dumps: queue depth, slot
        phases, per-slot seq_len, free pages, prefix-cache state."""
        snap = {
            "queue_depth": len(self._queue),
            "queued": [{"req_id": e.item.req_id,
                        "prompt_tokens": int(e.item.prompt.size),
                        "priority": e.item.priority,
                        "requeues": e.requeues,
                        "need_pages":
                            -(-self._alloc_tokens(e.item)
                              // self.block_size)}
                       for e in list(self._queue)[:16]],
            "slots": [{"slot": i, "phase": s.phase,
                       "req_id": s.req.req_id if s.req else None,
                       "seq_len": s.seq_len,
                       "prefill_pos": s.prefill_pos}
                      for i, s in enumerate(self._slots)],
            "pages_free": len(self.mgr.free),
            "num_blocks": self.num_blocks,
            "capacity": self.capacity,
        }
        if self._pcache is not None:
            snap["prefix_cache"] = self._pcache.metrics()
        return snap

    @property
    def telemetry(self) -> Optional[TelemetryPlane]:
        """The continuous telemetry plane, or None when disabled."""
        return self._telemetry

    def _telemetry_alert(self, alert: Dict):
        """Plane alert callback: stamp an ``alert`` timeline event; a
        page-severity alert additionally self-documents through the
        flight-recorder stall-dump machinery (scheduler snapshot + the
        alert that fired)."""
        obs = self._obs
        if obs is None:
            return
        obs.timeline.record(
            "alert", rule=alert.get("rule"),
            severity=alert.get("severity"), metric=alert.get("metric"),
            value=alert.get("value"), threshold=alert.get("threshold"))
        if (alert.get("severity") == "page"
                and self._telemetry is not None
                and self._telemetry.config.page_dumps):
            obs.stall_dump(
                f"telemetry alert: {alert.get('rule')} on "
                f"{alert.get('metric')}", self.scheduler_snapshot(),
                metrics={"alert": alert})

    def metrics(self) -> Dict:
        # the flight recorder parks raw collective_calls/bytes counters
        # in the adopted dict; they surface ONLY under the structured
        # "collectives" key below (the Trainer.metrics contract)
        c = {k: (dict(v) if isinstance(v, dict) else v)
             for k, v in self.counters.items()
             if k not in ("collective_calls", "collective_bytes")}
        if self._mesh is not None:
            c["mesh"] = self._mesh.describe()
        wall = ((self._t_last - self._t_first)
                if self._t_first is not None and self._t_last is not None
                else 0.0)
        c["wall_time_s"] = round(wall, 6)
        c["tokens_per_sec"] = (round(c["tokens_generated"] / wall, 3)
                               if wall > 0 else 0.0)
        # prompt tokens processed over the same window: prefill- vs
        # decode-bound workloads are indistinguishable without it
        c["prefill_tokens_per_sec"] = (
            round(c["prefill_tokens"] / wall, 3) if wall > 0 else 0.0)
        # TTFTs measured before the last reset_metrics() belong to the
        # warmup window — a request in flight across the reset keeps
        # its Request object but must not pollute this window's stats
        cut = self._metrics_reset_t
        ttfts = [r.ttft for r in self._requests
                 if r.ttft is not None
                 and (cut is None or (r.first_token_t or 0.0) >= cut)]
        c["ttft_ms_mean"] = (round(float(np.mean(ttfts)) * 1e3, 3)
                             if ttfts else None)
        c["ttft_ms_max"] = (round(float(np.max(ttfts)) * 1e3, 3)
                            if ttfts else None)
        steps = c["decode_steps"]
        c["slot_utilization"] = (
            round(c["live_slot_steps"] / (steps * self.capacity), 4)
            if steps else 0.0)
        c["decode_variant"] = self.decode_variant
        c["prefill_variant"] = self.prefill_variant
        c["weight_quant_variant"] = self.weight_quant_variant
        c["roofline"] = self._roofline_metrics()
        c["scheduler"] = self._scheduler_metrics()
        if self._pcache is not None:
            c["prefix_cache"] = self._pcache.metrics()
        if self._telemetry is not None:
            c["telemetry"] = self._telemetry.snapshot()
        if self._obs is not None:
            obs = self._obs
            c["latency"] = obs.latency_snapshot()
            c["gauges"] = obs.gauges_snapshot()
            c["retrace_warnings"] = len(obs.watchdog.events)
            c["stall_dumps"] = (len(obs.stall_dumps)
                                + obs.stall_dumps_suppressed)
            c["timeline_events"] = len(obs.timeline)
            c["timeline_dropped"] = obs.timeline.dropped
            if self._flight is not None:
                # the bound recorder feeds per-(op, axis) latency
                # histograms + call/byte counters — one structured
                # sub-dict, schema-frozen in test_observability
                c["collectives"] = _collectives_snapshot(self.counters,
                                                         obs)
        return c

    def _scheduler_metrics(self) -> Dict:
        """The SLO-admission window report: per-class queue-wait stats
        (running O(1) sums — never a request-list scan), deadline
        attainment (fraction of deadline-carrying requests admitted
        within their deadline; None when none carried one), and the
        live queue depth. Same shape in both observability modes."""
        per = {str(cls): {
                   "admitted": int(st[0]),
                   "queue_wait_ms_mean": (round(st[1] / st[0], 3)
                                          if st[0] else 0.0),
                   "queue_wait_ms_max": round(st[2], 3)}
               for cls, st in sorted(self._sched_cls.items())}
        n, ok = self._slo
        return {"per_class": per,
                "slo_attainment": (round(ok / n, 4) if n else None),
                # the raw attainment counters: the telemetry plane's
                # burn-rate windows difference these across samples
                "slo_seen": int(n), "slo_attained": int(ok),
                "queue_depth": len(self._queue)}

    def reset_metrics(self):
        """Zero the throughput counters/timers (e.g. after a compile
        warmup pass). Trace counters are cumulative and stay — but the
        retrace watchdog arms HERE: any program that traces after this
        call is a steady-state retrace and warns."""
        for k in ("decode_steps", "prefill_chunks", "prefill_tokens",
                  "prefill_pad_tokens",
                  "live_slot_steps", "tokens_generated",
                  "requests_submitted", "requests_completed",
                  "drain_truncations", "preemptions", "requeues",
                  "deadline_expired", "kv_spill_bytes",
                  "kv_restore_bytes"):
            self.counters[k] = 0
        self._sched_cls = {}
        self._slo = [0, 0]
        if self._pcache is not None:
            # workload counters like the above (the cached PAGES stay —
            # only the counts restart, so a warmed-up bench window
            # reports its own hits/skips, not the warmup's)
            for k in self._pcache.stats:
                self._pcache.stats[k] = 0
        self._t_first = self._t_last = None
        self._metrics_reset_t = self._clock()
        self._requests = [r for r in self._requests if not r.done]
        if self._flight is not None:
            # the recorder's call/byte counters live in the adopted
            # dict; reset_window() below restarts the collective
            # latency HISTOGRAMS, so the counters must restart with
            # them — metrics()["collectives"] reports ONE window
            # (calls == histogram count), never warmup-inflated totals
            self.counters.pop("collective_calls", None)
            self.counters.pop("collective_bytes", None)
        if self._obs is not None:
            self._obs.reset_window()
            self._obs.watchdog.mark_warmup(self.counters)

    # -- observability export -----------------------------------------
    @property
    def observability(self) -> Optional[Observability]:
        return self._obs

    def _require_obs(self) -> Observability:
        if self._obs is None:
            raise RuntimeError(
                "observability is disabled for this engine; construct "
                "with ServingEngine(..., observability=True)")
        return self._obs

    def export_trace(self, path: str) -> str:
        """Write the request-lifecycle chrome trace (+ gauge counter
        tracks + the per-arm roofline annotation track) to ``path`` —
        open in Perfetto / chrome://tracing."""
        from ..observability.roofline import roofline_chrome_events
        return self._require_obs().export_chrome(
            path,
            extra_events=roofline_chrome_events(self._roofline_metrics()))

    def write_timeline(self, path: str) -> str:
        """Write the structured per-phase JSONL (events + per-request
        records) to ``path`` — input for tools/trace_summary.py. The
        meta header carries the per-arm roofline model so the summary
        can print measured step time against the bandwidth floor."""
        return self._require_obs().write_jsonl(
            path, header={"capacity": self.capacity,
                          "num_blocks": self.num_blocks,
                          "block_size": self.block_size,
                          "roofline": self._roofline_metrics()})

    # -- scheduling ---------------------------------------------------
    def _temp_of(self, gen: GenerationConfig) -> float:
        return 0.0 if (gen.greedy or gen.temperature == 0.0) \
            else float(gen.temperature)

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _admit(self) -> int:
        """Admit from the queue until blocked; returns the number of
        deadline expiries (scheduler progress the caller must count)."""
        now = self._clock()
        expired = self._queue.pop_expired(now)
        for entry in expired:
            self._expire(entry.item, now)
        while self._queue:
            entry = self._queue.best(now)
            req = entry.item
            # a slot first — idle, or a strictly lower-priority decode
            # victim (candidate only; the preemption itself waits until
            # the page check passes). Slots are checked BEFORE pages so
            # a saturated engine never pays the prefix-cache acquire
            # (which pins pages and may device-copy a COW fork) on
            # every step just to release it again.
            slot_id = next((i for i, s in enumerate(self._slots)
                            if s.phase == "idle"), None)
            victim = None
            if slot_id is None:
                victim = self._preempt_candidate(req)
                if victim is None:
                    break
            acquired = None
            if req.resume is None:
                ok, acquired = self._acquire_pages(req)
                if not ok:
                    # the line head is page-starved. Fresh requests may
                    # not overtake it (page fairness — FIFO-within-
                    # order backpressure), but a RESUME entry allocates
                    # NOTHING and holds pages whose release is the only
                    # way the head ever unblocks, so the best resume
                    # entry admits instead (deadlock freedom: a
                    # preempted victim parked behind a page-short head
                    # must not pin the pool forever).
                    entry = self._queue.best(
                        now, pred=lambda e: e.item.resume is not None)
                    if entry is None:
                        break
                    req = entry.item
                    if slot_id is None:
                        # preemption rights are per-entry (raw class):
                        # re-pick the victim for the resume entry
                        victim = self._preempt_candidate(req)
                        if victim is None:
                            break
            if slot_id is None:
                slot_id = self._preempt(victim)
            self._queue.remove(entry)
            if req.resume is not None:
                # valid KV pages already attached (a preempted decode
                # slot, or a disaggregated KV handoff): re-enter decode
                # directly — no pages to allocate, no prefill
                self._admit_resume(slot_id, req, now)
                continue
            slot = self._slots[slot_id]
            if self._quant and self._kv_scales is None:
                # static scales calibrate from the first admitted prompt
                # BEFORE any prefill/decode program exists, so the
                # programs close over the final scale arrays
                self._calibrate(req.prompt)
            matched = shared = 0
            if acquired is not None:
                pages, matched, shared = acquired
                # matched pages join the block table directly; their
                # references transfer to this request's table entries
                self.mgr.attach(req.req_id, pages, owned=True)
            table = self.mgr.allocate(req.req_id,
                                      self._alloc_tokens(req))
            slot.req = req
            slot.phase = "prefill"
            slot.seq_len = 0
            slot.prefill_pos = matched     # prefill only the suffix
            self._slot_tables[slot_id] = 0
            self._slot_tables[slot_id, :len(table)] = table
            self._slot_wtables[slot_id] = self._slot_tables[slot_id]
            self._slot_wtables[slot_id, :shared] = 0
            self._record_admit(req, slot_id, now, matched)
        return len(expired)

    def _acquire_pages(self, req: Request):
        """Page-availability check for a fresh admission: ``(ok,
        acquired)``. Without a prefix cache this is a pure free-list
        check; with one, ``acquire()`` longest-prefix matches (capped
        at S-1 so the request always prefills >= 1 token, the logits
        source for its first sampled token), PINS the matched pages,
        and owns the backpressure check — free plus evictable must
        cover the un-matched remainder."""
        need = -(-self._alloc_tokens(req) // self.block_size)
        if self._pcache is None:
            return len(self.mgr.free) >= need, None
        acquired = self._pcache.acquire(
            req.prompt, int(req.prompt.size) - 1, need)
        return acquired is not None, acquired

    def _record_admit(self, req: Request, slot_id: int, now: float,
                      matched: int = 0):
        """Admission bookkeeping shared by the fresh and resume paths:
        queue-wait stats per priority class, SLO attainment, the
        queue_wait histogram and the timeline event."""
        first = req.admit_t is None
        if first:
            # admit_t is the FIRST admission (queue-wait semantics);
            # a resume keeps it so per-request records report the
            # original admission wait, not the requeue wait
            req.admit_t = self._clock()
            wait_ms = (req.admit_t - req.submit_t) * 1e3
            st = self._sched_cls.setdefault(req.priority, [0, 0.0, 0.0])
            st[0] += 1
            st[1] += wait_ms
            st[2] = max(st[2], wait_ms)
            if req.deadline_s is not None:
                self._slo[0] += 1
                if wait_ms <= req.deadline_s * 1e3:
                    self._slo[1] += 1
            if self._obs is not None:
                self._obs.hist("queue_wait_ms").observe(wait_ms)
        if self._obs is not None:
            wait_ms = (self._clock() - req.submit_t) * 1e3
            self._obs.timeline.record(
                "admit" if first else "resume", req.req_id,
                slot=slot_id, queue_wait_ms=round(wait_ms, 3),
                matched_tokens=matched, priority=req.priority)

    def _expire(self, req: Request, now: float):
        """Admission deadline passed while queued: reject, never admit
        late. A fresh request holds no pages; an expired RESUME entry
        cannot occur (started entries never expire)."""
        req.done = True
        req.expired = True
        req.finish_t = now
        self.counters["deadline_expired"] += 1
        if req.deadline_s is not None:
            self._slo[0] += 1       # a deadline seen and MISSED
        if req.req_id in self.mgr.tables:     # defensive: resume state
            self.mgr.release(req.req_id)
        if self._obs is not None:
            self._obs.timeline.record(
                "expired", req.req_id, priority=req.priority,
                waited_ms=round((now - req.submit_t) * 1e3, 3))

    def _preempt_candidate(self, req: Request) -> Optional[int]:
        """The decode slot a waiting ``req`` may evict: the strictly
        lower-priority (HIGHER class) live decode slot, worst class
        first, latest-admitted within a class (least progress lost).
        Raw classes compare — aging promotes queue ORDER, not the right
        to evict running work. None when no slot is evictable."""
        cand = [(s.req.priority, s.req.admit_t or 0.0, i)
                for i, s in enumerate(self._slots)
                if s.phase == "decode"]
        if not cand:
            return None
        cls, _, slot_id = max(cand)
        return slot_id if cls > req.priority else None

    def _preempt(self, slot_id: int) -> int:
        """Evict a decode slot: the victim's KV pages stay attached in
        the BlockManager and its decode carry (seq_len, last token) is
        saved on the request, so the requeued entry — re-inserted at
        its ORIGINAL line position within its class — resumes decode
        bit-identically to the un-preempted run."""
        slot = self._slots[slot_id]
        req = slot.req
        req.resume = (slot.seq_len, int(self._h_tok[slot_id]))
        req.preemptions += 1
        self.counters["preemptions"] += 1
        self.counters["requeues"] += 1
        # requeue the request's ORIGINAL entry: class, submit time and
        # line seq survive, the requeue count ticks, and started=True
        # exempts it from deadline expiry (its admission SLO was met)
        self._queue.requeue(req.qentry)
        if self._obs is not None:
            self._obs.timeline.record(
                "preempt", req.req_id, slot=slot_id,
                priority=req.priority,
                gen_tokens=len(req.tokens), seq_len=slot.seq_len)
        self._clear_slot(slot_id)
        return slot_id

    def _admit_resume(self, slot_id: int, req: Request, now: float):
        """Re-enter decode from saved carry: the slot gets exactly the
        values the vacated slot held (or, for a disaggregated handoff,
        the prefill group's first-token carry), so the decode stream
        continues bit-identically."""
        seq_len, tok = req.resume
        req.resume = None
        table = self.mgr.tables.get(req.req_id)
        if not table:
            raise RuntimeError(
                f"resume of request {req.req_id} without attached KV "
                "pages — preemption must retain the victim's pages")
        slot = self._slots[slot_id]
        slot.req = req
        slot.phase = "decode"
        slot.seq_len = seq_len
        slot.prefill_pos = int(req.prompt.size)
        self._slot_tables[slot_id] = 0
        self._slot_tables[slot_id, :len(table)] = table
        self._slot_wtables[slot_id] = self._slot_tables[slot_id]
        self._h_tok[slot_id] = tok
        self._h_seq[slot_id] = seq_len
        self._h_tables[slot_id] = self._slot_tables[slot_id]
        self._h_temps[slot_id] = self._temp_of(req.gen)
        self._dirty = True
        self._record_admit(req, slot_id, now)

    def _run_prefill(self) -> bool:
        for slot_id, slot in enumerate(self._slots):
            if slot.phase != "prefill":
                continue
            req = slot.req
            S = req.prompt.size
            pos0 = slot.prefill_pos
            n = min(S - pos0, self.buckets[-1])
            P = self._bucket_for(n)
            # the program cache keys the bucket AND the kernel route
            # (force pins / VMEM budget / interpret override) exactly
            # like generation.py's _PAGED_CACHE: a program traced under
            # a pin must not be replayed for unpinned calls
            pk = (P,) + self._prefill_route_key()
            fn = self._prefill_fns.get(pk)
            if fn is None:
                fn = self._prefill_fns[pk] = self._make_prefill_fn(P)
                self._prefill_kind[pk] = ("pallas"
                                          if self._prefill_fused_for(P)
                                          else "ref")
            toks = np.zeros((1, P), np.int32)
            toks[0, :n] = req.prompt[pos0:pos0 + n]
            t0 = self._clock() if self._obs is not None else 0.0
            if self._flight is not None:
                inv = self._coll_prefill.get(P)
                if inv is None:
                    inv = self._coll_prefill[P] = tuple(
                        self._mesh.collective_inventory(self.cfg, B=1,
                                                        chunk=P))
                tasks = self._record_collectives(inv)
            else:
                tasks = None
            # pos0/last_idx ride at the platform default int width so
            # the literal indices inside cached_forward's dynamic
            # slices promote consistently whether or not x64 is on
            tok, self._d_key, self._k_pools, self._v_pools = fn(
                self.params, jnp.asarray(toks), jnp.asarray(pos0),
                jnp.asarray(self._slot_tables[slot_id].copy()),
                jnp.asarray(self._slot_wtables[slot_id].copy()),
                jnp.asarray(n - 1),
                jnp.asarray(self._temp_of(req.gen), jnp.float32),
                self._d_key, self._k_pools, self._v_pools)
            self._end_collectives(tasks)
            self.counters["prefill_chunks"] += 1
            self.counters["prefill_tokens"] += n
            self.counters["prefill_pad_tokens"] += P - n
            if self._obs is not None:
                # host dispatch time only (the chunk completes async on
                # device; forcing it here would ADD a sync to the loop)
                dur_ms = (self._clock() - t0) * 1e3
                self._obs.hist("prefill_chunk_ms").observe(dur_ms)
                self._obs.timeline.record(
                    "prefill_chunk", req.req_id, dur_ms=dur_ms,
                    pos0=pos0, n=n, bucket=P,
                    variant=self._prefill_kind.get(pk, "ref"))
            slot.prefill_pos += n
            if slot.prefill_pos < S:
                # mid-prompt chunk done: the chunked-prefill handoff
                # hook (disagg.py streams completed pages to the decode
                # group while later chunks still run). No-op here.
                self._on_prefill_chunk(slot_id)
            if slot.prefill_pos == S:
                first = int(np.asarray(tok))
                req.first_token_t = self._clock()
                req.ttft = req.first_token_t - req.submit_t
                req.tokens.append(first)
                if self._obs is not None:
                    self._obs.timeline.record(
                        "first_token", req.req_id,
                        ttft_ms=round(req.ttft * 1e3, 3))
                self.counters["tokens_generated"] += 1
                slot.seq_len = S
                if self._pcache is not None:
                    # the prompt's KV is fully valid NOW — index it so
                    # concurrent requests sharing the prefix hit while
                    # this one is still decoding. Decode appends at
                    # positions >= S, beyond every position the tree
                    # claims of these pages, so sharing them live is
                    # safe; _finish later extends the index with the
                    # generated tokens.
                    self._pcache.insert(
                        req.prompt,
                        list(self.mgr.tables.get(req.req_id, ())))
                self._on_prefill_complete(slot_id, first)
            return True
        return False

    def _on_prefill_chunk(self, slot_id: int):
        """Hook: one mid-prompt prefill chunk completed (the slot's
        ``prefill_pos`` already advanced, more prompt remains). The
        disaggregated prefill worker overrides this to stream the
        chunk's completed KV pages to the decode group."""

    def offload_metrics(self) -> Dict:
        """The host-tier report the fleet aggregates across replicas:
        page counts from the radix tree + bytes from the engine
        counters. All zeros without ``kv_offload``."""
        pc = self._pcache.stats if self._pcache is not None else {}
        return {
            "spilled_pages": pc.get("spilled_pages", 0),
            "restored_pages": pc.get("restored_pages", 0),
            "readopted_pages": pc.get("readopted_pages", 0),
            "host_evicted_pages": pc.get("host_evicted_pages", 0),
            "host_pages": (self._pcache.host_pages
                           if self._pcache is not None else 0),
            "spill_bytes": self.counters["kv_spill_bytes"],
            "restore_bytes": self.counters["kv_restore_bytes"],
        }

    def _on_prefill_complete(self, slot_id: int, first: int):
        """Prompt fully prefilled and first token sampled: transition
        the slot to decode (or finish on EOS / single-token budget).
        The disaggregated prefill worker overrides this to hand the
        request's KV pages to the decode group instead."""
        slot = self._slots[slot_id]
        req = slot.req
        if (first == req.gen.eos_token_id
                or req.gen.max_new_tokens <= 1):
            self._finish(slot_id)
        else:
            slot.phase = "decode"
            self._h_tok[slot_id] = first
            self._h_seq[slot_id] = slot.seq_len
            self._h_tables[slot_id] = self._slot_tables[slot_id]
            self._h_temps[slot_id] = self._temp_of(req.gen)
            self._dirty = True

    def _run_decode(self) -> bool:
        live = [i for i, s in enumerate(self._slots)
                if s.phase == "decode"]
        if not live:
            return False
        if self._decode_fn is None:
            self._decode_fn = self._make_decode_fn()
        if self._dirty:
            self._d_tok = self._upload(self._h_tok.copy())
            self._d_seq = self._upload(self._h_seq.copy())
            self._d_tables = self._upload(self._h_tables.copy())
            self._d_temps = self._upload(self._h_temps.copy())
            self._dirty = False
        t0 = self._clock() if self._obs is not None else 0.0
        tasks = self._record_collectives(self._coll_decode)
        (self._d_tok, self._d_seq, self._d_key, self._k_pools,
         self._v_pools) = self._decode_fn(
            self.params, self._d_tok, self._d_seq, self._d_tables,
            self._d_temps, self._d_key, self._k_pools, self._v_pools)
        nxt = np.asarray(self._d_tok)       # the per-step host sync
        self._end_collectives(tasks)
        self.counters["decode_steps"] += 1
        self.counters["live_slot_steps"] += len(live)
        if self._obs is not None:
            # dispatch-to-sync wall time: the d2h read above already
            # synchronizes every step, so this measures real step
            # latency without adding any device round-trip
            dur_ms = (self._clock() - t0) * 1e3
            self._obs.hist("decode_step_ms").observe(dur_ms)
            # per-variant attribution, mirroring the prefill chunk's
            # ``variant`` stamp: which decode-block implementation
            # served this step (tools/trace_summary.py --mode serving)
            v = self.decode_variant
            dv = v["block"] if v["block"] == "pallas_block" \
                else v["attn"]
            self._obs.timeline.record("decode_step", dur_ms=dur_ms,
                                      live_slots=len(live),
                                      decode_variant=dv)
        for i in live:
            slot = self._slots[i]
            req = slot.req
            t = int(nxt[i])
            req.tokens.append(t)
            self.counters["tokens_generated"] += 1
            slot.seq_len += 1
            self._h_seq[i] = slot.seq_len
            self._h_tok[i] = t
            if (t == req.gen.eos_token_id
                    or len(req.tokens) >= req.gen.max_new_tokens):
                self._finish(i)
        return True

    def _finish(self, slot_id: int):
        slot = self._slots[slot_id]
        req = slot.req
        req.done = True
        req.finish_t = self._clock()
        if self._obs is not None:
            n_gen = len(req.tokens)
            tpot_ms = (((req.finish_t - req.first_token_t)
                        / (n_gen - 1)) * 1e3
                       if n_gen > 1 and req.first_token_t is not None
                       else None)
            rec = {
                "req_id": req.req_id,
                "prompt_tokens": int(req.prompt.size),
                "gen_tokens": n_gen,
                "queue_wait_ms": (round((req.admit_t - req.submit_t)
                                        * 1e3, 3)
                                  if req.admit_t is not None else None),
                "ttft_ms": (round(req.ttft * 1e3, 3)
                            if req.ttft is not None else None),
                "tpot_ms": (round(tpot_ms, 3)
                            if tpot_ms is not None else None),
                "e2e_ms": round((req.finish_t - req.submit_t) * 1e3, 3),
                "priority": req.priority,
                **({"preemptions": req.preemptions}
                   if req.preemptions else {}),
            }
            # a request whose first token predates the last reset
            # carries a warmup-measured TTFT: keep its record but
            # exclude it from the histograms — the SAME predicate
            # metrics() uses for ttft_ms_mean/max, so the two never
            # disagree within one snapshot
            cut = self._metrics_reset_t
            self._obs.observe_request(
                rec, stale=(cut is not None
                            and req.first_token_t is not None
                            and req.first_token_t < cut))
            self._obs.timeline.record("finish", req.req_id,
                                      gen_tokens=n_gen)
        if self._pcache is not None and slot.seq_len > 0:
            # hand the pages to the radix tree instead of freeing them.
            # Valid KV covers prompt + all generated tokens except the
            # last sampled one (its KV was never written): that is
            # exactly slot.seq_len positions.
            gen_n = slot.seq_len - req.prompt.size
            seq = np.concatenate(
                [req.prompt, np.asarray(req.tokens[:gen_n], np.int32)])
            self._pcache.insert(
                seq, list(self.mgr.tables.get(req.req_id, ())))
        self.mgr.release(req.req_id)
        self._clear_slot(slot_id)
        self.counters["requests_completed"] += 1

    def _clear_slot(self, slot_id: int):
        """Vacate a slot WITHOUT touching the request's KV pages: the
        finish path releases them first; preemption and the
        disaggregated handoff deliberately keep them attached."""
        slot = self._slots[slot_id]
        slot.req = None
        slot.phase = "idle"
        slot.seq_len = 0
        slot.prefill_pos = 0
        self._slot_tables[slot_id] = 0
        self._slot_wtables[slot_id] = 0
        self._h_tok[slot_id] = 0
        self._h_seq[slot_id] = 0
        self._h_tables[slot_id] = 0
        self._h_temps[slot_id] = 0.0
        self._dirty = True          # vacated slot must not be written

    # -- jitted programs ----------------------------------------------
    # decode step args: (params, tok, seq_lens, tables, temps, key,
    # k_pools, v_pools) -> (tok, seq_lens, key, k_pools, v_pools).
    # ONE declaration of which args are donated and which outputs feed
    # which args next call — _make_decode_fn and program_specs both
    # read these, so the audit spec cannot drift from the program
    _DECODE_DONATE = (1, 2, 5, 6, 7)
    _DECODE_CARRY = {0: 1, 1: 2, 2: 5, 3: 6, 4: 7}   # out idx -> argnum
    # prefill chunk args: (params, toks, pos0, table, wtable, last_idx,
    # temp, key, k_pools, v_pools) -> (tok, key, k_pools, v_pools)
    _PREFILL_DONATE = (7, 8, 9)
    _PREFILL_CARRY = {1: 7, 2: 8, 3: 9}

    def _make_decode_fn(self, record_variant=True):
        if self._mesh is not None:
            return self._make_decode_fn_tp(record_variant)
        cfg, counters = self.cfg, self.counters
        scales = self._kv_scales    # closed over: fixed after calibration
        fused = self._fused
        if fused:
            decode_step = functools.partial(_fused_decode_step,
                                            mode=fused)
        else:
            decode_step = _paged_decode_step

        def step(params, tok, seq_lens, tables, temps, key,
                 k_pools, v_pools):
            counters["decode_traces"] += 1
            if fused and record_variant:
                # trace-time snapshot: the same dispatch the
                # decode_step below consults, captured in the same
                # context, so decode_variant reports compiled reality.
                # Audit clones (program_specs) trace under their own
                # pins/env and must not clobber the live report
                self._decode_variant = self._resolve_variant()
            logits, k_pools, v_pools = decode_step(
                params, tok, cfg, k_pools, v_pools, tables, seq_lens,
                kv_scales=scales)
            key, sub = jax.random.split(key)
            nxt = _sample_slots(logits, sub, temps)
            # inactive (padded) slots hold seq 0 and stay there; their
            # write above landed in scratch page 0, never read
            seq_lens = jnp.where(seq_lens > 0, seq_lens + 1, 0)
            return nxt, seq_lens, key, k_pools, v_pools

        # donate the whole carried state, not just the pools: tok/seq/
        # key are replaced by this call's outputs every step (on host
        # mutation the mirrors re-upload fresh arrays), so the old
        # buffers update in place — the donation audit's own finding
        return jax.jit(step, donate_argnums=self._DECODE_DONATE)

    def _make_decode_fn_tp(self, record_variant=True):
        """The tensor-parallel decode program: the SAME signature,
        donation and carry contract as the single-device one — the
        per-shard forward (inference/tp.py) runs under shard_map over
        the ServingMesh, sampling runs on the replicated logits outside
        it. Still ONE jitted program; admission/completion never change
        shapes, so steady state stays zero retraces."""
        cfg, counters = self.cfg, self.counters
        scales = self._kv_scales
        fused = self._fused
        sm = self._mesh
        sharded = sm.sharded_decode_fn(cfg, fused,
                                       quant=scales is not None,
                                       params=self.params)

        def step(params, tok, seq_lens, tables, temps, key,
                 k_pools, v_pools):
            counters["decode_traces"] += 1
            if fused and record_variant:
                self._decode_variant = self._resolve_variant()
            extra = tuple(scales) if scales is not None else ()
            logits, k_pools, v_pools = sharded(
                params, tok, seq_lens, tables, k_pools, v_pools, *extra)
            key, sub = jax.random.split(key)
            nxt = _sample_slots(logits, sub, temps)
            seq_lens = jnp.where(seq_lens > 0, seq_lens + 1, 0)
            return nxt, seq_lens, key, k_pools, v_pools

        return jax.jit(step, donate_argnums=self._DECODE_DONATE)

    def _prefill_route_key(self):
        """The fused-prefill route's contribution to the per-bucket
        program cache key (empty when the knob is off)."""
        return _prefill_route(self._fused_prefill) \
            if (self._fused_prefill and self._prefill_mesh_ok) else ()

    def _prefill_meta(self, P: int):
        from ..ops.pallas.fused_prefill_block import prefill_meta
        return prefill_meta(self.cfg, P, self.block_size,
                            self.max_blocks, self._k_pools.dtype,
                            self._quant, weight_dtype=self._wq)

    def _prefill_fused_for(self, P: int) -> bool:
        """Whether bucket ``P``'s chunk program should be the
        pool-direct fused one: ALL-OR-NOTHING — both prefill-block ops
        must resolve to the Pallas megakernels, otherwise the verbatim
        pre-fusion chunk runs (bit-identical by construction)."""
        if not self._fused_prefill or not self._prefill_mesh_ok:
            return False
        from ..ops.pallas.fused_prefill_block import (
            prefill_fused_selected)
        return prefill_fused_selected(self._prefill_meta(P),
                                      self._fused_prefill)

    @property
    def prefill_variant(self) -> Dict:
        """Which prefill-chunk implementation this engine's bucket
        programs run: ``{"mode": ..., "attn": ..., "mlp": ...}`` —
        captured when a fused chunk TRACES (the decode_variant
        contract); before that, what dispatch would pick now for the
        largest bucket."""
        if not self._fused_prefill or not self._prefill_mesh_ok:
            return {"mode": "unfused", "attn": "unfused",
                    "mlp": "unfused"}
        if self._prefill_variant is not None:
            return dict(self._prefill_variant)
        from ..ops.pallas.fused_prefill_block import (
            resolve_prefill_blocks)
        _, _, names = resolve_prefill_blocks(
            self._prefill_meta(self.buckets[-1]), self._fused_prefill)
        return {"mode": str(self._fused_prefill), **names}

    def _make_prefill_fn_fused(self, P: int, record_variant=True):
        """The pool-direct fused chunk program for bucket ``P``: same
        signature, donation and <=1-trace-per-bucket contract as the
        unfused chunk, but per layer ONE fused attention kernel over
        the paged history + ONE fused MLP kernel, with the chunk's K/V
        scattered through the WRITE table (only the chunk's own
        positions move — not the whole dense view) and ragged
        valid-length bounds skipping pad compute."""
        cfg, counters = self.cfg, self.counters
        MB, BS = self.max_blocks, self.block_size
        scales = self._kv_scales
        mode = self._fused_prefill
        counters["prefill_traces"].setdefault(P, 0)

        def chunk(params, toks, pos0, table, wtable, last_idx, temp,
                  key, k_pools, v_pools):
            counters["prefill_traces"][P] += 1
            if record_variant:
                # trace-time snapshot: the same dispatch the forward
                # below consults, captured in the same context (the
                # decode_variant idiom; audit clones must not clobber)
                from ..ops.pallas.fused_prefill_block import (
                    resolve_prefill_blocks)
                _, _, names = resolve_prefill_blocks(
                    self._prefill_meta(P), mode)
                self._prefill_variant = {"mode": str(mode), **names}
            n_valid = (jnp.asarray(last_idx, jnp.int32)
                       + jnp.int32(1))
            logits, k_pools, v_pools = _fused_prefill_forward(
                params, toks[0], cfg, k_pools, v_pools, table, wtable,
                pos0, n_valid, kv_scales=scales, mode=mode)
            lg = jax.lax.dynamic_slice_in_dim(logits, last_idx, 1,
                                              axis=0)
            key, sub = jax.random.split(key)
            tok = _sample_slots(lg, sub, temp[None])[0]
            return tok, key, k_pools, v_pools

        return jax.jit(chunk, donate_argnums=self._PREFILL_DONATE)

    def _make_prefill_fn(self, P: int, record_variant=True):
        if self._prefill_fused_for(P):
            return self._make_prefill_fn_fused(
                P, record_variant=record_variant)
        if self._mesh is not None:
            return self._make_prefill_fn_tp(P)
        return self._make_prefill_fn_ref(P)

    def _make_prefill_fn_ref(self, P: int):
        """The verbatim pre-fusion chunk: gather the request's pages
        into a dense view, run ``cached_forward``, scatter the whole
        view back through the WRITE table — the fused path's
        bit-identical fallback."""
        cfg, counters = self.cfg, self.counters
        MB, BS = self.max_blocks, self.block_size
        L, KV, hd = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                     cfg.head_dim)
        scales = self._kv_scales
        counters["prefill_traces"].setdefault(P, 0)

        def chunk(params, toks, pos0, table, wtable, last_idx, temp, key,
                  k_pools, v_pools):
            counters["prefill_traces"][P] += 1
            # this request's pages as a dense [L, 1, T, KV, hd] cache:
            # the chunk runs the SAME cached_forward math as generate()'s
            # prefill, so single-request outputs match token-for-token
            kc = jnp.take(k_pools, table, axis=1) \
                .reshape(L, 1, MB * BS, KV, hd)
            vc = jnp.take(v_pools, table, axis=1) \
                .reshape(L, 1, MB * BS, KV, hd)
            if scales is not None:
                kc = dequant_cache(kc, scales[0]).astype(cfg.dtype)
                vc = dequant_cache(vc, scales[1]).astype(cfg.dtype)
            logits, kc, vc = cached_forward(params, toks, cfg, kc, vc,
                                            pos0)
            if scales is not None:
                kc = quant_cache(kc, scales[0])
                vc = quant_cache(vc, scales[1])
            # the scatter goes through the WRITE table: entries backed
            # by shared prefix-cache pages are redirected to scratch
            # page 0 there, so the chunk cannot corrupt a shared page
            # (without a prefix cache wtable == table)
            k_pools = k_pools.at[:, wtable].set(
                kc.reshape(L, MB, BS, KV, hd).astype(k_pools.dtype))
            v_pools = v_pools.at[:, wtable].set(
                vc.reshape(L, MB, BS, KV, hd).astype(v_pools.dtype))
            # sample the request's FIRST token from the last valid
            # position (only meaningful on the final chunk)
            lg = jax.lax.dynamic_slice_in_dim(logits, last_idx, 1,
                                              axis=1)[:, 0]
            key, sub = jax.random.split(key)
            tok = _sample_slots(lg, sub, temp[None])[0]
            return tok, key, k_pools, v_pools

        # key is carried state exactly like the pools: the caller
        # rebinds self._d_key to the returned key, so donate it too
        return jax.jit(chunk, donate_argnums=self._PREFILL_DONATE)

    def _make_prefill_fn_tp(self, P: int):
        """Tensor-parallel bucketed prefill chunk: the per-shard body
        gathers the request's pages into a LOCAL dense view (the page
        indices are host-global; each shard holds its slice of the
        head axis), runs the tensor-parallel ``cached_forward`` mirror
        and scatters back through the WRITE table — same signature,
        donation and <=1-trace-per-bucket contract as the single-device
        chunk."""
        from .tp import _tp_cached_forward
        cfg, counters = self.cfg, self.counters
        MB, BS = self.max_blocks, self.block_size
        L, hd = cfg.num_hidden_layers, cfg.head_dim
        scales = self._kv_scales
        sm = self._mesh
        counters["prefill_traces"].setdefault(P, 0)
        rep = sm.replicated
        in_specs = (sm.param_specs(cfg, self.params), rep, rep, rep,
                    rep, sm.pool_spec, sm.pool_spec)
        if scales is not None:
            in_specs += (sm.scale_spec, sm.scale_spec)

        def fwd(params, toks, pos0, table, wtable, k_pools, v_pools,
                *sc):
            KV_l = k_pools.shape[3]       # local KV heads of this shard
            kc = jnp.take(k_pools, table, axis=1) \
                .reshape(L, 1, MB * BS, KV_l, hd)
            vc = jnp.take(v_pools, table, axis=1) \
                .reshape(L, 1, MB * BS, KV_l, hd)
            if sc:
                kc = dequant_cache(kc, sc[0]).astype(cfg.dtype)
                vc = dequant_cache(vc, sc[1]).astype(cfg.dtype)
            logits, kc, vc = _tp_cached_forward(
                params, toks, cfg, kc, vc, pos0, axis=sm.axis,
                collective=sm.collective)
            if sc:
                kc = quant_cache(kc, sc[0])
                vc = quant_cache(vc, sc[1])
            k_pools = k_pools.at[:, wtable].set(
                kc.reshape(L, MB, BS, KV_l, hd).astype(k_pools.dtype))
            v_pools = v_pools.at[:, wtable].set(
                vc.reshape(L, MB, BS, KV_l, hd).astype(v_pools.dtype))
            return logits, k_pools, v_pools

        sharded = shard_map_norep(fwd, sm.mesh, in_specs,
                                  (rep, sm.pool_spec, sm.pool_spec))

        def chunk(params, toks, pos0, table, wtable, last_idx, temp,
                  key, k_pools, v_pools):
            counters["prefill_traces"][P] += 1
            extra = tuple(scales) if scales is not None else ()
            logits, k_pools, v_pools = sharded(
                params, toks, pos0, table, wtable, k_pools, v_pools,
                *extra)
            lg = jax.lax.dynamic_slice_in_dim(logits, last_idx, 1,
                                              axis=1)[:, 0]
            key, sub = jax.random.split(key)
            tok = _sample_slots(lg, sub, temp[None])[0]
            return tok, key, k_pools, v_pools

        return jax.jit(chunk, donate_argnums=self._PREFILL_DONATE)

    def _calibrate(self, prompt: np.ndarray):
        cfg, counters = self.cfg, self.counters
        P = self._bucket_for(min(int(prompt.size), self.buckets[-1]))
        if self._calib_fn is None or self._calib_bucket != P:
            def calib(params, toks):
                counters["calibration_traces"] += 1
                kc, vc = init_cache(cfg, 1, toks.shape[1],
                                    dtype=cfg.dtype)
                _, kc, vc = cached_forward(params, toks, cfg, kc, vc, 0)
                amax = lambda x: jnp.max(                  # noqa: E731
                    jnp.abs(x.astype(jnp.float32)), axis=(1, 2, 4))
                return amax(kc), amax(vc)
            self._calib_fn = jax.jit(calib)
            self._calib_bucket = P
        toks = np.zeros((1, P), np.int32)
        n = min(int(prompt.size), P)
        toks[0, :n] = prompt[:n]
        k_amax, v_amax = self._calib_fn(self.params, jnp.asarray(toks))
        self._kv_scales = (jnp.maximum(k_amax / 127.0, 1e-8),
                           jnp.maximum(v_amax / 127.0, 1e-8))

    # -- static program audit -----------------------------------------
    def program_specs(self, register: bool = True):
        """:class:`paddle_tpu.analysis.ProgramSpec` entries for the
        engine's jitted programs — the decode step, one prefill per
        bucket, and (with a prefix cache) the COW page copier — with
        abstract signatures derived from the engine's own shapes. The
        fns are FRESH jit instances, so auditing them can never disturb
        the live programs' compilation caches; their traced python
        bodies do tick the trace counters, which :meth:`audit`
        snapshots and restores."""
        from ..analysis import ProgramSpec, REGISTRY, abstract_signature
        sds = jax.ShapeDtypeStruct
        C, MB = self.capacity, self.max_blocks
        params_sd = abstract_signature(self.params)
        pools_sd = abstract_signature(self._k_pools)
        key_sd = abstract_signature(self._d_key)
        n_p = len(jax.tree_util.tree_leaves(params_sd))
        # arg 0 is the params pytree (n_p flat leaves); every later
        # arg is a single leaf, so argnum k>0 sits at flat index
        # n_p + (k - 1) — the class-level carry maps (argnum-keyed, the
        # same declarations the jit donate_argnums read) convert here
        flat = lambda argnum: n_p + argnum - 1          # noqa: E731
        # a FORCED-pallas engine registers the fused decode program
        # under its own name so the audit gate covers the megakernel
        # path next to (not instead of) the default program; a mesh'd
        # engine suffixes _tp the same way (the collective-consistency
        # rule gates the sharded programs against the DECLARED axes)
        sm = self._mesh
        tp_sfx = "_tp" if sm is not None else ""
        axes = (sm.axis,) if sm is not None else ()
        tags = ("serving",) + (("tp",) if sm is not None else ())
        decode_name = ("serving_decode_fused"
                       if self._fused in ("pallas",)
                       else "serving_decode_block"
                       if self._fused in ("block",)
                       else "serving_decode")
        # a forced-pallas-PREFILL engine registers its bucket programs
        # under their own name the same way (the audit gate covers the
        # fused chunk next to, not instead of, the default program)
        prefill_base = ("serving_prefill_fused"
                        if self._fused_prefill in ("pallas",)
                        else "serving_prefill")
        specs = [ProgramSpec(
            name=decode_name + tp_sfx, fn=self._make_decode_fn(
                record_variant=False),
            args=(params_sd, sds((C,), jnp.int32), sds((C,), jnp.int32),
                  sds((C, MB), jnp.int32), sds((C,), jnp.float32),
                  key_sd, pools_sd, pools_sd),
            donate_argnums=self._DECODE_DONATE,
            carry={o: flat(a) for o, a in self._DECODE_CARRY.items()},
            mesh_axes=axes, tags=tags)]
        # pos0/last_idx ride at the platform default int width
        # (serving._run_prefill stages them with a bare jnp.asarray)
        idx_dt = jnp.asarray(0).dtype
        for P in self.buckets:
            specs.append(ProgramSpec(
                name=f"{prefill_base}{tp_sfx}_{P}",
                fn=self._make_prefill_fn(P, record_variant=False),
                args=(params_sd, sds((1, P), jnp.int32), sds((), idx_dt),
                      sds((MB,), jnp.int32), sds((MB,), jnp.int32),
                      sds((), idx_dt), sds((), jnp.float32), key_sd,
                      pools_sd, pools_sd),
                donate_argnums=self._PREFILL_DONATE,
                carry={o: flat(a)
                       for o, a in self._PREFILL_CARRY.items()},
                mesh_axes=axes, tags=tags))
        if self._pcache is not None:
            specs.append(ProgramSpec(
                name="serving_page_copy" + tp_sfx, fn=self._copy_fn,
                args=(pools_sd, pools_sd, sds((), jnp.int32),
                      sds((), jnp.int32)),
                donate_argnums=(0, 1), carry={0: 0, 1: 1},
                mesh_axes=axes, tags=tags))
        if self._kv_offload:
            # the host-tier handoff pair (fresh jit instances — the
            # disagg_kv_extract/insert idiom): a single-page gather out
            # of the pools and the donated single-page scatter back
            ext, ins = self._make_offload_fns()
            ps = self._k_pools.shape
            W = self._offload_window
            page_sd = sds((ps[0], W) + ps[2:], self._k_pools.dtype)
            idx_sd = sds((W,), jnp.int32)
            specs.append(ProgramSpec(
                name="serving_kv_spill_extract" + tp_sfx, fn=ext,
                args=(pools_sd, pools_sd, idx_sd),
                mesh_axes=axes, tags=tags + ("offload",)))
            specs.append(ProgramSpec(
                name="serving_kv_restore_insert" + tp_sfx, fn=ins,
                args=(pools_sd, pools_sd, idx_sd, page_sd, page_sd),
                donate_argnums=(0, 1), carry={0: 0, 1: 1},
                mesh_axes=axes, tags=tags + ("offload",)))
        if register:
            for s in specs:
                REGISTRY.register(s)
        return specs

    def audit(self, register: bool = True):
        """Static audit of every engine program (trace-only — nothing
        executes, live compiled programs are untouched, and the trace
        counters the tier-1 suite pins are snapshotted/restored).
        Returns the list of :class:`AuditReport`; the finding count
        lands in the ``audit_findings`` counter."""
        from ..analysis import audit_spec as _audit, publish_findings
        import copy
        snap = {k: copy.deepcopy(self.counters[k])
                for k in ("decode_traces", "prefill_traces",
                          "calibration_traces", "offload_traces")}
        try:
            reports = [_audit(s)
                       for s in self.program_specs(register=register)]
        finally:
            self.counters.update(snap)
        publish_findings(reports, counters=self.counters, obs=self._obs)
        return reports
