"""Tensor-parallel serving over a named mesh.

ROADMAP item 1, stage 1: shard the serving hot path — the paged KV
pools, the QKV/o-proj/MLP weights and the per-slot attention
computation — along the HEAD axis of a 1-D named mesh via ``shard_map``
(through the ``core/jax_compat.py`` shims), so ``ServingEngine`` /
``generate_paged`` keep running ONE jitted decode program and <=1
prefill program per bucket while N chips split the attention bandwidth
and hold N× the resident KV pages (FlashFuser's inter-core scaling
argument; ClusterFusion++'s full-block decode model — PAPERS.md).

Sharding scheme (:func:`paddle_tpu.models.llama.tp_param_specs`):

- KV pools ``[L, N_pages, BS, KV, hd]`` shard axis 3 (KV heads). The
  page TABLES stay host-global — a page index names the same physical
  page on every shard, each shard holding that page's slice of the
  head axis — so the ``BlockManager``, the radix prefix cache, COW
  forks and LRU eviction work completely unchanged.
- q/k/v/gate/up projections shard their OUTPUT columns (head-major, so
  a contiguous column range is a contiguous head range); embedding,
  norms and lm_head stay replicated — the residual stream ``x`` is
  replicated everywhere, which is what lets sampling run identically
  on every shard and the host read one logical token array.

Collective placement — ``ServingMesh.collective``:

- ``"psum"`` (default, bandwidth-optimal): o_proj/down_proj row-shard;
  each sub-block computes a partial product over its local heads /
  intermediate columns and ONE ``psum`` per sub-block (2 per layer)
  rebuilds the replicated residual. Greedy output is ROUNDOFF-parity
  vs the single-device engine: the all-reduce sums N partial matmul
  reductions in a different association order than the single fused
  reduction (the PR-6 mode=pallas precedent — documented, and the
  tests pin token-level agreement).
- ``"gather"`` (the documented bit-identical mode): o_proj/down_proj
  stay replicated; the per-shard attention heads / SwiGLU columns
  all-gather back to the full tensor FIRST, so every matmul sees
  exactly the single-device operands, shapes and reduction order.
  Greedy output is BIT-identical to the single-device engine (the
  tier-1 suite asserts it over a mixed-arrival stream).

Both placements run the transformer math through the PR-6 kernel
registry: the per-shard dims (local head/intermediate counts) plus the
``tp`` degree feed ``decode_meta_dims``, so on TPU the fused decode
megakernels dispatch per shard — ``residual=False`` returns the bare
o/down projection partial for the psum placement — and everywhere else
the EXACT unfused composition runs (``"gather"`` always uses the
composition: its bit-parity contract is defined by the single-device
op sequence).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.jax_compat import axis_size, shard_map_norep

__all__ = ["ServingMesh", "tp_reject_reason", "normalize_mesh"]

_COLLECTIVES = ("psum", "gather")


def normalize_mesh(mesh) -> Optional["ServingMesh"]:
    """None | ServingMesh | 1-D jax Mesh | int tp -> ServingMesh|None —
    the one mesh-argument normalization serving.py and generation.py
    share."""
    if mesh is None:
        return None
    if isinstance(mesh, ServingMesh):
        return mesh
    if isinstance(mesh, int):
        return ServingMesh.make(tp=mesh)
    if isinstance(mesh, Mesh):
        axes = list(mesh.shape)
        if len(axes) != 1:
            raise ValueError(
                f"serving needs a 1-D mesh, got axes {dict(mesh.shape)}"
                " (wrap a ServingMesh to name the tp axis explicitly)")
        return ServingMesh(mesh, axis=axes[0])
    raise TypeError(f"mesh must be ServingMesh | jax Mesh | int | None,"
                    f" got {type(mesh).__name__}")


def tp_reject_reason(cfg, tp: int) -> Optional[str]:
    """Why ``cfg`` cannot shard over ``tp`` shards — None when it can.
    The clean fallback reason string: head-axis sharding needs every
    sharded dimension to divide evenly (a ragged shard would change
    shapes per device and break the single-program contract)."""
    if tp == 1:
        return None
    checks = (("num_key_value_heads", cfg.num_key_value_heads),
              ("num_attention_heads", cfg.num_attention_heads),
              ("intermediate_size", cfg.intermediate_size))
    for name, v in checks:
        if v % tp != 0:
            return (f"{name}={v} is not divisible by tp={tp}: head-axis "
                    f"sharding needs {name} % tp == 0 (use a divisor of "
                    f"{v}, or tp=1)")
    return None


@dataclasses.dataclass(frozen=True)
class ServingMesh:
    """The serving stack's tensor-parallel mesh: a 1-D device mesh, its
    axis name, and the collective placement. Holds the one definition
    of every NamedSharding the sharded programs use (pools, weights,
    replicated slot state), so serving.py / generation.py / the audit
    catalog cannot drift apart on layout.

    Build with :meth:`make` (first ``tp`` visible devices) or wrap an
    existing 1-D :class:`jax.sharding.Mesh`.
    """
    mesh: Mesh
    axis: str = "tp"
    collective: str = "psum"

    def __post_init__(self):
        if self.collective not in _COLLECTIVES:
            raise ValueError(f"collective must be one of {_COLLECTIVES},"
                             f" got {self.collective!r}")
        if len(self.mesh.shape) != 1 or self.axis not in self.mesh.shape:
            raise ValueError(
                f"ServingMesh needs a 1-D mesh over axis {self.axis!r}, "
                f"got mesh axes {dict(self.mesh.shape)}")

    @classmethod
    def make(cls, tp: Optional[int] = None, axis: str = "tp",
             collective: str = "psum", devices=None) -> "ServingMesh":
        devices = list(devices if devices is not None else jax.devices())
        tp = len(devices) if tp is None else int(tp)
        if tp < 1 or tp > len(devices):
            raise ValueError(f"tp={tp} but only {len(devices)} device(s)"
                             " visible")
        return cls(Mesh(np.array(devices[:tp]), (axis,)), axis=axis,
                   collective=collective)

    @property
    def tp(self) -> int:
        return int(self.mesh.shape[self.axis])

    @property
    def devices(self) -> list:
        return list(self.mesh.devices.flat)

    def split(self, first: int) -> Tuple["ServingMesh", "ServingMesh"]:
        """Split this mesh's device list into two disjoint ServingMesh
        groups: the first ``first`` devices and the remainder — the
        disaggregated engine's (prefill, decode) chip groups. Both keep
        this mesh's axis name and collective placement."""
        devs = self.devices
        if not 1 <= first < len(devs):
            raise ValueError(
                f"split(first={first}) needs 1 <= first < {len(devs)} "
                f"(the mesh has {len(devs)} device(s); both groups "
                "need at least one)")
        mk = lambda d: ServingMesh(                      # noqa: E731
            Mesh(np.array(d), (self.axis,)), axis=self.axis,
            collective=self.collective)
        return mk(devs[:first]), mk(devs[first:])

    def describe(self) -> Dict:
        return {"axis": self.axis, "tp": self.tp,
                "collective": self.collective}

    # -- shardings ----------------------------------------------------
    @property
    def pool_spec(self) -> P:
        """KV pools [L, N_pages, BS, KV, hd]: shard the KV-head axis."""
        return P(None, None, None, self.axis, None)

    @property
    def scale_spec(self) -> P:
        """int8 cache scales [L, KV]: shard with their pools."""
        return P(None, self.axis)

    @property
    def replicated(self) -> P:
        return P()

    def param_specs(self, cfg, params=None) -> Dict:
        """PartitionSpec tree for a llama param tree; pass ``params``
        when the tree may carry quantized weight leaves (the spec tree
        must mirror their dict structure)."""
        from ..models.llama import tp_param_specs
        return tp_param_specs(cfg, axis=self.axis,
                              collective=self.collective,
                              params=params)

    def sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def shard(self, tree, specs):
        """device_put a pytree onto the mesh under ``specs`` (a
        matching pytree of PartitionSpecs, or one spec for all)."""
        if isinstance(specs, P):
            sh = self.sharding(specs)
            return jax.tree_util.tree_map(
                lambda x: jax.device_put(x, sh), tree)
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, self.sharding(s)), tree,
            specs)

    def replicate(self, x):
        """Commit an array replicated onto the mesh (host-mirror
        re-uploads go through this so donation never needs a reshard)."""
        return jax.device_put(x, self.sharding(P()))

    # -- sharded program wiring ---------------------------------------
    def sharded_decode_fn(self, cfg, fused, quant: bool, params=None):
        """The shard_map'd per-step decode forward: ``(params, tok,
        seq_lens, tables, k_pools, v_pools, *scales) -> (logits,
        k_pools, v_pools)`` — the ONE wiring of in/out specs around
        :func:`_tp_decode_step`, shared by ``ServingEngine``'s decode
        program and ``generate_paged``'s chunk runner so the two can
        never desync on layout or signature. ``params``: pass the real
        tree when it may carry quantized weight leaves (spec-structure
        mirroring)."""
        rep = self.replicated
        in_specs = (self.param_specs(cfg, params), rep, rep, rep,
                    self.pool_spec, self.pool_spec)
        if quant:
            in_specs += (self.scale_spec, self.scale_spec)

        def fwd(params, tok, seq_lens, tables, k_pools, v_pools, *sc):
            return _tp_decode_step(
                params, tok, cfg, k_pools, v_pools, tables, seq_lens,
                kv_scales=(tuple(sc) if sc else None), axis=self.axis,
                collective=self.collective, fused=fused)

        return shard_map_norep(fwd, self.mesh, in_specs,
                               (rep, self.pool_spec, self.pool_spec))

    # -- validation ---------------------------------------------------
    def reject_reason(self, cfg) -> Optional[str]:
        return tp_reject_reason(cfg, self.tp)

    def supports(self, cfg) -> Tuple[bool, str]:
        """(ok, reason) — the kernel-registry ``supports()`` idiom."""
        reason = self.reject_reason(cfg)
        if reason is not None:
            return False, reason
        return True, (f"tp={self.tp} over axis {self.axis!r} "
                      f"({self.collective} placement)")

    # -- flight-recorder inventory ------------------------------------
    def collective_inventory(self, cfg, B: int, chunk: int = 1) -> list:
        """The DECLARED per-step collectives of one sharded decode step
        (or one prefill chunk of ``chunk`` tokens): [(op, axis, shape,
        dtype)] with the per-step call count folded into the leading
        shape dim, so ``CommTask.nbytes`` counts the step's full
        logical payload. The serving engine replays this inventory
        through the bound flight recorder around each dispatched step —
        host-observed spans (the engine's sync-point philosophy), with
        the byte counters exact because the shapes are static."""
        L, D = cfg.num_hidden_layers, cfg.hidden_size
        dt = str(jnp.dtype(cfg.dtype))
        if self.collective == "psum":
            # one psum per sub-block: attn o-proj partial + MLP down
            # partial, each [B or B*chunk, D]
            return [("psum", self.axis, (2 * L, B * chunk, D), dt)]
        H, hd = cfg.num_attention_heads, cfg.head_dim
        F = cfg.intermediate_size
        return [
            ("all_gather", self.axis,
             (L, B * chunk, H // self.tp, hd), dt),
            ("all_gather", self.axis, (L, B * chunk, F // self.tp), dt),
        ]


# ---------------------------------------------------------------------------
# per-shard program bodies (run INSIDE shard_map: every array below is
# the LOCAL shard; tok/seq_lens/tables and the residual stream are
# replicated)
# ---------------------------------------------------------------------------
def _wshape(w):
    """Stored shape of a weight leaf (plain array or quantized dict —
    quantization/ptq.py format). Column counts are what the local-dim
    reads below need, and int4 packing never halves the output
    columns of q/k/v/gate/up."""
    if isinstance(w, dict):
        return (w["qw8"] if "qw8" in w else w["qw4"]).shape
    return w.shape


def _local_dims(params, cfg):
    """Local head/intermediate counts, read off the sharded arrays
    (shard_map hands the body local shapes, so the arrays themselves
    are the single source of truth for what this shard owns)."""
    hd = cfg.head_dim
    H_loc = _wshape(params["layers"]["q_proj"])[2] // hd
    KV_loc = _wshape(params["layers"]["k_proj"])[2] // hd
    F_loc = _wshape(params["layers"]["gate_proj"])[2]
    return H_loc, KV_loc, F_loc


def _lm_head(params):
    head = params.get("lm_head")
    if head is None:
        head = params["embed_tokens"].T
    return head


def _tp_decode_step(params, tok, cfg, k_pools, v_pools, block_tables,
                    seq_lens, kv_scales=None, axis="tp",
                    collective="psum", fused=False):
    """One tensor-parallel decode token per slot — the per-shard body
    of the engine's single jitted decode program. Mirrors
    ``generation._paged_decode_step`` / ``_fused_decode_step`` exactly,
    with the collective placement documented in the module docstring.

    ``fused``: the decode-block route (False = the exact composition,
    "auto"/"pallas"/"ref" = registry dispatch over the PER-SHARD meta).
    The "gather" placement always runs the composition — its bit-parity
    contract IS the single-device op sequence.
    """
    from ..ops import rms_norm as fused_rms_norm
    from ..ops.paged_attention import write_to_pool, write_to_pool_quant
    from ..ops.pallas.fused_decode_block import (attn_block_ref,
                                                 decode_meta_dims,
                                                 mlp_block_ref,
                                                 resolve_decode_blocks)
    from ..ops.rope import build_rope_cache

    if fused == "block":
        # the single-launch block kernel is single-device by contract
        # (its supports() rejects tp != 1); a forced "block" under a
        # mesh is a configuration error, not a silent fallback —
        # checked before the axis-env lookup so the error fires even
        # outside shard_map
        raise ValueError("fused_decode='block' is single-device: "
                         "tensor-parallel decode runs the per-stage "
                         "kernels")
    # static axis-env lookup (jax_compat): NO collective may be emitted
    # here — the audited jaxpr carries exactly the declared collectives
    tp = int(axis_size(axis))
    B = tok.shape[0]
    H_loc, KV_loc, F_loc = _local_dims(params, cfg)
    quant = kv_scales is not None
    if collective == "gather":
        return _tp_decode_step_gather(params, tok, cfg, k_pools,
                                      v_pools, block_tables, seq_lens,
                                      kv_scales, axis)
    if fused:
        from .generation import _wq_mode
        meta = decode_meta_dims(
            B, cfg.hidden_size, H_loc, KV_loc, cfg.head_dim, F_loc,
            k_pools.shape[2], block_tables.shape[1], cfg.dtype,
            k_pools.dtype, quant, tp=tp,
            weight_dtype=_wq_mode(params))
        attn_fn, mlp_fn, _ = resolve_decode_blocks(meta, fused)
    else:
        attn_fn, mlp_fn = attn_block_ref, mlp_block_ref

    x = jnp.take(params["embed_tokens"], tok, axis=0)          # [B, D]
    sin, cos = build_rope_cache(cfg.max_position_embeddings,
                                cfg.head_dim, base=cfg.rope_theta)

    def layer(x, xs):
        if kv_scales is None:
            lp, kp, vp = xs
            scales = None
        else:
            lp, kp, vp, ksc, vsc = xs
            scales = (ksc, vsc)
        part, k_new, v_new = attn_fn(
            x, lp["input_norm"].astype(x.dtype), lp["q_proj"],
            lp["k_proj"], lp["v_proj"], lp["o_proj"], sin, cos, kp, vp,
            block_tables, seq_lens, scales, cfg.rms_norm_eps,
            residual=False)
        # ONE all-reduce for the attention sub-block, then the
        # replicated residual add (partial sums associate differently
        # than the single-device reduction: roundoff-parity, documented)
        x = x + jax.lax.psum(part, axis)
        if scales is None:
            kp, vp = write_to_pool(kp, vp, block_tables, seq_lens,
                                   k_new.astype(kp.dtype),
                                   v_new.astype(vp.dtype))
        else:
            kp, vp = write_to_pool_quant(kp, vp, block_tables, seq_lens,
                                         k_new, v_new, ksc, vsc)
        part = mlp_fn(x, lp["post_norm"].astype(x.dtype),
                      lp["gate_proj"], lp["up_proj"], lp["down_proj"],
                      cfg.rms_norm_eps, residual=False)
        x = x + jax.lax.psum(part, axis)       # the MLP sub-block's one
        return x, (kp, vp)

    scan_xs = (params["layers"], k_pools, v_pools) if kv_scales is None \
        else (params["layers"], k_pools, v_pools) + tuple(kv_scales)
    x, (k_pools, v_pools) = jax.lax.scan(layer, x, scan_xs)
    x = fused_rms_norm(x[:, None], params["final_norm"].astype(x.dtype),
                       cfg.rms_norm_eps)[:, 0]
    return x @ _lm_head(params), k_pools, v_pools


def _tp_decode_step_gather(params, tok, cfg, k_pools, v_pools,
                           block_tables, seq_lens, kv_scales, axis):
    """The "gather" placement decode body: per-shard heads/columns,
    all-gather BEFORE o_proj/down_proj so those matmuls see exactly the
    single-device operands — bit-identical greedy output by
    construction (every float op has the same inputs, shapes and
    reduction order as ``_paged_decode_step``)."""
    from ..ops import rms_norm as fused_rms_norm, swiglu as fused_swiglu
    from ..ops.paged_attention import (paged_attention_decode,
                                       paged_attention_decode_quant,
                                       write_to_pool, write_to_pool_quant)
    from ..ops.rope import apply_rope, build_rope_cache
    from .generation import _mm

    H, hd = cfg.num_attention_heads, cfg.head_dim
    B = tok.shape[0]
    H_loc, KV_loc, _ = _local_dims(params, cfg)
    x = jnp.take(params["embed_tokens"], tok, axis=0)
    pos_ids = seq_lens[:, None]
    sin, cos = build_rope_cache(cfg.max_position_embeddings,
                                cfg.head_dim, base=cfg.rope_theta)

    def layer(x, xs):
        if kv_scales is None:
            lp, kp, vp = xs
        else:
            lp, kp, vp, ksc, vsc = xs
        h = fused_rms_norm(x[:, None], lp["input_norm"].astype(x.dtype),
                           cfg.rms_norm_eps)[:, 0]
        q = _mm(h, lp["q_proj"]).reshape(B, 1, H_loc, hd)
        k = _mm(h, lp["k_proj"]).reshape(B, 1, KV_loc, hd)
        v = _mm(h, lp["v_proj"]).reshape(B, 1, KV_loc, hd)
        q = apply_rope(q, sin, cos, position_ids=pos_ids)
        k = apply_rope(k, sin, cos, position_ids=pos_ids)
        if kv_scales is None:
            kp, vp = write_to_pool(kp, vp, block_tables, seq_lens,
                                   k[:, 0].astype(kp.dtype),
                                   v[:, 0].astype(vp.dtype))
            attn = paged_attention_decode(q[:, 0], kp, vp, block_tables,
                                          seq_lens + 1)
        else:
            kp, vp = write_to_pool_quant(kp, vp, block_tables, seq_lens,
                                         k[:, 0], v[:, 0], ksc, vsc)
            attn = paged_attention_decode_quant(
                q[:, 0], kp, vp, block_tables, seq_lens + 1, ksc, vsc)
        # heads shard contiguously, so tiled all-gather on the head
        # axis rebuilds the exact single-device [B, H, hd] tensor
        attn = jax.lax.all_gather(attn, axis, axis=1, tiled=True)
        x = x + _mm(attn.reshape(B, H * hd).astype(x.dtype),
                    lp["o_proj"])
        h = fused_rms_norm(x[:, None], lp["post_norm"].astype(x.dtype),
                           cfg.rms_norm_eps)[:, 0]
        ff = fused_swiglu(_mm(h, lp["gate_proj"]), _mm(h, lp["up_proj"]))
        ff = jax.lax.all_gather(ff, axis, axis=1, tiled=True)  # [B, F]
        x = x + _mm(ff, lp["down_proj"])
        return x, (kp, vp)

    scan_xs = (params["layers"], k_pools, v_pools) if kv_scales is None \
        else (params["layers"], k_pools, v_pools) + tuple(kv_scales)
    x, (k_pools, v_pools) = jax.lax.scan(layer, x, scan_xs)
    x = fused_rms_norm(x[:, None], params["final_norm"].astype(x.dtype),
                       cfg.rms_norm_eps)[:, 0]
    return x @ _lm_head(params), k_pools, v_pools


def _tp_cached_layer(lp, x, sin, cos, cfg, kc, vc, pos, axis,
                     collective):
    """Tensor-parallel mirror of ``generation._cached_layer``: decoder
    block over S new tokens at absolute position ``pos``, reading and
    writing the LOCAL slice of the dense cache (kc/vc [B, T, KV_loc,
    hd]). Same op sequence per shard; the collective placement decides
    how the residual stream is rebuilt (module docstring)."""
    from ..inference.generation import _mm, _repeat_kv
    from ..ops import rms_norm as fused_rms_norm, swiglu as fused_swiglu
    from ..ops.rope import apply_rope

    H, hd = cfg.num_attention_heads, cfg.head_dim
    b, s, _ = x.shape
    T = kc.shape[1]
    H_loc = _wshape(lp["q_proj"])[1] // hd
    KV_loc = _wshape(lp["k_proj"])[1] // hd
    h = fused_rms_norm(x, lp["input_norm"].astype(x.dtype),
                       cfg.rms_norm_eps)
    q = _mm(h, lp["q_proj"]).reshape(b, s, H_loc, hd)
    k = _mm(h, lp["k_proj"]).reshape(b, s, KV_loc, hd)
    v = _mm(h, lp["v_proj"]).reshape(b, s, KV_loc, hd)
    q = apply_rope(q, sin, cos)
    k = apply_rope(k, sin, cos)
    kc = jax.lax.dynamic_update_slice(kc, k.astype(kc.dtype),
                                      (0, pos, 0, 0))
    vc = jax.lax.dynamic_update_slice(vc, v.astype(vc.dtype),
                                      (0, pos, 0, 0))
    rep = H_loc // KV_loc                 # groups survive sharding
    kk = _repeat_kv(kc, rep)              # [B, T, H_loc, hd]
    vv = _repeat_kv(vc, rep)
    scale = 1.0 / math.sqrt(hd)
    scores = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32),
                        kk.astype(jnp.float32)) * scale
    t_idx = jnp.arange(T)[None, None, None, :]
    q_idx = pos + jnp.arange(s)[None, None, :, None]
    scores = jnp.where(t_idx <= q_idx, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    attn = jnp.einsum("bhst,bthd->bshd", probs, vv.astype(jnp.float32))
    if collective == "gather":
        attn = jax.lax.all_gather(attn, axis, axis=2, tiled=True)
        attn = attn.astype(x.dtype).reshape(b, s, H * hd)
        x = x + _mm(attn, lp["o_proj"])
    else:
        attn = attn.astype(x.dtype).reshape(b, s, H_loc * hd)
        x = x + jax.lax.psum(_mm(attn, lp["o_proj"]), axis)
    h = fused_rms_norm(x, lp["post_norm"].astype(x.dtype),
                       cfg.rms_norm_eps)
    ff = fused_swiglu(_mm(h, lp["gate_proj"]), _mm(h, lp["up_proj"]))
    if collective == "gather":
        ff = jax.lax.all_gather(ff, axis, axis=2, tiled=True)
        x = x + _mm(ff, lp["down_proj"])
    else:
        x = x + jax.lax.psum(_mm(ff, lp["down_proj"]), axis)
    return x, kc, vc


def _tp_cached_forward(params, tokens, cfg, k_cache, v_cache, pos,
                       axis="tp", collective="psum"):
    """Tensor-parallel mirror of ``generation.cached_forward`` — the
    per-shard PREFILL body. ``k_cache``/``v_cache`` are the LOCAL dense
    views [L, B, T, KV_loc, hd]; tokens and the returned logits are
    replicated. Same program structure (one scan over layers), so
    bucketed chunked prefill keeps <=1 trace per bucket."""
    from ..ops import rms_norm as fused_rms_norm
    from ..ops.rope import build_rope_cache

    x = jnp.take(params["embed_tokens"], tokens, axis=0)
    T = k_cache.shape[2]
    sin_full, cos_full = build_rope_cache(T, cfg.head_dim,
                                          base=cfg.rope_theta)
    s = tokens.shape[1]
    sin = jax.lax.dynamic_slice_in_dim(sin_full, pos, s, axis=0)
    cos = jax.lax.dynamic_slice_in_dim(cos_full, pos, s, axis=0)

    def scan_fn(carry, xs):
        lp, kc, vc = xs
        x, kc, vc = _tp_cached_layer(lp, carry, sin, cos, cfg, kc, vc,
                                     pos, axis, collective)
        return x, (kc, vc)

    x, (k_cache, v_cache) = jax.lax.scan(
        scan_fn, x, (params["layers"], k_cache, v_cache))
    x = fused_rms_norm(x, params["final_norm"].astype(x.dtype),
                       cfg.rms_norm_eps)
    return x @ _lm_head(params), k_cache, v_cache
