"""Data loading (reference: python/paddle/io/).

TPU-native design: the DataLoader keeps the reference surface (Dataset,
samplers, workers, collate) but adds device prefetch — batches are staged to
the accelerator asynchronously so input pipeline overlaps compute, replacing
the reference's shared-memory worker IPC + pin-memory path
(python/paddle/io/dataloader/dataloader_iter.py:368).
"""
from .dataset import (Dataset, IterableDataset, TensorDataset,  # noqa: F401
                      ComposeDataset, ChainDataset, Subset, ConcatDataset,
                      random_split)
from .sampler import (Sampler, SequenceSampler, RandomSampler,  # noqa: F401
                      WeightedRandomSampler, BatchSampler,
                      SubsetRandomSampler, DistributedBatchSampler)
from .dataloader import (DataLoader, default_collate_fn,  # noqa: F401
                         WorkerInfo, get_worker_info)

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "Subset", "ConcatDataset", "random_split", "Sampler",
    "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
    "BatchSampler", "SubsetRandomSampler", "DistributedBatchSampler",
    "DataLoader", "WorkerInfo", "get_worker_info",
]
