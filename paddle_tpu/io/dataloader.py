"""DataLoader (reference: python/paddle/io/reader.py:262 DataLoader,
dataloader_iter.py:368 multiprocess iter).

TPU-native design:
- worker pool via a thread/process pool feeding an ordered prefetch queue —
  the reference's shared-memory tensor IPC is unnecessary because host numpy
  batches go straight into a PjRt host-to-device transfer;
- ``prefetch_to_device``: up to ``prefetch_factor`` batches are staged onto
  the accelerator asynchronously (jax.device_put is async) so H2D overlaps
  the previous step's compute — replacing the reference's pin-memory +
  cuda-stream copy path.
"""
from __future__ import annotations

import collections
import itertools
import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterable, List, Optional

import numpy as np
import jax

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler, SequenceSampler, RandomSampler

__all__ = ["DataLoader", "default_collate_fn"]


def default_collate_fn(batch):
    """Stack samples into batched arrays
    (reference: python/paddle/io/dataloader/collate.py)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch, axis=0)
    if isinstance(sample, Tensor):
        return Tensor(np.stack([s.numpy() for s in batch], axis=0))
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, collections.abc.Mapping):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, collections.abc.Sequence):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(s)) for s in transposed]
    raise TypeError(f"cannot collate batch of type {type(sample)}")


class _PrefetchIter:
    def __init__(self, loader, index_iter):
        self.loader = loader
        self.index_iter = index_iter
        self.pool = (ThreadPoolExecutor(loader.num_workers)
                     if loader.num_workers > 0 else None)
        self.pending = collections.deque()
        self.prefetch = max(loader.prefetch_factor, 1) * max(
            loader.num_workers, 1)
        self._fill()

    def _load(self, indices):
        ds = self.loader.dataset
        samples = [ds[i] for i in indices]
        batch = self.loader.collate_fn(samples)
        return self.loader._to_device(batch)

    def _fill(self):
        while len(self.pending) < self.prefetch:
            try:
                indices = next(self.index_iter)
            except StopIteration:
                return
            if self.pool is not None:
                self.pending.append(self.pool.submit(self._load, indices))
            else:
                self.pending.append(indices)

    def __next__(self):
        if not self.pending:
            if self.pool is not None:
                self.pool.shutdown(wait=False)
            raise StopIteration
        item = self.pending.popleft()
        self._fill()
        if self.pool is not None:
            return item.result()
        return self._load(item)

    def __iter__(self):
        return self


class _IterableDatasetIter:
    def __init__(self, loader):
        self.loader = loader
        self.it = iter(loader.dataset)

    def __next__(self):
        samples = list(itertools.islice(self.it, self.loader.batch_size))
        if not samples:
            raise StopIteration
        if self.loader.drop_last and \
                len(samples) < self.loader.batch_size:
            raise StopIteration
        batch = self.loader.collate_fn(samples)
        return self.loader._to_device(batch)

    def __iter__(self):
        return self


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False,
                 prefetch_to_device=True):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.prefetch_to_device = prefetch_to_device
        self.return_list = return_list
        self._is_iterable = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif not self._is_iterable and batch_size is not None:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)
        else:
            self.batch_sampler = None

    def _to_device(self, batch):
        if not self.prefetch_to_device:
            return _to_tensors(batch)
        def put(x):
            if isinstance(x, np.ndarray):
                if x.dtype == np.float64:
                    x = x.astype(np.float32)
                return Tensor(jax.device_put(x))
            if isinstance(x, Tensor):
                return Tensor(jax.device_put(x._value))
            return x
        return jax.tree_util.tree_map(
            put, batch,
            is_leaf=lambda x: isinstance(x, (np.ndarray, Tensor)))

    def __iter__(self):
        if self._is_iterable:
            return _IterableDatasetIter(self)
        return _PrefetchIter(self, iter(self.batch_sampler))

    def __len__(self):
        if self._is_iterable:
            raise TypeError("IterableDataset has no __len__")
        return len(self.batch_sampler)


def _to_tensors(batch):
    def conv(x):
        if isinstance(x, np.ndarray):
            return Tensor(x)
        return x
    return jax.tree_util.tree_map(
        conv, batch, is_leaf=lambda x: isinstance(x, (np.ndarray, Tensor)))
