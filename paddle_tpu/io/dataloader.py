"""DataLoader (reference: python/paddle/io/reader.py:262 DataLoader,
dataloader_iter.py:368 multiprocess iter).

TPU-native design:
- worker pool via a thread/process pool feeding an ordered prefetch queue —
  the reference's shared-memory tensor IPC is unnecessary because host numpy
  batches go straight into a PjRt host-to-device transfer;
- ``prefetch_to_device``: up to ``prefetch_factor`` batches are staged onto
  the accelerator asynchronously (jax.device_put is async) so H2D overlaps
  the previous step's compute — replacing the reference's pin-memory +
  cuda-stream copy path.
"""
from __future__ import annotations

import collections
import itertools
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterable, List, Optional

import numpy as np
import jax

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler, SequenceSampler, RandomSampler

__all__ = ["DataLoader", "default_collate_fn"]


def default_collate_fn(batch):
    """Stack samples into batched arrays
    (reference: python/paddle/io/dataloader/collate.py)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch, axis=0)
    if isinstance(sample, Tensor):
        return Tensor(np.stack([s.numpy() for s in batch], axis=0))
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, collections.abc.Mapping):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, collections.abc.Sequence):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(s)) for s in transposed]
    raise TypeError(f"cannot collate batch of type {type(sample)}")


class _DevicePrefetchIter:
    """Double-buffered async H2D stage (reference:
    python/paddle/io/dataloader/dataloader_iter.py:368 — pin-memory +
    buffer-reader thread hiding ingest behind compute). A dedicated
    thread pulls host batches from ``src``, stages them on device
    (``jax.device_put``), and keeps up to ``depth`` staged batches
    queued ahead of the consumer, so the transfer for batch N+1 runs
    while the step consuming batch N computes. One thread serializes
    transfers — deliberate: concurrent h2d streams contend for the
    same PCIe/tunnel bandwidth without helping latency."""

    _END = ("end", None)

    def __init__(self, src, stage, depth=2, on_next=None):
        self.q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._done = False
        self._src = src
        self._stage = stage
        # observability hook: called with the staged-queue depth after
        # each consumer pull (a queue pinned at 0 = ingest-bound, at
        # depth = compute-bound); must be cheap and never raise
        self._on_next = on_next
        self._thread = threading.Thread(
            target=self._run, name="device-prefetch", daemon=True)
        self._thread.start()

    def _put(self, item):
        while not self._stop.is_set():
            try:
                self.q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _run(self):
        try:
            for host_batch in self._src:
                if self._stop.is_set():
                    return
                if not self._put(("item", self._stage(host_batch))):
                    return
            self._put(self._END)
        except BaseException as e:  # noqa: BLE001 — relayed to consumer
            self._put(("err", e))

    def __next__(self):
        # after an error was relayed (or close()), the producer thread
        # is gone and nothing will ever be enqueued again — a blocking
        # get() would deadlock a consumer that catches the error and
        # keeps iterating; terminate the iteration instead
        if self._done:
            raise StopIteration
        while True:
            try:
                kind, payload = self.q.get(timeout=0.1)
                break
            except queue.Empty:
                if self._stop.is_set():
                    self._done = True
                    raise StopIteration from None
        if kind == "item":
            if self._on_next is not None:
                self._on_next(self.q.qsize())
            return payload
        self._done = True
        self._stop.set()
        if kind == "err":
            raise payload
        raise StopIteration

    def __iter__(self):
        return self

    def close(self):
        self._stop.set()

    def __del__(self):
        self._stop.set()


class _PrefetchIter:
    def __init__(self, loader, index_iter):
        self.loader = loader
        self.index_iter = index_iter
        self.pool = (ThreadPoolExecutor(loader.num_workers)
                     if loader.num_workers > 0 else None)
        self.pending = collections.deque()
        self.prefetch = max(loader.prefetch_factor, 1) * max(
            loader.num_workers, 1)
        self._fill()

    def _load(self, indices):
        ds = self.loader.dataset
        samples = [ds[i] for i in indices]
        batch = self.loader.collate_fn(samples)
        # pooled workers stage to device in-thread (overlapped there);
        # the synchronous num_workers=0 path returns the host batch and
        # lets DataLoader.__iter__ wrap it in _DevicePrefetchIter
        if self.pool is not None:
            return self.loader._to_device(batch)
        return batch

    def _fill(self):
        while len(self.pending) < self.prefetch:
            try:
                indices = next(self.index_iter)
            except StopIteration:
                return
            if self.pool is not None:
                self.pending.append(self.pool.submit(self._load, indices))
            else:
                self.pending.append(indices)

    def __next__(self):
        if not self.pending:
            if self.pool is not None:
                self.pool.shutdown(wait=False)
            raise StopIteration
        item = self.pending.popleft()
        self._fill()
        if self.pool is not None:
            return item.result()
        return self._load(item)

    def __iter__(self):
        return self


class WorkerInfo:
    """reference: io/dataloader/worker.py WorkerInfo — id / num_workers /
    dataset of the calling worker; None in the main process."""

    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset

    def __repr__(self):
        return (f"WorkerInfo(id={self.id}, "
                f"num_workers={self.num_workers})")


_WORKER_INFO = [None]


def get_worker_info():
    """reference: python/paddle/io/__init__.py get_worker_info — worker
    context inside DataLoader subprocess/thread workers, else None."""
    return _WORKER_INFO[0]


def _worker_loop(dataset, collate_fn, task_q, result_q, use_shm,
                 worker_init_fn, worker_id, num_workers=0):
    """Subprocess worker (reference: python/paddle/io/dataloader/worker.py
    _worker_loop): pulls (batch_idx, indices) tasks, pushes collated numpy
    batches back — through the native shared-memory ring queue
    (csrc/shm_queue.cc) when available, else a multiprocessing.Queue.
    Workers never touch jax; device_put happens in the parent."""
    import pickle
    import traceback
    _WORKER_INFO[0] = WorkerInfo(worker_id, num_workers, dataset)
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    while True:
        task = task_q.get()
        if task is None:
            return
        bidx, indices = task
        try:
            batch = collate_fn([dataset[i] for i in indices])
            msg = (bidx, "ok", batch)
        except Exception:  # noqa: BLE001 — propagate to parent
            msg = (bidx, "exc", traceback.format_exc())
        if use_shm:
            result_q.put(pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL))
        else:
            result_q.put(msg)


class _ProcessPoolIter:
    """Multiprocess prefetch iterator with batch reordering (reference:
    dataloader_iter.py _DataLoaderIterMultiProcess)."""

    def __init__(self, loader, index_iter):
        import multiprocessing as mp
        import os
        self.loader = loader
        self.index_iter = index_iter
        # forkserver by default: forking a process that already holds
        # XLA/JAX runtime threads is a known deadlock source (CPython 3.12
        # warns on it). Unpicklable worker args (e.g. closures in tests)
        # fall back to fork; PADDLE_TPU_DATALOADER_START_METHOD overrides.
        method = getattr(loader, "_mp_start_method", None)
        if method is None:
            method = os.environ.get("PADDLE_TPU_DATALOADER_START_METHOD")
        if method is None:
            import io as _io
            import pickle as _pkl
            probed = (loader.dataset, loader.collate_fn,
                      getattr(loader, "worker_init_fn", None))
            # anything living in __main__ pickles by reference but forces
            # the forkserver child to re-import (re-execute) the training
            # script — only safe under fork
            in_main = any(
                getattr(type(o), "__module__", None) == "__main__" or
                getattr(o, "__module__", None) == "__main__"
                for o in probed if o is not None)
            try:
                # probe into a null sink — no materialized copy of a
                # potentially multi-GB in-memory dataset
                class _Null(_io.RawIOBase):
                    def write(self, b):
                        return len(b)
                _pkl.Pickler(_Null(), _pkl.HIGHEST_PROTOCOL).dump(probed)
                method = "fork" if in_main else "forkserver"
            except Exception:
                method = "fork"
            loader._mp_start_method = method  # probe once per loader
        try:
            ctx = mp.get_context(method)
        except ValueError:
            ctx = mp.get_context("fork")
        self.task_q = ctx.Queue()
        self.result_shm = None
        if loader.use_shared_memory:
            try:
                from ..core.native import SharedMemoryQueue
                name = f"/ptq_dl_{os.getpid()}_{id(self) & 0xFFFFFF:x}"
                self.result_shm = SharedMemoryQueue(name,
                                                    capacity=256 << 20)
            except Exception:
                self.result_shm = None
        self.use_shm = self.result_shm is not None
        self.result_q = self.result_shm if self.use_shm else ctx.Queue()
        self.workers = [
            ctx.Process(target=_worker_loop,
                        args=(loader.dataset, loader.collate_fn,
                              self.task_q, self.result_q, self.use_shm,
                              loader.worker_init_fn, i,
                              loader.num_workers),
                        daemon=True)
            for i in range(loader.num_workers)]
        for w in self.workers:
            w.start()
        self.buffer = {}
        self.next_idx = 0
        self.sent_idx = 0
        self.exhausted = False
        self.prefetch = max(loader.prefetch_factor, 1) * loader.num_workers
        # paddle semantics: timeout=0 means no limit; worker death is
        # detected by liveness polling, not by the timeout
        self.timeout = loader.timeout if loader.timeout else None
        self._fill()

    def _fill(self):
        while not self.exhausted and \
                self.sent_idx - self.next_idx < self.prefetch:
            try:
                indices = next(self.index_iter)
            except StopIteration:
                self.exhausted = True
                return
            self.task_q.put((self.sent_idx, indices))
            self.sent_idx += 1

    def _recv(self):
        """Blocking receive in short slices, checking worker liveness each
        slice (reference: dataloader_iter.py _thread_monitor + worker
        watchdog): a worker killed mid-batch (OOM) raises a clear error
        instead of an opaque queue timeout."""
        import pickle
        import queue as _queue
        deadline = (time.time() + self.timeout) if self.timeout else None
        while True:
            try:
                if self.use_shm:
                    return pickle.loads(self.result_q.get(timeout=5.0))
                return self.result_q.get(timeout=5.0)
            except (TimeoutError, _queue.Empty):
                dead = [w for w in self.workers
                        if not w.is_alive() and w.exitcode not in (0, None)]
                if dead:
                    self._shutdown()
                    raise RuntimeError(
                        f"DataLoader worker (pid {dead[0].pid}) exited "
                        f"unexpectedly with code {dead[0].exitcode} — "
                        f"likely killed (OOM?)") from None
                if deadline and time.time() > deadline:
                    self._shutdown()
                    raise TimeoutError(
                        f"DataLoader batch not produced within "
                        f"{self.timeout}s (workers alive)") from None

    def __next__(self):
        if self.next_idx >= self.sent_idx and self.exhausted:
            self._shutdown()
            raise StopIteration
        while self.next_idx not in self.buffer:
            bidx, status, payload = self._recv()
            if status == "exc":
                self._shutdown()
                raise RuntimeError(
                    f"DataLoader worker failed for batch {bidx}:\n{payload}")
            self.buffer[bidx] = payload
        batch = self.buffer.pop(self.next_idx)
        self.next_idx += 1
        self._fill()
        return batch

    def _shutdown(self):
        for _ in self.workers:
            self.task_q.put(None)
        for w in self.workers:
            w.join(timeout=5)
            if w.is_alive():
                w.terminate()
        if self.result_shm is not None:
            self.result_shm.close()
            self.result_shm = None

    def __del__(self):
        try:
            if any(w.is_alive() for w in self.workers):
                self._shutdown()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    def __iter__(self):
        return self


class _IterableDatasetIter:
    def __init__(self, loader):
        self.loader = loader
        self.it = iter(loader.dataset)

    def __next__(self):
        samples = list(itertools.islice(self.it, self.loader.batch_size))
        if not samples:
            raise StopIteration
        if self.loader.drop_last and \
                len(samples) < self.loader.batch_size:
            raise StopIteration
        return self.loader.collate_fn(samples)

    def __iter__(self):
        return self


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False,
                 prefetch_to_device=True, worker_type="thread"):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        # "thread" (default: zero-copy into device_put, fine for numpy-light
        # pipelines) or "process" (reference behavior: subprocess workers +
        # shared-memory IPC, for GIL-heavy transforms)
        self.worker_type = worker_type
        self.prefetch_to_device = prefetch_to_device
        self.return_list = return_list
        self._is_iterable = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif not self._is_iterable and batch_size is not None:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)
        else:
            self.batch_sampler = None

    def _to_device(self, batch):
        if not self.prefetch_to_device:
            return _to_tensors(batch)
        def put(x):
            if isinstance(x, np.ndarray):
                if x.dtype == np.float64:
                    x = x.astype(np.float32)
                return Tensor(jax.device_put(x))
            if isinstance(x, Tensor):
                return Tensor(jax.device_put(x._value))
            return x
        return jax.tree_util.tree_map(
            put, batch,
            is_leaf=lambda x: isinstance(x, (np.ndarray, Tensor)))

    def __iter__(self):
        if self._is_iterable:
            inner = _IterableDatasetIter(self)
        elif self.worker_type == "process" and self.num_workers > 0:
            inner = _ProcessPoolIter(self, iter(self.batch_sampler))
        else:
            inner = _PrefetchIter(self, iter(self.batch_sampler))
            if inner.pool is not None:
                # thread workers already stage to device in-pool; their
                # futures run ahead of the consumer, so h2d is overlapped
                return inner
        if not self.prefetch_to_device:
            return map(_to_tensors, inner)
        return _DevicePrefetchIter(inner, self._to_device,
                                   depth=max(self.prefetch_factor, 1))

    def __len__(self):
        if self._is_iterable:
            raise TypeError("IterableDataset has no __len__")
        return len(self.batch_sampler)


def _to_tensors(batch):
    def conv(x):
        if isinstance(x, np.ndarray):
            return Tensor(x)
        return x
    return jax.tree_util.tree_map(
        conv, batch, is_leaf=lambda x: isinstance(x, (np.ndarray, Tensor)))
