"""Datasets (reference: python/paddle/io/dataloader/dataset.py)."""
from __future__ import annotations

import bisect
from typing import List, Sequence

import numpy as np

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "Subset", "ConcatDataset", "random_split"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError(
            f"{type(self).__name__} must implement __getitem__")

    def __len__(self):
        raise NotImplementedError(
            f"{type(self).__name__} must implement __len__")


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError(
            f"{type(self).__name__} must implement __iter__")

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        from ..core.tensor import Tensor
        assert all(t.shape[0] == tensors[0].shape[0] for t in tensors), \
            "tensors must share dim 0"
        self.tensors = tensors

    def __getitem__(self, index):
        return tuple(t[index] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        assert self.datasets
        n = len(self.datasets[0])
        assert all(len(d) == n for d in self.datasets)

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        sample = []
        for d in self.datasets:
            item = d[idx]
            sample.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(sample)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in
                                           self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        i = bisect.bisect_right(self.cumulative_sizes, idx)
        off = idx - (self.cumulative_sizes[i - 1] if i > 0 else 0)
        return self.datasets[i][off]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    import math
    if all(isinstance(l, float) for l in lengths) and \
            abs(sum(lengths) - 1.0) < 1e-6:
        sizes = []
        for frac in lengths:
            sizes.append(int(math.floor(len(dataset) * frac)))
        rem = len(dataset) - sum(sizes)
        for i in range(rem):
            sizes[i % len(sizes)] += 1
        lengths = sizes
    if sum(lengths) != len(dataset):
        raise ValueError("sum of input lengths != dataset length")
    from ..core.random import next_key
    import jax
    perm = np.asarray(jax.random.permutation(next_key(), len(dataset)))
    out = []
    off = 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n].tolist()))
        off += n
    return out
