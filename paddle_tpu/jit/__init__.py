"""paddle_tpu.jit (reference: python/paddle/jit/__init__.py)."""
from .api import (to_static, not_to_static, ignore_module,  # noqa: F401
                  TracedFunction, enable_to_static)
from .save_load import save, load, TranslatedLayer  # noqa: F401
from .train_step import train_step, TrainStep  # noqa: F401

__all__ = ["to_static", "not_to_static", "save", "load", "enable_to_static",
           "train_step", "TrainStep"]
