"""paddle_tpu.jit (reference: python/paddle/jit/__init__.py)."""
from .api import (to_static, not_to_static, ignore_module,  # noqa: F401
                  TracedFunction, enable_to_static)
from .save_load import save, load, TranslatedLayer  # noqa: F401
from .train_step import train_step, TrainStep  # noqa: F401

__all__ = ["to_static", "not_to_static", "save", "load", "enable_to_static",
           "set_verbosity", "set_code_level",
           "train_step", "TrainStep"]


# SOT logging knobs (reference: jit/sot/utils/envs.py). Module state the
# SOT recorder consults when emitting segment diagnostics.
_SOT_LOG = {"verbosity": 0, "code_level": -1}


def set_verbosity(level=0, also_to_stdout=False):
    """reference: jit/sot set_verbosity — SOT translate log verbosity."""
    _SOT_LOG["verbosity"] = int(level)


def set_code_level(level=100, also_to_stdout=False):
    """reference: jit/sot set_code_level — dump level for SOT-generated
    code objects."""
    _SOT_LOG["code_level"] = int(level)
