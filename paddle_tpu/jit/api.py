"""jit.to_static: whole-program compilation.

TPU-native re-design of the reference dy2static stack (python/paddle/jit/
api.py:197 to_static, SOT bytecode tracer python/paddle/jit/sot/, CINN):
instead of bytecode capture + PIR + CINN, the eager code is traced by JAX
into ONE pure jaxpr (parameters/buffers/inputs as traced args), compiled by
XLA, and the compiled call is recorded as a single node on the eager autograd
tape — so ``loss.backward()`` runs the XLA-compiled backward. Guards =
jax.jit's shape/dtype cache keys plus explicit static-arg keys.

Parameter discovery: one eager "discovery" pass runs the function with a
dispatch hook that records every persistable leaf Tensor touched (parameters
and registered buffers) — the analog of the reference's program translator
collecting ``Parameter`` vars.

Known limit: a NON-persistable closure tensor (e.g. a module-level flag
created with to_tensor) is a trace-time constant on the whole-graph path —
its value is baked into the compiled program, like any Python closure
constant. The SOT segmented path (sot.py, taken on graph break) guards
such tensors instead.
"""
from __future__ import annotations

import functools
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch, no_grad, to_value
from ..core import tensor as tensor_mod
from ..core.random import next_key, traced_key_source

__all__ = ["to_static", "not_to_static", "ignore_module", "TracedFunction",
           "enable_to_static"]

_collector = threading.local()


def _collect_hook(t: Tensor):
    seen = getattr(_collector, "tensors", None)
    if seen is not None and t.persistable and t._grad_node is None \
            and id(t) not in seen:
        seen[id(t)] = t


# patch dispatch to surface persistable leaves during discovery
_orig_dispatch = tensor_mod.dispatch


def _dispatch_with_collection(fn, tensor_args, name="op", multi_output=False,
                              **kw):
    if getattr(_collector, "tensors", None) is not None:
        for a in tensor_args:
            if isinstance(a, Tensor):
                _collect_hook(a)
    return _orig_dispatch(fn, tensor_args, name=name,
                          multi_output=multi_output, **kw)


def _install_collector_patch():
    if tensor_mod.dispatch is not _dispatch_with_collection:
        tensor_mod.dispatch = _dispatch_with_collection
        # rebind in modules that imported dispatch by name
        import sys
        for mod_name, mod in list(sys.modules.items()):
            if mod_name.startswith("paddle_tpu") and mod is not None and \
                    getattr(mod, "dispatch", None) is _orig_dispatch:
                mod.dispatch = _dispatch_with_collection


class TracedFunction:
    """The compiled callable returned by to_static
    (reference: StaticFunction, python/paddle/jit/dy2static/
    program_translator.py:839)."""

    def __init__(self, fn: Callable, input_spec=None, build_strategy=None,
                 full_graph=True, backend=None, layer=None):
        self._fn = fn
        self._layer = layer
        self._input_spec = input_spec
        self._cache: Dict[Any, Tuple] = {}
        self._params: Optional[List[Tensor]] = None
        self._buffers: Optional[List[Tensor]] = None
        self._enabled = True
        functools.update_wrapper(self, fn)

    # -- discovery -----------------------------------------------------------
    def _discover(self, args, kwargs):
        _install_collector_patch()
        _collector.tensors = {}
        try:
            out = self._fn(*args, **kwargs)
        finally:
            found = _collector.tensors
            _collector.tensors = None
        tensors = list(found.values())
        if self._layer is not None:
            # deterministic order + completeness from the layer registries
            ordered = list(dict.fromkeys(
                list(self._layer.parameters()) +
                list(self._layer.buffers()) + tensors))
            tensors = ordered
        params = [t for t in tensors if not t.stop_gradient]
        buffers = [t for t in tensors if t.stop_gradient]
        self._params = params
        self._buffers = buffers
        return out

    # -- cache key -----------------------------------------------------------
    def _key(self, args, kwargs):
        leaves, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        sig = []
        for l in leaves:
            if isinstance(l, Tensor):
                sig.append(("T", tuple(l.shape), str(l.dtype)))
            elif isinstance(l, (jax.Array, np.ndarray)):
                sig.append(("A", tuple(l.shape), str(l.dtype)))
            else:
                sig.append(("S", l))
        training = getattr(self._layer, "training", None)
        from ..amp.auto_cast import amp_state
        return (treedef, tuple(sig), training, amp_state.enabled,
                str(amp_state.dtype) if amp_state.enabled else "")

    # -- build ---------------------------------------------------------------
    def _build(self, args, kwargs):
        params, buffers = self._params, self._buffers
        fn = self._fn

        # record output structure during a traced run
        out_tree = [None]

        def pure(param_vals, buffer_vals, rng_key, in_leaves, treedef):
            saved = [t._value for t in params]
            saved_b = [t._value for t in buffers]
            for t, v in zip(params, param_vals):
                t._value = v
            for t, v in zip(buffers, buffer_vals):
                t._value = v
            try:
                wrapped = [Tensor(l, stop_gradient=True)
                           if isinstance(l, (jax.Array, jax.core.Tracer))
                           else l for l in in_leaves]
                a, kw = jax.tree_util.tree_unflatten(treedef, wrapped)
                with no_grad(), traced_key_source(rng_key):
                    out = fn(*a, **kw)
                out_leaves, tree = jax.tree_util.tree_flatten(
                    out, is_leaf=lambda x: isinstance(x, Tensor))
                out_tree[0] = tree
                out_vals = [to_value(o) if isinstance(o, Tensor) else o
                            for o in out_leaves]
                new_buf = [t._value for t in buffers]
                return tuple(out_vals) + tuple(new_buf)
            finally:
                for t, v in zip(params, saved):
                    t._value = v
                for t, v in zip(buffers, saved_b):
                    t._value = v

        return pure, out_tree

    # -- call ----------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        if not self._enabled or not _to_static_enabled[0]:
            return self._fn(*args, **kwargs)
        if self._params is None:
            self._discover(args, kwargs)  # eager warmup defines params
        key = self._key(args, kwargs)
        entry = self._cache.get(key)
        in_leaves, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        tensor_leaf_idx = [i for i, l in enumerate(in_leaves)
                          if isinstance(l, (Tensor, jax.Array, np.ndarray))]
        if entry is None:
            pure, out_tree = self._build(args, kwargs)

            def flat_fn(*flat):
                np_, nb = len(self._params), len(self._buffers)
                param_vals = flat[:np_]
                buffer_vals = flat[np_:np_ + nb]
                rng_key = flat[np_ + nb]
                tensor_in = flat[np_ + nb + 1:]
                leaves = list(in_leaves)
                for i, v in zip(tensor_leaf_idx, tensor_in):
                    leaves[i] = v
                return pure(param_vals, buffer_vals, rng_key, leaves,
                            treedef)
            # jit => one XLA program for the whole forward; grad-of-jit
            # compiles the backward too (the CINN-equivalent step)
            flat_fn = jax.jit(flat_fn)
            entry = (flat_fn, out_tree)
            self._cache[key] = entry
        flat_fn, out_tree = entry
        if flat_fn == "eager":
            return self._fn(*args, **kwargs)
        if flat_fn == "sot":
            return out_tree(*args, **kwargs)  # (tag, SegmentedFunction)
        tensor_in = [to_value(in_leaves[i]) if isinstance(in_leaves[i], Tensor)
                     else jnp.asarray(in_leaves[i]) for i in tensor_leaf_idx]
        rng = next_key()
        all_args = tuple(self._params) + tuple(self._buffers) + (
            Tensor(rng),) + tuple(
            in_leaves[i] if isinstance(in_leaves[i], Tensor) else
            Tensor(jnp.asarray(in_leaves[i])) for i in tensor_leaf_idx)
        try:
            outs = dispatch(flat_fn, all_args, name="to_static",
                            multi_output=True)
        except (jax.errors.TracerBoolConversionError,
                jax.errors.ConcretizationTypeError,
                jax.errors.TracerArrayConversionError,
                jax.errors.TracerIntegerConversionError) as e:
            # graph break: tensor-dependent Python control flow cannot be
            # traced as ONE program — switch this signature to SOT-style
            # segmented execution: compiled subgraphs around the breaking
            # construct, guarded on the consumed scalar outcomes
            # (reference: python/paddle/jit/sot/translate.py:37)
            import warnings
            warnings.warn(
                f"to_static: graph break ({type(e).__name__}) — switching "
                "to segmented (SOT-style) execution for this call "
                "signature: subgraphs around the break stay compiled. Use "
                "paddle.where/lax.cond-style ops to keep the graph whole.",
                stacklevel=2)
            from .sot import SegmentedFunction
            seg = SegmentedFunction(self._fn)
            self._cache[key] = ("sot", seg)
            return seg(*args, **kwargs)
        n_buf = len(self._buffers)
        out_vals = outs[:len(outs) - n_buf]
        new_buf = outs[len(outs) - n_buf:]
        with no_grad():
            for t, v in zip(self._buffers, new_buf):
                t._value = v._value
        return jax.tree_util.tree_unflatten(out_tree[0], list(out_vals))

    # -- introspection -------------------------------------------------------
    @property
    def parameters(self):
        return self._params

    def concrete_program(self):
        return self._cache

    def rollback(self):
        self._enabled = False
        return self._fn


_to_static_enabled = [True]


def enable_to_static(flag: bool):
    _to_static_enabled[0] = bool(flag)


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=True, **kwargs):
    """reference: python/paddle/jit/api.py:197."""
    from ..nn import Layer

    def decorate(obj):
        if isinstance(obj, Layer):
            traced = TracedFunction(obj.__call__, input_spec, build_strategy,
                                    full_graph, backend, layer=obj)
            obj.forward_static = traced
            orig_call = obj.__call__
            obj._traced = traced
            # route layer calls through the compiled path
            object.__setattr__(obj, "__call_traced__", traced)
            obj.forward_original = obj.forward
            return _LayerProxy(obj, traced)
        return TracedFunction(obj, input_spec, build_strategy, full_graph,
                              backend)

    if function is not None:
        return decorate(function)
    return decorate


class _LayerProxy:
    """Wraps a Layer so calling it hits the compiled path while attribute
    access falls through (mirrors reference behavior where to_static(layer)
    returns the layer with a patched forward)."""

    def __init__(self, layer, traced):
        object.__setattr__(self, "_layer", layer)
        object.__setattr__(self, "_traced", traced)

    def __call__(self, *args, **kwargs):
        return self._traced(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._layer, name)

    def __setattr__(self, name, value):
        setattr(self._layer, name, value)


def not_to_static(fn=None):
    if fn is None:
        return lambda f: f
    return fn


def ignore_module(modules):
    return None
