"""jit.save / jit.load (reference: python/paddle/jit/api.py:956 save,
pir_translated_layer.py).

TPU-native format: StableHLO text of the compiled forward + a params pickle.
A loaded ``TranslatedLayer`` replays the StableHLO module for inference (the
reference's deploy path through PIR programs); if StableHLO export is
unavailable for a program, falls back to re-tracing a pickled callable is NOT
attempted — weights + spec are still saved so the model can be rebuilt.
"""
from __future__ import annotations

import json
import os
import pickle
from typing import Any, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, no_grad, to_value

__all__ = ["save", "load", "TranslatedLayer"]


def _spec_of(v):
    return {"shape": list(np.shape(v)), "dtype": str(np.asarray(v).dtype
                                                     if not hasattr(v, "dtype")
                                                     else v.dtype)}


@no_grad()
def save(layer, path: str, input_spec=None, **configs):
    """Serialise forward as StableHLO + weights
    (reference: python/paddle/jit/api.py:956)."""
    from ..nn import Layer
    from ..static import InputSpec
    from .api import TracedFunction, _LayerProxy

    if isinstance(layer, _LayerProxy):
        layer = layer._layer
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    if isinstance(layer, Layer):
        layer.eval()
        pure_fn, params, buffers = layer.functional()
        state = {k: np.asarray(v) for k, v in {**params, **buffers}.items()}
        if input_spec is None:
            raise ValueError("jit.save requires input_spec for a Layer "
                             "(shapes must be static for AOT export)")
        example = []
        for spec in input_spec:
            if isinstance(spec, InputSpec):
                shape = [1 if (s is None or s < 0) else s for s in spec.shape]
                example.append(jnp.zeros(shape, dtype=spec.dtype))
            elif isinstance(spec, Tensor):
                example.append(to_value(spec))
            else:
                example.append(jnp.asarray(spec))

        def fwd(params, buffers, *inputs):
            out, _ = pure_fn(params, buffers, *inputs)
            return out

        from jax import export as jax_export
        exported = jax_export.export(jax.jit(fwd))(params, buffers, *example)
        hlo = exported.mlir_module()
        with open(path + ".stablehlo.mlir", "w") as f:
            f.write(hlo)
        meta = {
            "format": "stablehlo",
            "inputs": [_spec_of(e) for e in example],
            "param_keys": list(params.keys()),
            "buffer_keys": list(buffers.keys()),
        }
        with open(path + ".meta.json", "w") as f:
            json.dump(meta, f)
        with open(path + ".pdiparams", "wb") as f:
            pickle.dump(state, f, protocol=4)
        # keep the serialised Exported for exact reload
        with open(path + ".exported", "wb") as f:
            f.write(exported.serialize())
        return path
    raise TypeError("jit.save expects a Layer (functions: use "
                    "paddle_tpu.static.export_stablehlo)")


class TranslatedLayer:
    """Inference-only callable rebuilt from an exported program
    (reference: python/paddle/jit/translated_layer.py)."""

    def __init__(self, exported, state, meta):
        self._exported = exported
        self._params = {k: jnp.asarray(state[k])
                        for k in meta["param_keys"]}
        self._buffers = {k: jnp.asarray(state[k])
                         for k in meta["buffer_keys"]}
        self._meta = meta
        self.training = False

    def __call__(self, *inputs):
        vals = [to_value(i) if isinstance(i, Tensor) else jnp.asarray(i)
                for i in inputs]
        out = self._exported.call(self._params, self._buffers, *vals)
        return jax.tree_util.tree_map(Tensor, out)

    def eval(self):
        return self

    def forward(self, *inputs):
        return self(*inputs)


def load(path: str, **configs) -> TranslatedLayer:
    from jax import export as jax_export
    with open(path + ".exported", "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    with open(path + ".meta.json") as f:
        meta = json.load(f)
    return TranslatedLayer(exported, state, meta)
