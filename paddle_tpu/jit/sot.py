"""SOT-parity subgraph compilation for graph breaks.

Reference: python/paddle/jit/sot/translate.py:37 + eval_frame.c:392 —
Paddle's SOT rewrites bytecode so the parts of a function BETWEEN
data-dependent constructs still run as compiled subgraphs, guarded for
re-entry. The TPU-native equivalent here needs no bytecode: eager ops
already funnel through ``core.tensor.dispatch``, so on the first call we
RECORD the dispatched op stream while the function runs eagerly, close a
segment whenever Python consumes a concrete scalar from a Tensor
(``__bool__`` / ``__int__`` / ``__float__`` / ``item()`` — the breaking
constructs), and on later calls replay each segment as ONE jitted XLA
program. Each consumed scalar becomes a GUARD: its replayed value must
match the recorded outcome (the control-flow path), else the recording
is invalidated and that call re-records eagerly. Shape/dtype guards are
the caller's cache key (jit/api.py ``TracedFunction._key``).

Replayed segments enter the autograd tape as one node each (dispatch +
jax.vjp), so ``loss.backward()`` after a segmented forward runs
XLA-compiled backward programs too.

Known limits (fall back to per-call eager, never wrong results):
- Python-level side effects inside the function (in-place buffer value
  assignment, appending to external lists) are not replayed; a recording
  that mutated externals is marked replay-unsafe at record time.
- ``.numpy()`` / ``__array__`` consumption of an in-flight tensor is a
  full-array guard we do not attempt; the recording is replay-unsafe.
- A guard that flips every call degenerates to eager + recording
  overhead (same complexity class as reference SOT guard churn).
- ``float(t)`` / ``t.item()`` guard on the EXACT value, so any input or
  parameter change re-records — matching reference SOT's treatment of
  ``.item()`` as a constant-guard. Prefer ``if t > c:`` (a bool
  consumption): the guard is then the branch OUTCOME, which stays stable
  across parameter updates, so training loops keep replaying.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch, to_value
from ..core import tensor as tensor_mod
from ..core.random import next_key, traced_key_source

__all__ = ["SegmentedFunction"]


class _Op:
    __slots__ = ("name", "fn", "in_slots", "out_ids", "multi", "amp")

    def __init__(self, name, fn, in_slots, out_ids, multi, amp):
        self.name = name
        self.fn = fn
        self.in_slots = in_slots
        self.out_ids = out_ids
        self.multi = multi
        self.amp = amp


class _Guard:
    """A recorded scalar consumption: replay must reproduce ``outcome``."""
    __slots__ = ("tid", "kind", "outcome", "args")

    def __init__(self, tid, kind, outcome, args=()):
        self.tid = tid
        self.kind = kind
        self.outcome = outcome
        self.args = args


class _Recorder:
    """Active while the user function runs eagerly; mirrors
    static.Program's dispatch recording (core/tensor.py
    ``_SEGMENT_RECORDER``) plus scalar-consumption events."""

    def __init__(self):
        self.events: List[Any] = []     # _Op | _Guard interleaved
        self.produced: set = set()
        self.externals: Dict[int, Tensor] = {}
        self.ext_snapshot: Dict[int, Any] = {}   # _value at capture time
        self.keep: List[Tensor] = []    # id() identity must not be reused
        self.replay_safe = True
        self.input_ids: List[int] = []

    def _record(self, name, fn, tensor_args, values, results, multi):
        from ..amp.auto_cast import amp_state
        in_slots = []
        for a, v in zip(tensor_args, values):
            if isinstance(a, Tensor):
                tid = id(a)
                if tid not in self.produced and tid not in self.externals \
                        and tid not in self.input_ids:
                    self.externals[tid] = a
                    self.ext_snapshot[tid] = a._value
                in_slots.append(("var", tid))
            else:
                in_slots.append(("const", v))
        out_ids = tuple(id(t) for t in results)
        self.produced.update(out_ids)
        self.events.append(_Op(name, fn, tuple(in_slots), out_ids, multi,
                               bool(amp_state.enabled)))
        self.keep.extend(a for a in tensor_args if isinstance(a, Tensor))
        self.keep.extend(results)

    def on_scalar(self, tensor, kind, outcome, args=()):
        tid = id(tensor)
        if tid not in self.produced and tid not in self.externals and \
                tid not in self.input_ids:
            # a tensor the recording has not seen as an op input yet
            # (e.g. a module-level flag consumed before any use): capture
            # it as an external so the guard still protects the control
            # path when its value changes between calls
            self.externals[tid] = tensor
            self.ext_snapshot[tid] = tensor._value
        self.events.append(_Guard(tid, kind, outcome, args))

    def on_mutation(self, tensor):
        """Any Python-level in-place mutation (set_value/fill_/zero_/
        __setitem__/_replace_value) during recording: side effects do not
        replay, so the whole recording is replay-unsafe. Conservative by
        design; raw ``t._value = x`` assignments that bypass these entry
        points are caught by the external-snapshot backstop only if the
        tensor was read first."""
        self.replay_safe = False

    def mark_unsafe(self):
        self.replay_safe = False


# -- scalar-consumption hooks -------------------------------------------------
# Installed once; ~zero cost when no recorder is active.
_ACTIVE: List[Optional[_Recorder]] = [None]
_HOOKED = [False]


_IN_HOOK = [False]


def _install_scalar_hooks():
    if _HOOKED[0]:
        return
    _HOOKED[0] = True

    def wrap(method_name, kind, cast):
        orig = getattr(Tensor, method_name)

        def wrapped(self, *a, **kw):
            rec = _ACTIVE[0]
            if rec is None or _IN_HOOK[0]:
                return orig(self, *a, **kw)
            # reentrancy guard: item()/__float__ call numpy() internally;
            # only the OUTERMOST consumption is the break event
            _IN_HOOK[0] = True
            try:
                out = orig(self, *a, **kw)
            finally:
                _IN_HOOK[0] = False
            if kind == "array":
                rec.mark_unsafe()
            else:
                rec.on_scalar(self, kind, cast(out), args=a)
            return out
        wrapped.__name__ = method_name
        setattr(Tensor, method_name, wrapped)

    wrap("__bool__", "bool", bool)
    wrap("__int__", "int", int)
    wrap("__float__", "float", float)
    wrap("item", "item", lambda v: v)
    wrap("numpy", "array", None)


class _Segment:
    __slots__ = ("ops", "in_ids", "consts", "out_ids", "compiled")

    def __init__(self, ops, in_ids, out_ids):
        self.ops = ops
        self.in_ids = in_ids
        self.out_ids = out_ids
        self.compiled = None

    def fn(self):
        if self.compiled is not None:
            return self.compiled
        ops, in_ids, out_ids = self.ops, self.in_ids, self.out_ids
        from ..amp.auto_cast import maybe_cast_inputs

        def seg_fn(rng_key, *in_vals):
            env = dict(zip(in_ids, in_vals))
            # ops drawing randomness (dropout, …) call next_key() inside
            # their recorded fn; thread a per-call key ARGUMENT so the
            # jitted program doesn't bake the key as a retrace-forcing
            # constant (same design as static.Program._build_replay)
            with traced_key_source(rng_key):
                for op in ops:
                    args = tuple(env[s] if kind == "var" else s
                                 for kind, s in op.in_slots)
                    if op.amp:
                        args = maybe_cast_inputs(op.name, args)
                    out = op.fn(*args)
                    outs = tuple(out) if op.multi else (out,)
                    for oid, o in zip(op.out_ids, outs):
                        env[oid] = o
            return tuple(env[i] for i in out_ids)

        self.compiled = jax.jit(seg_fn)
        return self.compiled


class SegmentedFunction:
    """One (function, signature) pair executed SOT-style.

    First call (and any call after a guard mismatch): records while
    running eagerly. Later calls: replays compiled segments + guards.
    ``stats`` reports (ops_total, ops_compiled) of the last replayed
    call for observability/tests."""

    def __init__(self, fn: Callable):
        self._fn = fn
        self._plan = None           # list[_Segment | _Guard]
        self._out_tree = None
        self._out_slots = None      # ("var", tid) | ("const", leaf)
        self._keep = None
        self._externals = None
        self._input_ids = None
        self._never_replay = False  # recording proved replay-unsafe
        self.last_was_replay = False
        self.stats = (0, 0)
        _install_scalar_hooks()

    # -- recording -----------------------------------------------------------
    def _record_call(self, args, kwargs):
        rec = _Recorder()
        in_leaves, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        leaf_pos = []
        in_snap = {}
        for i, l in enumerate(in_leaves):
            if isinstance(l, Tensor):
                rec.input_ids.append(id(l))
                leaf_pos.append(i)
                in_snap[id(l)] = l._value
        prev = _ACTIVE[0]
        prev_rec = tensor_mod._SEGMENT_RECORDER[0]
        _ACTIVE[0] = rec
        tensor_mod._SEGMENT_RECORDER[0] = rec
        try:
            out = self._fn(*args, **kwargs)
        finally:
            _ACTIVE[0] = prev
            tensor_mod._SEGMENT_RECORDER[0] = prev_rec
        # replay-unsafe if the call mutated any captured external's or
        # input's value (Python-level side effects do not replay)
        for tid, t in rec.externals.items():
            if t._value is not rec.ext_snapshot.get(tid, t._value):
                rec.mark_unsafe()
                break
        if rec.replay_safe:
            for i in leaf_pos:
                l = in_leaves[i]
                if l._value is not in_snap[id(l)]:
                    rec.mark_unsafe()
                    break
        if rec.replay_safe:
            self._finalize(rec, out, leaf_pos)
        else:
            self._plan = None
            self._never_replay = True
        return out

    def _finalize(self, rec, out, leaf_pos):
        out_leaves, out_tree = jax.tree_util.tree_flatten(
            out, is_leaf=lambda x: isinstance(x, Tensor))
        out_slots = []
        for l in out_leaves:
            if isinstance(l, Tensor):
                tid = id(l)
                if tid in rec.produced or tid in rec.externals or \
                        tid in rec.input_ids:
                    out_slots.append(("var", tid))
                else:
                    out_slots.append(("const", l))
            else:
                out_slots.append(("const", l))

        # split events into segments at guard boundaries
        segments_ops: List[List[_Op]] = [[]]
        plan_shape: List[Any] = []
        for ev in rec.events:
            if isinstance(ev, _Op):
                segments_ops[-1].append(ev)
            else:
                plan_shape.append(("seg", segments_ops[-1]))
                plan_shape.append(("guard", ev))
                segments_ops.append([])
        plan_shape.append(("seg", segments_ops[-1]))

        # ids needed after each segment: later var-slots, guards, outputs
        needed_after: List[set] = []
        future: set = set(tid for k, tid in out_slots if k == "var")
        for kind, payload in reversed(plan_shape):
            if kind == "guard":
                future = future | {payload.tid}
            else:
                needed_after.append(set(future))
                for op in payload:
                    for sk, sv in op.in_slots:
                        if sk == "var":
                            future.add(sv)
        needed_after.reverse()

        plan: List[Any] = []
        seg_i = 0
        for kind, payload in plan_shape:
            if kind == "guard":
                plan.append(payload)
                continue
            ops = payload
            produced_here = set()
            for op in ops:
                produced_here.update(op.out_ids)
            in_ids = []
            for op in ops:
                for sk, sv in op.in_slots:
                    if sk == "var" and sv not in produced_here and \
                            sv not in in_ids:
                        in_ids.append(sv)
            out_ids = sorted(produced_here & needed_after[seg_i])
            seg_i += 1
            if ops or out_ids:
                plan.append(_Segment(ops, in_ids, tuple(out_ids)))
        self._plan = plan
        self._out_tree = out_tree
        self._out_slots = out_slots
        # rec.keep pinned intermediates only to stop id() reuse DURING
        # recording; after finalize the plan's tids are purely symbolic
        # (replay populates env from input positions, externals, and
        # segment outputs), so drop them to free the activations
        self._keep = None
        self._externals = rec.externals
        self._input_ids = list(rec.input_ids)
        self._leaf_pos = leaf_pos

    # -- replay --------------------------------------------------------------
    def _replay(self, args, kwargs):
        in_leaves, _ = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        env: Dict[int, Tensor] = {}
        for rec_id, pos in zip(self._input_ids, self._leaf_pos):
            env[rec_id] = in_leaves[pos]
        for tid, t in self._externals.items():
            env[tid] = t

        ops_total = sum(len(p.ops) for p in self._plan
                        if isinstance(p, _Segment))
        for step in self._plan:
            if isinstance(step, _Guard):
                t = env[step.tid]
                val = t.numpy()
                got = {"bool": lambda: bool(val),
                       "int": lambda: int(val),
                       "float": lambda: float(val),
                       "item": lambda: val.item(*step.args)}[step.kind]()
                if got != step.outcome:
                    return None  # control path diverged
                continue
            if not step.ops and not step.out_ids:
                continue
            seg_in = (Tensor(next_key()),) + tuple(
                env[i] for i in step.in_ids)
            outs = dispatch(step.fn(), seg_in, name="sot_segment",
                            multi_output=True)
            for oid, o in zip(step.out_ids, outs):
                env[oid] = o
        # const slots return a FRESH Tensor per replay: handing out the
        # recorded object would let a caller's in-place mutation corrupt
        # every later replay of this signature
        out_leaves = [env[s] if k == "var" else
                      (Tensor(s._value, stop_gradient=s.stop_gradient)
                       if isinstance(s, Tensor) else s)
                      for k, s in self._out_slots]
        self.stats = (ops_total + sum(
            1 for p in self._plan if isinstance(p, _Guard)), ops_total)
        return jax.tree_util.tree_unflatten(self._out_tree, out_leaves)

    def __call__(self, *args, **kwargs):
        if self._never_replay:
            self.last_was_replay = False
            return self._fn(*args, **kwargs)
        if self._plan is not None:
            out = self._replay(args, kwargs)
            if out is not None:
                self.last_was_replay = True
                return out
            self._plan = None  # guard mismatch: re-record this call
        self.last_was_replay = False
        return self._record_call(args, kwargs)
