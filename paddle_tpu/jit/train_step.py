"""Whole-train-step compilation: forward + loss + backward + grad clip +
optimizer update as ONE donated XLA program.

The reference keeps its dygraph hot path in C++ (SURVEY §3.1-3.2: _C_ops
dispatch, GradNode walk, fused multi_tensor optimizer kernels). The TPU-native
equivalent is stronger: the entire step is a single jaxpr compiled by XLA, so
the compiler fuses elementwise work into the matmuls, overlaps HBM traffic,
and buffer donation keeps memory flat. This is the path `bench.py` and any
serious single-host training should use; the eager Layer path remains for
debugging.

Usage::

    step = paddle.jit.train_step(model, loss_fn, optimizer,
                                 amp_level="O1", amp_dtype="bfloat16")
    loss = step(x, y)           # one XLA execution

``loss_fn(out, *labels)`` receives the model output(s) as Tensors.
Model parameters, optimizer accumulators and layer buffers (e.g. BatchNorm
running stats) are updated in place after every call, so checkpointing via
``model.state_dict()`` / ``optimizer.state_dict()`` keeps working.
"""
from __future__ import annotations

import contextlib
from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, no_grad, to_value
from ..core.random import next_key, traced_key_source

__all__ = ["train_step", "TrainStep"]


def _as_tuple(x):
    if isinstance(x, (tuple, list)):
        return tuple(x)
    return (x,)


class TrainStep:
    def __init__(self, model, loss_fn: Callable, optimizer,
                 amp_level: Optional[str] = None,
                 amp_dtype: str = "bfloat16", donate: bool = True):
        self._model = model
        self._loss_fn = loss_fn
        self._opt = optimizer
        self._amp_level = amp_level
        self._amp_dtype = amp_dtype
        self._donate = donate

        pure_fn, params, buffers = model.functional()
        self._pure_fn = pure_fn
        self._param_objs = dict(model.named_parameters())
        self._buffer_objs = dict(model.named_buffers())

        opt_ids = {id(p) for p in optimizer._parameter_list}
        self._train_names = [k for k, p in self._param_objs.items()
                             if not p.stop_gradient and id(p) in opt_ids]
        self._frozen_names = [k for k in params if k not in
                              set(self._train_names)]

        # static per-param meta, in fixed name order (state itself is read
        # fresh from the model/optimizer objects at every call — see
        # _gather_state — so set_state_dict between calls is honored)
        opt = optimizer
        objs = [self._param_objs[k] for k in self._train_names]
        maps = opt._group_maps()
        self._metas = [opt._param_meta(p, maps) for p in objs]
        # L1Decay adds coeff*sign(p) to the grad inside the fused program
        # (the L2 slot in metas is 0 for L1 — see Optimizer._l1_coeff)
        self._l1 = tuple(opt._l1_coeff(p, maps) for p in objs)
        self._acc_names = opt._accumulator_names()
        masters = [opt._master(p) for p in objs]
        self._has_master = tuple(m is not None for m in masters)
        clip = opt._clip_mode()
        if clip is not None and clip[0] == "eager":
            # a custom ClipGradBase may do host-side work (float(), numpy)
            # that cannot run inside the compiled step — and if it could,
            # its thresholds would be constant-folded at trace time
            raise ValueError(
                "jit.train_step supports ClipGradByValue/ClipGradByNorm/"
                "ClipGradByGlobalNorm; custom grad_clip callables only work "
                "on the eager Optimizer.step() path")
        self._clip = clip
        self._compiled = {}

    # -- traced step ---------------------------------------------------------
    def _amp_ctx(self):
        if self._amp_level is None:
            return contextlib.nullcontext()
        from ..amp.auto_cast import auto_cast
        return auto_cast(enable=True, level=self._amp_level,
                         dtype=self._amp_dtype)

    def _build(self, n_inputs, n_labels, nan_check=False):
        pure_fn, loss_fn = self._pure_fn, self._loss_fn
        metas, acc_names = self._metas, self._acc_names
        has_master, clip = self._has_master, self._clip
        names = self._train_names
        opt_update = self._opt._build_fused(metas, has_master, clip,
                                            acc_names)

        def step_fn(trainable, slots, buffers, frozen, lr, step, rng, *data):
            inputs = data[:n_inputs]
            labels = data[n_inputs:]

            def loss_of(tp):
                all_p = {**tp, **frozen}
                with no_grad(), traced_key_source(rng), self._amp_ctx():
                    out, new_buf = pure_fn(all_p, buffers, *inputs)
                    wrapped = jax.tree_util.tree_map(
                        lambda v: Tensor(v, stop_gradient=True), out)
                    label_ts = tuple(Tensor(l, stop_gradient=True)
                                     for l in labels)
                    if isinstance(wrapped, (tuple, list)):
                        loss = loss_fn(*wrapped, *label_ts)
                    else:
                        loss = loss_fn(wrapped, *label_ts)
                loss_v = to_value(loss) if isinstance(loss, Tensor) else loss
                return loss_v.astype(jnp.float32), new_buf

            (loss, new_buf), grads = jax.value_and_grad(
                loss_of, has_aux=True)(trainable)

            g_vals = tuple(grads[k] for k in names)
            p_vals = tuple(trainable[k] for k in names)
            if any(self._l1):
                g_vals = tuple(
                    g + c * jnp.sign(p.astype(g.dtype)) if c else g
                    for g, p, c in zip(g_vals, p_vals, self._l1))
            acc_vals = slots["accs"]
            new_ps, new_accs, new_masters = opt_update(
                p_vals, g_vals, acc_vals, slots["masters"], lr, step)
            new_trainable = dict(zip(names, new_ps))
            new_slots = {"accs": new_accs, "masters": new_masters}
            if nan_check:
                # FLAGS_check_nan_inf inside the compiled program: finite
                # flags for loss, every gradient and every updated param
                # (reference checks post-kernel in the interpreter too,
                # framework/new_executor/nan_inf_utils.cc)
                watched = {"loss": loss}
                watched.update({f"grad:{k}": g
                                for k, g in zip(names, g_vals)})
                watched.update({f"param:{k}": p
                                for k, p in new_trainable.items()})
                finite = jnp.stack([jnp.isfinite(v).all()
                                    for v in watched.values()])
                return loss, new_trainable, new_slots, new_buf, finite
            return loss, new_trainable, new_slots, new_buf

        # no donation in nan-check mode: on failure the pre-step state must
        # survive (donated inputs would be invalidated)
        donate = (0, 1, 2) if self._donate and not nan_check else ()
        return jax.jit(step_fn, donate_argnums=donate)

    # -- state gather (fresh every call: reference reads, no device work) ----
    def _gather_state(self):
        opt = self._opt
        objs = [self._param_objs[k] for k in self._train_names]
        trainable = {k: to_value(self._param_objs[k])
                     for k in self._train_names}
        frozen = {k: to_value(self._param_objs[k])
                  for k in self._frozen_names}
        slots = {
            "accs": {n: tuple(opt._get_accumulator(n, p) for p in objs)
                     for n in self._acc_names},
            "masters": tuple(
                opt._accumulators["master_weight"][id(p)]
                for i, p in enumerate(objs) if self._has_master[i]),
        }
        buffers = {k: to_value(v) for k, v in self._buffer_objs.items()}
        return trainable, slots, buffers, frozen

    # -- call ----------------------------------------------------------------
    def __call__(self, inputs, labels=()):
        inputs = tuple(to_value(x) if isinstance(x, Tensor) else jnp.asarray(x)
                       for x in _as_tuple(inputs))
        labels = tuple(to_value(x) if isinstance(x, Tensor) else jnp.asarray(x)
                       for x in _as_tuple(labels))
        from ..core.flags import GLOBAL_FLAGS
        from ..ops.pallas._util import (fused_train_mode,
                                        fused_vmem_budget, interpret_mode)
        from ..ops.pallas.registry import KERNELS
        nan_check = bool(GLOBAL_FLAGS.get("check_nan_inf"))
        # the fused-train mode, any registry force pins, the VMEM
        # budget and the interpret override are trace-time dispatch
        # inputs for models routed through the fused training kernels:
        # a flipped knob must retrace, not replay a program compiled
        # under the other routing
        key = (len(inputs), len(labels), nan_check,
               fused_train_mode(), KERNELS.forced_state(),
               fused_vmem_budget(), bool(interpret_mode()),
               tuple((x.shape, str(x.dtype)) for x in inputs + labels))
        fn = self._compiled.get(key)
        if fn is None:
            fn = self._build(len(inputs), len(labels), nan_check=nan_check)
            self._compiled[key] = fn
        trainable, slots, buffers, frozen = self._gather_state()
        lr = jnp.asarray(self._opt.get_lr(), jnp.float32)
        step = jnp.asarray(self._opt._global_step + 1, jnp.float32)
        rng = next_key()
        out = fn(trainable, slots, buffers, frozen, lr, step, rng,
                 *inputs, *labels)
        if nan_check:
            loss, self._trainable, self._slots, self._buffers, finite = out
            import numpy as np
            ok = np.asarray(finite)
            if not ok.all():
                watched = (["loss"] +
                           [f"grad:{k}" for k in self._train_names] +
                           [f"param:{k}" for k in self._train_names])
                bad = [n for n, o in zip(watched, ok) if not o]
                msg = (f"check_nan_inf: non-finite values in compiled train "
                       f"step: {bad[:8]}{'...' if len(bad) > 8 else ''}")
                if GLOBAL_FLAGS.get("check_nan_inf_level") >= 1:
                    import warnings
                    warnings.warn(msg, stacklevel=2)
                else:
                    # pre-step state is intact (no donation in this mode):
                    # drop the poisoned update and fail loudly
                    raise FloatingPointError(msg)
        else:
            loss, self._trainable, self._slots, self._buffers = out
        self._opt._global_step += 1
        self._writeback()
        return Tensor(loss, stop_gradient=True)

    # -- state sync (reference swaps only; no device work) -------------------
    def _writeback(self):
        opt = self._opt
        mi = 0
        for i, k in enumerate(self._train_names):
            p = self._param_objs[k]
            p._replace_value(self._trainable[k])
            for n in self._acc_names:
                opt._accumulators[n][id(p)] = self._slots["accs"][n][i]
            if self._has_master[i]:
                opt._accumulators["master_weight"][id(p)] = \
                    self._slots["masters"][mi]
                mi += 1
        for k, obj in self._buffer_objs.items():
            if k in self._buffers:
                obj._value = self._buffers[k]


def train_step(model, loss_fn, optimizer, amp_level=None,
               amp_dtype="bfloat16", donate=True) -> TrainStep:
    """Compile model forward + ``loss_fn`` + backward + optimizer into one
    donated XLA program. See module docstring."""
    return TrainStep(model, loss_fn, optimizer, amp_level=amp_level,
                     amp_dtype=amp_dtype, donate=donate)
