"""Metrics (reference: python/paddle/metric/metrics.py)."""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self._name = name or "acc"
        self.maxk = max(self.topk)
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        p = pred.numpy() if isinstance(pred, Tensor) else np.asarray(pred)
        l = label.numpy() if isinstance(label, Tensor) else np.asarray(label)
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l[..., 0]
        topk_idx = np.argsort(-p, axis=-1)[..., :self.maxk]
        correct = topk_idx == l[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct):
        c = correct.numpy() if isinstance(correct, Tensor) else \
            np.asarray(correct)
        n = c.shape[0] if c.ndim > 0 else 1
        for i, k in enumerate(self.topk):
            hit = c[..., :k].any(axis=-1).sum()
            self.total[i] += float(hit)
            self.count[i] += int(np.prod(c.shape[:-1]))
        res = [t / max(cnt, 1) for t, cnt in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name=None):
        super().__init__()
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        l = labels.numpy() if isinstance(labels, Tensor) else \
            np.asarray(labels)
        pred_pos = (p > 0.5).reshape(-1)
        lab = l.reshape(-1).astype(bool)
        self.tp += int((pred_pos & lab).sum())
        self.fp += int((pred_pos & ~lab).sum())

    def accumulate(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__()
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        l = labels.numpy() if isinstance(labels, Tensor) else \
            np.asarray(labels)
        pred_pos = (p > 0.5).reshape(-1)
        lab = l.reshape(-1).astype(bool)
        self.tp += int((pred_pos & lab).sum())
        self.fn += int((~pred_pos & lab).sum())

    def accumulate(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__()
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        l = labels.numpy() if isinstance(labels, Tensor) else \
            np.asarray(labels)
        if p.ndim == 2:
            p = p[:, 1]
        idx = (p * self.num_thresholds).astype(int).clip(
            0, self.num_thresholds)
        lab = l.reshape(-1).astype(bool)
        np.add.at(self._stat_pos, idx[lab], 1)
        np.add.at(self._stat_neg, idx[~lab], 1)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # integrate from highest threshold down
        pos = self._stat_pos[::-1].cumsum()
        neg = self._stat_neg[::-1].cumsum()
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    from ..tensor.search import topk as topk_fn
    import jax.numpy as jnp
    vals, idx = topk_fn(input, k)
    l = label
    if l.ndim == 1:
        from ..tensor.manipulation import unsqueeze
        l = unsqueeze(l, [-1])
    from ..core.tensor import dispatch
    return dispatch(
        lambda i, lb: jnp.mean(jnp.any(i == lb, axis=-1)
                               .astype(jnp.float32)),
        (idx, l), name="accuracy")
