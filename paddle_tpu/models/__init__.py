"""Model zoo (reference: PaddleNLP-style model families built on the
framework; in-repo reference models python/paddle/vision/models plus the
incubate transformer stack)."""
from . import llama  # noqa: F401
