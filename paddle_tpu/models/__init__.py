"""Model zoo (reference: PaddleNLP-style model families built on the
framework; in-repo reference models python/paddle/vision/models plus the
incubate transformer stack)."""
from . import llama  # noqa: F401
from . import gpt  # noqa: F401
from . import bert  # noqa: F401
from . import vit  # noqa: F401
