"""Shared model-zoo helpers: mesh-axis resolution, masked cross entropy,
the pre-norm transformer block, and init utilities."""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import layer_norm as fused_layer_norm
from ..ops.flash_attention import flash_attention


def resolve_mesh_axes(mesh: Mesh) -> Tuple[Optional[str], Optional[str]]:
    """(fsdp, tp) axis names, honoring paddle-convention fallbacks
    ('sharding' for fsdp, 'mp' for tp) like llama.param_shardings."""
    have = set(mesh.axis_names)
    fsdp = "fsdp" if "fsdp" in have else ("sharding"
                                          if "sharding" in have else None)
    tp = "tp" if "tp" in have else ("mp" if "mp" in have else None)
    return fsdp, tp


def spec_fn(mesh: Mesh):
    """Returns s(*names) building a PartitionSpec restricted to mesh axes."""
    have = set(mesh.axis_names)

    def s(*names):
        return P(*[n if n in have or n is None else None for n in names])

    return s


def normal_init(key, shape, std=0.02, dtype=jnp.bfloat16):
    return (jax.random.normal(key, shape, jnp.float32) * std).astype(dtype)


def masked_cross_entropy(logits, labels) -> jax.Array:
    """Token cross entropy in fp32; negative labels are ignored.
    Shared by llama/gpt/bert losses (reference:
    c_softmax_with_cross_entropy semantics with ignore_index)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    valid = labels >= 0
    safe = jnp.where(valid, labels, 0)
    picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    n = jnp.maximum(jnp.sum(valid), 1)
    return -jnp.sum(jnp.where(valid, picked, 0.0)) / n


def fused_linear_cross_entropy(hidden, head, labels,
                               chunk_size: int = 1024) -> jax.Array:
    """Chunked lm-head + cross entropy that never materializes the full
    [T, V] logits (Liger-kernel style, arXiv:2410.10989): a lax.scan over
    token chunks computes logits [chunk, V] in fp32, reduces them to
    per-token (logsumexp, picked-logit) scalars, and the rematerialized
    backward recomputes each chunk — peak activation memory drops from
    O(T*V) to O(chunk*V). Semantics identical to
    ``masked_cross_entropy(hidden @ head, labels)``.

    hidden [..., D] (any leading shape), head [D, V], labels [...] int
    (negative = ignore).
    """
    d = hidden.shape[-1]
    flat = hidden.reshape(-1, d)
    lab = labels.reshape(-1)
    t = flat.shape[0]
    c = min(chunk_size, t)
    n_chunks = -(-t // c)
    pad = n_chunks * c - t
    if pad:
        flat = jnp.pad(flat, ((0, pad), (0, 0)))
        lab = jnp.pad(lab, (0, pad), constant_values=-1)
    flat = flat.reshape(n_chunks, c, d)
    lab = lab.reshape(n_chunks, c)

    @jax.checkpoint
    def chunk_ce(x_c, l_c):
        logits = (x_c @ head).astype(jnp.float32)     # [c, V] — the only
        lse = jax.scipy.special.logsumexp(logits, -1)  # [c]   live chunk
        valid = l_c >= 0
        safe = jnp.where(valid, l_c, 0)
        picked = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
        ce = jnp.where(valid, lse - picked, 0.0)
        return jnp.sum(ce), jnp.sum(valid).astype(jnp.float32)

    def scan_fn(carry, xs):
        s, n = carry
        cs, cn = chunk_ce(*xs)
        return (s + cs, n + cn), None

    (total, count), _ = jax.lax.scan(
        scan_fn, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (flat, lab))
    return total / jnp.maximum(count, 1.0)


def prenorm_block(lp, x, *, num_heads, head_dim, eps, causal):
    """Pre-norm transformer block (GPT/ViT convention): LN → QKV →
    flash attention → proj residual; LN → GELU MLP residual.
    Layer params: ln1_w/b, qkv(+_b), proj(+_b), ln2_w/b, fc(+_b),
    fc_out(+_b)."""
    b, s, D = x.shape
    h = fused_layer_norm(x, lp["ln1_w"].astype(x.dtype),
                         lp["ln1_b"].astype(x.dtype), eps)
    qkv = h @ lp["qkv"] + lp["qkv_b"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, s, num_heads, head_dim)
    k = k.reshape(b, s, num_heads, head_dim)
    v = v.reshape(b, s, num_heads, head_dim)
    attn = flash_attention(q, k, v, causal=causal).reshape(b, s, D)
    x = x + attn @ lp["proj"] + lp["proj_b"]
    h = fused_layer_norm(x, lp["ln2_w"].astype(x.dtype),
                         lp["ln2_b"].astype(x.dtype), eps)
    ff = jax.nn.gelu(h @ lp["fc"] + lp["fc_b"])
    x = x + ff @ lp["fc_out"] + lp["fc_out_b"]
    return x
