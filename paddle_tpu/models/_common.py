"""Shared model-zoo helpers: mesh-axis resolution, masked cross entropy,
the pre-norm transformer block, and init utilities."""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import layer_norm as fused_layer_norm
from ..ops.flash_attention import flash_attention


def resolve_mesh_axes(mesh: Mesh) -> Tuple[Optional[str], Optional[str]]:
    """(fsdp, tp) axis names, honoring paddle-convention fallbacks
    ('sharding' for fsdp, 'mp' for tp) like llama.param_shardings."""
    have = set(mesh.axis_names)
    fsdp = "fsdp" if "fsdp" in have else ("sharding"
                                          if "sharding" in have else None)
    tp = "tp" if "tp" in have else ("mp" if "mp" in have else None)
    return fsdp, tp


def spec_fn(mesh: Mesh):
    """Returns s(*names) building a PartitionSpec restricted to mesh axes."""
    have = set(mesh.axis_names)

    def s(*names):
        return P(*[n if n in have or n is None else None for n in names])

    return s


def normal_init(key, shape, std=0.02, dtype=jnp.bfloat16):
    return (jax.random.normal(key, shape, jnp.float32) * std).astype(dtype)


def masked_cross_entropy(logits, labels) -> jax.Array:
    """Token cross entropy in fp32; negative labels are ignored.
    Shared by llama/gpt/bert losses (reference:
    c_softmax_with_cross_entropy semantics with ignore_index)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    valid = labels >= 0
    safe = jnp.where(valid, labels, 0)
    picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    n = jnp.maximum(jnp.sum(valid), 1)
    return -jnp.sum(jnp.where(valid, picked, 0.0)) / n


def prenorm_block(lp, x, *, num_heads, head_dim, eps, causal):
    """Pre-norm transformer block (GPT/ViT convention): LN → QKV →
    flash attention → proj residual; LN → GELU MLP residual.
    Layer params: ln1_w/b, qkv(+_b), proj(+_b), ln2_w/b, fc(+_b),
    fc_out(+_b)."""
    b, s, D = x.shape
    h = fused_layer_norm(x, lp["ln1_w"].astype(x.dtype),
                         lp["ln1_b"].astype(x.dtype), eps)
    qkv = h @ lp["qkv"] + lp["qkv_b"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, s, num_heads, head_dim)
    k = k.reshape(b, s, num_heads, head_dim)
    v = v.reshape(b, s, num_heads, head_dim)
    attn = flash_attention(q, k, v, causal=causal).reshape(b, s, D)
    x = x + attn @ lp["proj"] + lp["proj_b"]
    h = fused_layer_norm(x, lp["ln2_w"].astype(x.dtype),
                         lp["ln2_b"].astype(x.dtype), eps)
    ff = jax.nn.gelu(h @ lp["fc"] + lp["fc_b"])
    x = x + ff @ lp["fc_out"] + lp["fc_out_b"]
    return x
