"""BERT / ERNIE-style bidirectional encoder with MLM + pooler heads.

Reference capability: ERNIE-3.0 (BASELINE config 4) is architecturally a
BERT-family encoder (its fused inference path is
fused_multi_transformer_kernel.cu; our serving analog is
paddle_tpu.inference). TPU-first structure mirrors models/llama.py:
stacked scanned layer params, non-causal flash attention, {fsdp, tp}
sharding specs.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import layer_norm as fused_layer_norm
from ..ops.flash_attention import flash_attention
from ._common import (resolve_mesh_axes, spec_fn, normal_init,
                      masked_cross_entropy)


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_epsilon: float = 1e-12
    dtype: Any = jnp.bfloat16
    remat: bool = True

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


BERT_TINY = BertConfig(vocab_size=512, hidden_size=128,
                       intermediate_size=256, num_hidden_layers=2,
                       num_attention_heads=4, max_position_embeddings=128)

# ERNIE-3.0 shares the encoder; alias for config parity
ErnieConfig = BertConfig
ERNIE_TINY = BERT_TINY


def init_params(cfg: BertConfig, key=None, dtype=None) -> Dict:
    dtype = dtype or cfg.dtype
    key = key if key is not None else jax.random.key(0)
    D, F, V = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    L = cfg.num_hidden_layers
    k = jax.random.split(key, 12)

    def nrm(kk, shape):
        return normal_init(kk, shape, dtype=dtype)

    return {
        "word_emb": nrm(k[0], (V, D)),
        "pos_emb": nrm(k[1], (cfg.max_position_embeddings, D)),
        "type_emb": nrm(k[2], (cfg.type_vocab_size, D)),
        "emb_ln_w": jnp.ones((D,), jnp.float32),
        "emb_ln_b": jnp.zeros((D,), jnp.float32),
        "layers": {
            "qkv": nrm(k[3], (L, D, 3 * D)),
            "qkv_b": jnp.zeros((L, 3 * D), dtype),
            "proj": nrm(k[4], (L, D, D)),
            "proj_b": jnp.zeros((L, D), dtype),
            "attn_ln_w": jnp.ones((L, D), jnp.float32),
            "attn_ln_b": jnp.zeros((L, D), jnp.float32),
            "fc": nrm(k[5], (L, D, F)),
            "fc_b": jnp.zeros((L, F), dtype),
            "fc_out": nrm(k[6], (L, F, D)),
            "fc_out_b": jnp.zeros((L, D), dtype),
            "ffn_ln_w": jnp.ones((L, D), jnp.float32),
            "ffn_ln_b": jnp.zeros((L, D), jnp.float32),
        },
        "pooler_w": nrm(k[7], (D, D)),
        "pooler_b": jnp.zeros((D,), dtype),
        "mlm_dense": nrm(k[8], (D, D)),
        "mlm_dense_b": jnp.zeros((D,), dtype),
        "mlm_ln_w": jnp.ones((D,), jnp.float32),
        "mlm_ln_b": jnp.zeros((D,), jnp.float32),
        "mlm_bias": jnp.zeros((V,), jnp.float32),
    }


def param_shardings(mesh: Mesh, cfg: BertConfig) -> Dict:
    fsdp, tp = resolve_mesh_axes(mesh)
    s = spec_fn(mesh)

    return {
        "word_emb": s(tp, fsdp),
        "pos_emb": s(None, fsdp),
        "type_emb": s(None, fsdp),
        "emb_ln_w": s(None), "emb_ln_b": s(None),
        "layers": {
            "qkv": s(None, fsdp, tp), "qkv_b": s(None, tp),
            "proj": s(None, tp, fsdp), "proj_b": s(None, None),
            "attn_ln_w": s(None, None), "attn_ln_b": s(None, None),
            "fc": s(None, fsdp, tp), "fc_b": s(None, tp),
            "fc_out": s(None, tp, fsdp), "fc_out_b": s(None, None),
            "ffn_ln_w": s(None, None), "ffn_ln_b": s(None, None),
        },
        "pooler_w": s(fsdp, tp), "pooler_b": s(tp),
        "mlm_dense": s(fsdp, tp), "mlm_dense_b": s(tp),
        "mlm_ln_w": s(None), "mlm_ln_b": s(None),
        "mlm_bias": s(tp),
    }


def _encoder_layer(lp, x, cfg: BertConfig, attn_bias=None):
    """Post-norm encoder block (BERT convention)."""
    H, hd = cfg.num_attention_heads, cfg.head_dim
    b, s, D = x.shape
    qkv = x @ lp["qkv"] + lp["qkv_b"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, s, H, hd)
    k = k.reshape(b, s, H, hd)
    v = v.reshape(b, s, H, hd)
    if attn_bias is not None:
        # padding mask path: fall back to the masked dense composition
        # (flash kernel is mask-free; XLA fuses this fine at BERT lengths)
        scores = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32),
                            k.astype(jnp.float32)) / (hd ** 0.5)
        scores = scores + attn_bias
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhst,bthd->bshd", probs,
                          v.astype(jnp.float32)).astype(x.dtype)
    else:
        attn = flash_attention(q, k, v, causal=False)
    attn = attn.reshape(b, s, D)
    x = fused_layer_norm(x + attn @ lp["proj"] + lp["proj_b"],
                         lp["attn_ln_w"].astype(x.dtype),
                         lp["attn_ln_b"].astype(x.dtype),
                         cfg.layer_norm_epsilon)
    ff = jax.nn.gelu(x @ lp["fc"] + lp["fc_b"])
    x = fused_layer_norm(x + ff @ lp["fc_out"] + lp["fc_out_b"],
                         lp["ffn_ln_w"].astype(x.dtype),
                         lp["ffn_ln_b"].astype(x.dtype),
                         cfg.layer_norm_epsilon)
    return x


def forward(params: Dict, input_ids, cfg: BertConfig,
            token_type_ids=None, attention_mask=None):
    """Returns (sequence_output [B,S,D], pooled_output [B,D])."""
    b, s = input_ids.shape
    x = jnp.take(params["word_emb"], input_ids, axis=0)
    x = x + params["pos_emb"][:s][None]
    if token_type_ids is None:
        token_type_ids = jnp.zeros_like(input_ids)
    x = x + jnp.take(params["type_emb"], token_type_ids, axis=0)
    x = fused_layer_norm(x, params["emb_ln_w"].astype(x.dtype),
                         params["emb_ln_b"].astype(x.dtype),
                         cfg.layer_norm_epsilon)
    attn_bias = None
    if attention_mask is not None:
        attn_bias = jnp.where(attention_mask[:, None, None, :] > 0,
                              0.0, -1e9).astype(jnp.float32)
    body = partial(_encoder_layer, cfg=cfg, attn_bias=attn_bias)
    if cfg.remat:
        body = jax.checkpoint(body)

    def scan_fn(carry, lp):
        return body(lp, carry), None

    x, _ = jax.lax.scan(scan_fn, x, params["layers"])
    pooled = jnp.tanh(x[:, 0] @ params["pooler_w"] + params["pooler_b"])
    return x, pooled


def mlm_logits(params: Dict, seq_out, cfg: BertConfig) -> jax.Array:
    """MLM head: dense + gelu + layer norm + tied-embedding decoder."""
    h = jax.nn.gelu(seq_out @ params["mlm_dense"] + params["mlm_dense_b"])
    h = fused_layer_norm(h, params["mlm_ln_w"].astype(h.dtype),
                         params["mlm_ln_b"].astype(h.dtype),
                         cfg.layer_norm_epsilon)
    return h @ params["word_emb"].T + params["mlm_bias"]


def mlm_loss(params: Dict, input_ids, labels, cfg: BertConfig,
             token_type_ids=None, attention_mask=None) -> jax.Array:
    """Masked-LM cross entropy; labels == -100 (or any negative) ignored
    (BASELINE config 2: BERT-base MLM pretraining)."""
    seq_out, _ = forward(params, input_ids, cfg, token_type_ids,
                         attention_mask)
    return masked_cross_entropy(mlm_logits(params, seq_out, cfg), labels)
