"""GPT-2/3-style decoder LM.

Reference capability: the fleet GPT configs under
test/collective/fleet/hybrid_strategy (the reference's standard
hybrid-parallel benchmark model family) and python/paddle/incubate fused
transformer blocks. Same TPU-first structure as models/llama.py: stacked
layer params scanned by lax.scan, flash attention, sharding specs keyed on
{fsdp, tp} mesh axes.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import layer_norm as fused_layer_norm
from ..ops.pallas.fused_train import fused_linear_ce
from ._common import (resolve_mesh_axes, spec_fn, normal_init,
                      prenorm_block)


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 1024
    layer_norm_epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # fused linear+CE loss path: None reads FLAGS_fused_train,
    # False/"ref" pins the chunked lax.scan composition, "pallas"
    # forces the Pallas custom_vjp kernel (see models/llama.py)
    fused_train: Any = None

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


GPT_TINY = GPTConfig(vocab_size=512, hidden_size=128, intermediate_size=256,
                     num_hidden_layers=2, num_attention_heads=4,
                     max_position_embeddings=128)


def init_params(cfg: GPTConfig, key=None, dtype=None) -> Dict:
    dtype = dtype or cfg.dtype
    key = key if key is not None else jax.random.key(0)
    D, F, V = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    L = cfg.num_hidden_layers
    k = jax.random.split(key, 8)

    def nrm(kk, shape):
        return normal_init(kk, shape, dtype=dtype)

    return {
        "wte": nrm(k[0], (V, D)),
        "wpe": nrm(k[1], (cfg.max_position_embeddings, D)),
        "layers": {
            "ln1_w": jnp.ones((L, D), jnp.float32),
            "ln1_b": jnp.zeros((L, D), jnp.float32),
            "qkv": nrm(k[2], (L, D, 3 * D)),
            "qkv_b": jnp.zeros((L, 3 * D), dtype),
            "proj": nrm(k[3], (L, D, D)),
            "proj_b": jnp.zeros((L, D), dtype),
            "ln2_w": jnp.ones((L, D), jnp.float32),
            "ln2_b": jnp.zeros((L, D), jnp.float32),
            "fc": nrm(k[4], (L, D, F)),
            "fc_b": jnp.zeros((L, F), dtype),
            "fc_out": nrm(k[5], (L, F, D)),
            "fc_out_b": jnp.zeros((L, D), dtype),
        },
        "ln_f_w": jnp.ones((D,), jnp.float32),
        "ln_f_b": jnp.zeros((D,), jnp.float32),
    }


def param_shardings(mesh: Mesh, cfg: GPTConfig) -> Dict:
    fsdp, tp = resolve_mesh_axes(mesh)
    s = spec_fn(mesh)

    return {
        "wte": s(tp, fsdp),
        "wpe": s(None, fsdp),
        "layers": {
            "ln1_w": s(None, None), "ln1_b": s(None, None),
            "qkv": s(None, fsdp, tp), "qkv_b": s(None, tp),
            "proj": s(None, tp, fsdp), "proj_b": s(None, None),
            "ln2_w": s(None, None), "ln2_b": s(None, None),
            "fc": s(None, fsdp, tp), "fc_b": s(None, tp),
            "fc_out": s(None, tp, fsdp), "fc_out_b": s(None, None),
        },
        "ln_f_w": s(None), "ln_f_b": s(None),
    }


def _block(lp, x, cfg: GPTConfig):
    return prenorm_block(lp, x, num_heads=cfg.num_attention_heads,
                         head_dim=cfg.head_dim,
                         eps=cfg.layer_norm_epsilon, causal=True)


def forward_hidden(params: Dict, tokens, cfg: GPTConfig) -> jax.Array:
    """Final-layer-norm hidden states [B, S, D] (the fused loss applies
    the tied lm head in chunks instead of materializing [B, S, V])."""
    b, s = tokens.shape
    x = jnp.take(params["wte"], tokens, axis=0) + \
        params["wpe"][:s][None, :, :]
    body = partial(_block, cfg=cfg)
    if cfg.remat:
        body = jax.checkpoint(body)

    def scan_fn(carry, lp):
        return body(lp, carry), None

    x, _ = jax.lax.scan(scan_fn, x, params["layers"])
    return fused_layer_norm(x, params["ln_f_w"].astype(x.dtype),
                            params["ln_f_b"].astype(x.dtype),
                            cfg.layer_norm_epsilon)


def forward(params: Dict, tokens, cfg: GPTConfig) -> jax.Array:
    # tied embeddings (GPT-2 convention)
    return forward_hidden(params, tokens, cfg) @ params["wte"].T


def loss_fn(params: Dict, tokens, labels, cfg: GPTConfig) -> jax.Array:
    """Next-token cross entropy via the fused chunked lm-head+CE —
    [B, S, V] logits are never materialized (previously full logits
    through ``masked_cross_entropy``); semantics unchanged (negative
    labels ignored, fp32 masked token mean)."""
    hidden = forward_hidden(params, tokens, cfg)
    return fused_linear_ce(hidden, params["wte"].T, labels,
                           mode=cfg.fused_train)
