"""LLaMA family — the flagship model (BASELINE config 3, the north star).

Two tiers:
1. **Functional core** (this file's ``init_params``/``forward``/
   ``build_forward``): pure pytree params + jax functions with GSPMD
   sharding rules — the performance path used by the Trainer, bench, and
   the multichip dryrun. RMSNorm/rope/flash-attention route through the
   ops/ pack (Pallas on TPU).
2. **Layer API** (``LlamaForCausalLM``): Paddle-style nn.Layer built on the
   fleet TP layers for eager/dygraph use.

Sharding rules (mesh axes [dp, fsdp, tp, sp] — SURVEY.md §7 step 4):
- embeddings/vocab: vocab dim on tp, hidden on fsdp
- attn qkv/o and mlp in/out projections: alternate (fsdp, tp)/(tp, fsdp) —
  Megatron layout, collectives ride ICI on tp
- activations: [batch→dp, seq→sp]
GQA (num_key_value_heads < num_attention_heads) supported.
"""
from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.rope import build_rope_cache, apply_rope
from ._common import masked_cross_entropy as _masked_cross_entropy
from ..ops import rms_norm as fused_rms_norm
from ..ops.flash_attention import flash_attention
from ..ops.pallas.fused_train import (fused_linear_ce,
                                      fused_swiglu as _fused_swiglu_train)
from ..ops.pallas.norms import residual_rms_norm as _residual_rms_norm

__all__ = ["LlamaConfig", "init_params", "forward", "loss_fn",
           "build_forward", "param_shardings", "tp_param_specs",
           "LLAMA_7B", "LLAMA_TINY"]


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # fused training-path kernels (Liger-style): None reads
    # FLAGS_fused_train (default on); False/"ref" pins the unfused
    # composition (bit-identical to the pre-fusion path), "pallas"
    # forces the Pallas kernels (tests / audit tracing on CPU)
    fused_train: Any = None

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


LLAMA_7B = LlamaConfig()
LLAMA_TINY = LlamaConfig(vocab_size=512, hidden_size=128,
                         intermediate_size=256, num_hidden_layers=2,
                         num_attention_heads=4, num_key_value_heads=2,
                         max_position_embeddings=256)


def init_params(cfg: LlamaConfig, key=None, dtype=None) -> Dict:
    """Initialise the parameter pytree (layers stacked on a leading axis for
    scan-friendly layout — one compiled layer body instead of L copies)."""
    dtype = dtype or cfg.dtype
    key = key if key is not None else jax.random.key(0)
    D, F, V = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    H = cfg.num_attention_heads
    KV = cfg.num_key_value_heads
    hd = cfg.head_dim
    L = cfg.num_hidden_layers
    k = jax.random.split(key, 10)
    std = 0.02

    def nrm(kk, shape, fan_in=None):
        return (jax.random.normal(kk, shape, dtype=jnp.float32) * std
                ).astype(dtype)

    params = {
        "embed_tokens": nrm(k[0], (V, D)),
        "layers": {
            "input_norm": jnp.ones((L, D), dtype=jnp.float32),
            "q_proj": nrm(k[1], (L, D, H * hd)),
            "k_proj": nrm(k[2], (L, D, KV * hd)),
            "v_proj": nrm(k[3], (L, D, KV * hd)),
            "o_proj": nrm(k[4], (L, H * hd, D)),
            "post_norm": jnp.ones((L, D), dtype=jnp.float32),
            "gate_proj": nrm(k[5], (L, D, F)),
            "up_proj": nrm(k[6], (L, D, F)),
            "down_proj": nrm(k[7], (L, F, D)),
        },
        "final_norm": jnp.ones((D,), dtype=jnp.float32),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = nrm(k[8], (D, V))
    return params


def param_shardings(mesh: Mesh, cfg: LlamaConfig) -> Dict:
    """PartitionSpecs per param (the sharding 'rules' — the analog of the
    reference's per-op spmd_rules applied to weights)."""
    have = set(mesh.axis_names)
    fsdp = "fsdp" if "fsdp" in have else ("sharding"
                                          if "sharding" in have else None)
    tp = "tp" if "tp" in have else ("mp" if "mp" in have else None)

    def s(*names):
        return P(*[n if n in have or n is None else None for n in names])

    specs = {
        "embed_tokens": s(tp, fsdp),
        "layers": {
            "input_norm": s(None, None),
            "q_proj": s(None, fsdp, tp),
            "k_proj": s(None, fsdp, tp),
            "v_proj": s(None, fsdp, tp),
            "o_proj": s(None, tp, fsdp),
            "post_norm": s(None, None),
            "gate_proj": s(None, fsdp, tp),
            "up_proj": s(None, fsdp, tp),
            "down_proj": s(None, tp, fsdp),
        },
        "final_norm": s(None),
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = s(fsdp, tp)
    return specs


def tp_param_specs(cfg: LlamaConfig, axis: str = "tp",
                   collective: str = "psum", params=None) -> Dict:
    """PartitionSpecs for SERVING tensor parallelism over a 1-D mesh:
    head-axis (Megatron) sharding of the per-layer projections, with
    everything the replicated residual stream touches kept replicated
    (embedding, norms, lm_head) so greedy sampling runs identically on
    every shard.

    ``collective="psum"`` row-shards o_proj/down_proj (their partial
    products all-reduce, one psum per sub-block — the bandwidth-optimal
    placement). ``collective="gather"`` keeps o_proj/down_proj
    REPLICATED and all-gathers the per-shard attention heads / MLP
    columns instead: every matmul then has exactly the single-device
    operands and shapes, which is what makes that mode's greedy output
    bit-identical (inference/tp.py documents the contract).

    ``params``: pass the actual tree when it may carry QUANTIZED
    weight leaves (``{"qw8"|"qw4": q, "scale": s}`` —
    quantization/ptq.py): the spec tree must mirror their dict
    structure. The integer tile keeps the base weight's spec
    (column sharding survives packing — int4 packs the hidden axis,
    never the output columns of q/k/v/gate/up) and the
    per-output-channel scales shard with the output columns (or stay
    replicated for the row-sharded o/down projections)."""
    col = P(None, None, axis)                  # shard output columns
    row = P(None, axis, None) if collective == "psum" else P(None, None,
                                                             None)
    specs = {
        "embed_tokens": P(None, None),
        "layers": {
            "input_norm": P(None, None),
            "q_proj": col, "k_proj": col, "v_proj": col,
            "o_proj": row,
            "post_norm": P(None, None),
            "gate_proj": col, "up_proj": col,
            "down_proj": row,
        },
        "final_norm": P(None),
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P(None, None)
    if params is not None:
        layers = params.get("layers", {})
        for k, w in layers.items():
            if isinstance(w, dict):
                base = specs["layers"][k]
                qk = "qw8" if "qw8" in w else "qw4"
                s_spec = P(None, axis) if base[-1] == axis \
                    else P(None, None)
                specs["layers"][k] = {qk: base, "scale": s_spec}
    return specs


def _decoder_layer(layer_params, x, sin, cos, cfg: LlamaConfig,
                   attn_mask=None):
    """One decoder block on [B, S, D]."""
    H, KV, hd = (cfg.num_attention_heads, cfg.num_key_value_heads,
                 cfg.head_dim)
    h = fused_rms_norm(x, layer_params["input_norm"].astype(x.dtype),
                       cfg.rms_norm_eps, mode=cfg.fused_train)
    b, s, _ = h.shape
    q = (h @ layer_params["q_proj"]).reshape(b, s, H, hd)
    kk = (h @ layer_params["k_proj"]).reshape(b, s, KV, hd)
    v = (h @ layer_params["v_proj"]).reshape(b, s, KV, hd)
    q = apply_rope(q, sin, cos)
    kk = apply_rope(kk, sin, cos)
    # GQA handled natively by the kernel (KV heads indexed, not repeated)
    attn = flash_attention(q, kk, v, causal=True)
    attn = attn.reshape(b, s, H * hd)
    # fused training path (Liger-style): the residual add + post-norm
    # collapse into one kernel and SwiGLU's fwd/bwd each run as one
    # pass; the dispatched fallback is the EXACT pre-fusion
    # composition, so mode "ref" / off-TPU is bit-identical to the
    # pre-fusion block
    x, h = _residual_rms_norm(attn @ layer_params["o_proj"], x,
                              layer_params["post_norm"].astype(x.dtype),
                              cfg.rms_norm_eps, mode=cfg.fused_train)
    ff = _fused_swiglu_train(h @ layer_params["gate_proj"],
                             h @ layer_params["up_proj"],
                             mode=cfg.fused_train)
    x = x + ff @ layer_params["down_proj"]
    return x


def forward_hidden(params: Dict, tokens, cfg: LlamaConfig,
                   positions=None) -> jax.Array:
    """Final-norm hidden states [B, S, D]. Layer loop is a lax.scan over
    the stacked layer params (single compiled block; PP slicing reuses
    the same body). The fused loss applies the lm head in chunks instead
    of materializing [B, S, V] logits."""
    x = jnp.take(params["embed_tokens"], tokens, axis=0)
    sin, cos = build_rope_cache(tokens.shape[1], cfg.head_dim,
                                base=cfg.rope_theta)
    if positions is not None:
        sin = jnp.take(sin, positions, axis=0)
        cos = jnp.take(cos, positions, axis=0)

    body = partial(_decoder_layer, sin=sin, cos=cos, cfg=cfg)
    if cfg.remat:
        body = jax.checkpoint(body)

    def scan_fn(carry, layer_params):
        return body(layer_params, carry), None

    x, _ = jax.lax.scan(scan_fn, x, params["layers"])
    return fused_rms_norm(x, params["final_norm"].astype(x.dtype),
                          cfg.rms_norm_eps, mode=cfg.fused_train)


def forward(params: Dict, tokens, cfg: LlamaConfig,
            positions=None) -> jax.Array:
    """Logits for [B, S] int tokens (hidden states @ lm head)."""
    x = forward_hidden(params, tokens, cfg, positions)
    head = params.get("lm_head")
    if head is None:
        head = params["embed_tokens"].T
    return x @ head


def loss_fn(params: Dict, tokens, labels, cfg: LlamaConfig) -> jax.Array:
    """Next-token cross entropy in fp32 via the fused chunked
    lm-head+CE — full [B, S, V] logits are never materialized (the
    reference's fused c_softmax_with_cross_entropy has the same goal for
    vocab-sharded logits). Registry-dispatched: the Pallas custom_vjp
    kernel on TPU (neither logits nor their gradient touch HBM), the
    lax.scan composition elsewhere (``cfg.fused_train`` pins a
    variant)."""
    hidden = forward_hidden(params, tokens, cfg)
    head = params.get("lm_head")
    if head is None:
        head = params["embed_tokens"].T
    return fused_linear_ce(hidden, head, labels, mode=cfg.fused_train)


def build_forward(cfg: LlamaConfig, key=None):
    """(fn, params) pair for compile checks."""
    params = init_params(cfg, key)

    def fn(params, tokens):
        return forward(params, tokens, cfg)

    return fn, params


# ---------------------------------------------------------------------------
# Layer-API tier (Paddle-style), built on fleet TP layers when a hybrid
# topology is active, plain layers otherwise.
# ---------------------------------------------------------------------------
def _lazy_layer_api():
    from .. import nn
    from ..core.tensor import Tensor, dispatch
    from ..nn import functional as Fn

    class LlamaMLP(nn.Layer):
        def __init__(self, cfg: LlamaConfig):
            super().__init__()
            self.gate_proj = nn.Linear(cfg.hidden_size,
                                       cfg.intermediate_size,
                                       bias_attr=False)
            self.up_proj = nn.Linear(cfg.hidden_size, cfg.intermediate_size,
                                     bias_attr=False)
            self.down_proj = nn.Linear(cfg.intermediate_size,
                                       cfg.hidden_size, bias_attr=False)

        def forward(self, x):
            return self.down_proj(
                Fn.swiglu(self.gate_proj(x), self.up_proj(x)))

    class LlamaAttention(nn.Layer):
        def __init__(self, cfg: LlamaConfig):
            super().__init__()
            self.cfg = cfg
            D, H, KV, hd = (cfg.hidden_size, cfg.num_attention_heads,
                            cfg.num_key_value_heads, cfg.head_dim)
            self.q_proj = nn.Linear(D, H * hd, bias_attr=False)
            self.k_proj = nn.Linear(D, KV * hd, bias_attr=False)
            self.v_proj = nn.Linear(D, KV * hd, bias_attr=False)
            self.o_proj = nn.Linear(H * hd, D, bias_attr=False)

        def forward(self, x, position_ids=None):
            cfg = self.cfg
            b, s, _ = x.shape
            H, KV, hd = (cfg.num_attention_heads, cfg.num_key_value_heads,
                         cfg.head_dim)
            from ..tensor.manipulation import reshape
            q = reshape(self.q_proj(x), [b, s, H, hd])
            k = reshape(self.k_proj(x), [b, s, KV, hd])
            v = reshape(self.v_proj(x), [b, s, KV, hd])

            def rope_and_attend(qv, kv, vv):
                sin, cos = build_rope_cache(s, hd, base=cfg.rope_theta)
                qv = apply_rope(qv, sin, cos)
                kv = apply_rope(kv, sin, cos)
                return flash_attention(qv, kv, vv, causal=True)
            out = dispatch(rope_and_attend, (q, k, v), name="llama_attention")
            out = reshape(out, [b, s, H * hd])
            return self.o_proj(out)

    class LlamaDecoderLayer(nn.Layer):
        def __init__(self, cfg: LlamaConfig):
            super().__init__()
            self.input_layernorm = nn.RMSNorm(cfg.hidden_size,
                                              cfg.rms_norm_eps)
            self.self_attn = LlamaAttention(cfg)
            self.post_attention_layernorm = nn.RMSNorm(cfg.hidden_size,
                                                       cfg.rms_norm_eps)
            self.mlp = LlamaMLP(cfg)

        def forward(self, x):
            x = x + self.self_attn(self.input_layernorm(x))
            x = x + self.mlp(self.post_attention_layernorm(x))
            return x

    class LlamaModel(nn.Layer):
        def __init__(self, cfg: LlamaConfig):
            super().__init__()
            self.cfg = cfg
            self.embed_tokens = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
            self.layers = nn.LayerList(
                [LlamaDecoderLayer(cfg)
                 for _ in range(cfg.num_hidden_layers)])
            self.norm = nn.RMSNorm(cfg.hidden_size, cfg.rms_norm_eps)

        def forward(self, input_ids):
            x = self.embed_tokens(input_ids)
            for layer in self.layers:
                x = layer(x)
            return self.norm(x)

    class LlamaForCausalLM(nn.Layer):
        def __init__(self, cfg: LlamaConfig):
            super().__init__()
            self.cfg = cfg
            self.llama = LlamaModel(cfg)
            self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                     bias_attr=False)

        def forward(self, input_ids, labels=None):
            hidden = self.llama(input_ids)
            logits = self.lm_head(hidden)
            if labels is not None:
                from ..nn import functional as Fn
                loss = Fn.cross_entropy(
                    logits.reshape([-1, self.cfg.vocab_size]),
                    labels.reshape([-1]), ignore_index=-100)
                return loss, logits
            return logits

    return (LlamaMLP, LlamaAttention, LlamaDecoderLayer, LlamaModel,
            LlamaForCausalLM)


def __getattr__(name):
    if name in ("LlamaMLP", "LlamaAttention", "LlamaDecoderLayer",
                "LlamaModel", "LlamaForCausalLM"):
        classes = _lazy_layer_api()
        mapping = dict(zip(("LlamaMLP", "LlamaAttention",
                            "LlamaDecoderLayer", "LlamaModel",
                            "LlamaForCausalLM"), classes))
        import sys
        mod = sys.modules[__name__]
        for k, v in mapping.items():
            setattr(mod, k, v)
        return mapping[name]
    raise AttributeError(name)
