"""Stable-Diffusion-style UNet with cross-attention (BASELINE config 6).

Reference capability: ppdiffusers UNet2DConditionModel running on the
reference's CINN static path; here the whole denoise step jit-compiles to
one XLA program (the CINN-slot is XLA itself, SURVEY §2.6 item 7).
TPU notes: GroupNorm+SiLU+conv chains fuse in XLA; attention blocks use the
flash kernel over flattened spatial tokens; keep channel counts multiples
of 128 at the attention levels for MXU tiling.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, List, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor, to_value
from ..ops.flash_attention import flash_attention


@dataclasses.dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    model_channels: int = 320
    channel_mult: Tuple[int, ...] = (1, 2, 4, 4)
    num_res_blocks: int = 2
    attention_levels: Tuple[int, ...] = (1, 2, 3)   # levels with attention
    num_heads: int = 8
    context_dim: int = 768           # text-encoder hidden size
    groups: int = 32


UNET_TINY = UNetConfig(model_channels=32, channel_mult=(1, 2),
                       num_res_blocks=1, attention_levels=(1,),
                       num_heads=2, context_dim=32, groups=8)


def timestep_embedding(t, dim):
    """Sinusoidal timestep embedding (DDPM convention)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(10000.0) * jnp.arange(half) / half)
    args = jnp.asarray(t)[:, None].astype(jnp.float32) * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


class ResBlock(nn.Layer):
    def __init__(self, in_ch, out_ch, time_dim, groups):
        super().__init__()
        self.norm1 = nn.GroupNorm(min(groups, in_ch), in_ch)
        self.conv1 = nn.Conv2D(in_ch, out_ch, 3, padding=1)
        self.time_proj = nn.Linear(time_dim, out_ch)
        self.norm2 = nn.GroupNorm(min(groups, out_ch), out_ch)
        self.conv2 = nn.Conv2D(out_ch, out_ch, 3, padding=1)
        self.act = nn.Silu()
        self.skip = (nn.Conv2D(in_ch, out_ch, 1)
                     if in_ch != out_ch else None)

    def forward(self, x, temb):
        h = self.conv1(self.act(self.norm1(x)))
        h = h + self.time_proj(self.act(temb))[:, :, None, None]
        h = self.conv2(self.act(self.norm2(h)))
        return h + (self.skip(x) if self.skip is not None else x)


class SpatialTransformer(nn.Layer):
    """Self-attn + cross-attn + GEGLU ff over flattened spatial tokens
    (the ppdiffusers BasicTransformerBlock shape)."""

    def __init__(self, channels, num_heads, context_dim):
        super().__init__()
        self.norm_in = nn.GroupNorm(min(32, channels), channels)
        self.proj_in = nn.Conv2D(channels, channels, 1)
        self.ln1 = nn.LayerNorm(channels)
        self.self_q = nn.Linear(channels, channels, bias_attr=False)
        self.self_k = nn.Linear(channels, channels, bias_attr=False)
        self.self_v = nn.Linear(channels, channels, bias_attr=False)
        self.self_o = nn.Linear(channels, channels)
        self.ln2 = nn.LayerNorm(channels)
        self.cross_q = nn.Linear(channels, channels, bias_attr=False)
        self.cross_k = nn.Linear(context_dim, channels, bias_attr=False)
        self.cross_v = nn.Linear(context_dim, channels, bias_attr=False)
        self.cross_o = nn.Linear(channels, channels)
        self.ln3 = nn.LayerNorm(channels)
        self.ff1 = nn.Linear(channels, channels * 8)     # GEGLU: 2*4x
        self.ff2 = nn.Linear(channels * 4, channels)
        self.proj_out = nn.Conv2D(channels, channels, 1)
        self.num_heads = num_heads
        self.channels = channels

    def _attend(self, q, k, v):
        # pass the ORIGINAL Tensors to dispatch (rewrapping raw values
        # would detach the tape and freeze the QKV projections); reshapes
        # happen inside the traced fn
        H = self.num_heads
        b, sq, C = q.shape
        sk = k.shape[1]
        hd = C // H
        from ..core.tensor import dispatch
        return dispatch(
            lambda qq, kk, vy: flash_attention(
                qq.reshape(b, sq, H, hd), kk.reshape(b, sk, H, hd),
                vy.reshape(b, sk, H, hd), causal=False).reshape(b, sq, C),
            (q, k, v), name="attention")

    def forward(self, x, context):
        b, c, h, w = x.shape
        residual = x
        hx = self.proj_in(self.norm_in(x))
        tokens = hx.transpose([0, 2, 3, 1]).reshape([b, h * w, c])
        t = self.ln1(tokens)
        tokens = tokens + self.self_o(
            self._attend(self.self_q(t), self.self_k(t), self.self_v(t)))
        t = self.ln2(tokens)
        tokens = tokens + self.cross_o(
            self._attend(self.cross_q(t), self.cross_k(context),
                         self.cross_v(context)))
        t = self.ln3(tokens)
        ff = self.ff1(t)
        gate, val = ff.chunk(2, axis=-1)
        from ..nn import functional as F
        tokens = tokens + self.ff2(F.gelu(gate) * val)
        hx = tokens.reshape([b, h, w, c]).transpose([0, 3, 1, 2])
        return residual + self.proj_out(hx)


class Downsample(nn.Layer):
    def __init__(self, ch):
        super().__init__()
        self.conv = nn.Conv2D(ch, ch, 3, stride=2, padding=1)

    def forward(self, x):
        return self.conv(x)


class Upsample2x(nn.Layer):
    def __init__(self, ch):
        super().__init__()
        self.up = nn.Upsample(scale_factor=2, mode="nearest")
        self.conv = nn.Conv2D(ch, ch, 3, padding=1)

    def forward(self, x):
        return self.conv(self.up(x))


class UNetModel(nn.Layer):
    """reference: ppdiffusers UNet2DConditionModel (conditioned denoiser
    eps = f(x_t, t, text_context))."""

    def __init__(self, cfg: UNetConfig = UNET_TINY):
        super().__init__()
        self.cfg = cfg
        ch = cfg.model_channels
        time_dim = ch * 4
        self.time_mlp1 = nn.Linear(ch, time_dim)
        self.time_mlp2 = nn.Linear(time_dim, time_dim)
        self.act = nn.Silu()
        self.conv_in = nn.Conv2D(cfg.in_channels, ch, 3, padding=1)

        # down path
        self.down_blocks = nn.LayerList()
        self.down_attns = nn.LayerList()
        self.downsamplers = nn.LayerList()
        chans = [ch]
        cur = ch
        for level, mult in enumerate(cfg.channel_mult):
            out_ch = ch * mult
            blocks = nn.LayerList()
            attns = nn.LayerList()
            for _ in range(cfg.num_res_blocks):
                blocks.append(ResBlock(cur, out_ch, time_dim, cfg.groups))
                attns.append(SpatialTransformer(out_ch, cfg.num_heads,
                                                cfg.context_dim)
                             if level in cfg.attention_levels else None)
                cur = out_ch
                chans.append(cur)
            self.down_blocks.append(blocks)
            self.down_attns.append(attns)
            if level != len(cfg.channel_mult) - 1:
                self.downsamplers.append(Downsample(cur))
                chans.append(cur)
            else:
                self.downsamplers.append(None)

        # middle
        self.mid_res1 = ResBlock(cur, cur, time_dim, cfg.groups)
        self.mid_attn = SpatialTransformer(cur, cfg.num_heads,
                                           cfg.context_dim)
        self.mid_res2 = ResBlock(cur, cur, time_dim, cfg.groups)

        # up path
        self.up_blocks = nn.LayerList()
        self.up_attns = nn.LayerList()
        self.upsamplers = nn.LayerList()
        for level, mult in reversed(list(enumerate(cfg.channel_mult))):
            out_ch = ch * mult
            blocks = nn.LayerList()
            attns = nn.LayerList()
            for _ in range(cfg.num_res_blocks + 1):
                skip_ch = chans.pop()
                blocks.append(ResBlock(cur + skip_ch, out_ch, time_dim,
                                       cfg.groups))
                attns.append(SpatialTransformer(out_ch, cfg.num_heads,
                                                cfg.context_dim)
                             if level in cfg.attention_levels else None)
                cur = out_ch
            self.up_blocks.append(blocks)
            self.up_attns.append(attns)
            self.upsamplers.append(Upsample2x(cur) if level != 0 else None)

        self.norm_out = nn.GroupNorm(min(cfg.groups, cur), cur)
        self.conv_out = nn.Conv2D(cur, cfg.out_channels, 3, padding=1)

    def forward(self, x, timesteps, context):
        cfg = self.cfg
        temb = Tensor(timestep_embedding(to_value(timesteps),
                                         cfg.model_channels))
        temb = self.time_mlp2(self.act(self.time_mlp1(temb)))

        h = self.conv_in(x)
        skips = [h]
        for blocks, attns, down in zip(self.down_blocks, self.down_attns,
                                       self.downsamplers):
            for blk, attn in zip(blocks, attns):
                h = blk(h, temb)
                if attn is not None:
                    h = attn(h, context)
                skips.append(h)
            if down is not None:
                h = down(h)
                skips.append(h)

        h = self.mid_res1(h, temb)
        h = self.mid_attn(h, context)
        h = self.mid_res2(h, temb)

        from ..tensor.manipulation import concat
        for blocks, attns, up in zip(self.up_blocks, self.up_attns,
                                     self.upsamplers):
            for blk, attn in zip(blocks, attns):
                h = concat([h, skips.pop()], axis=1)
                h = blk(h, temb)
                if attn is not None:
                    h = attn(h, context)
            if up is not None:
                h = up(h)

        return self.conv_out(self.act(self.norm_out(h)))


def ddim_step(unet, x_t, t, t_prev, context, alphas_cumprod):
    """One DDIM denoise step x_t → x_{t_prev} (eta=0).
    alphas_cumprod: [T] numpy/jax array of the scheduler's ᾱ."""
    eps = unet(x_t, jnp.full((x_t.shape[0],), t, jnp.int32), context)
    eps_v = to_value(eps)
    x_v = to_value(x_t)
    a_t = alphas_cumprod[t]
    a_prev = alphas_cumprod[t_prev] if t_prev >= 0 else jnp.asarray(1.0)
    x0 = (x_v - jnp.sqrt(1 - a_t) * eps_v) / jnp.sqrt(a_t)
    x_prev = jnp.sqrt(a_prev) * x0 + jnp.sqrt(1 - a_prev) * eps_v
    return Tensor(x_prev.astype(x_v.dtype))   # keep model dtype under x64
