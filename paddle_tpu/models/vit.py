"""Vision Transformer.

Reference capability: python/paddle/vision models family (the reference
ships CNN backbones in paddle.vision and ViT via PaddleClas configs built
on paddle.nn). TPU-first: patchify as a single strided conv
(lax.conv_general_dilated maps straight onto the MXU), scanned encoder
layers, flash attention over patch tokens.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import layer_norm as fused_layer_norm
from ._common import (resolve_mesh_axes, spec_fn, normal_init,
                      prenorm_block)


@dataclasses.dataclass
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    num_classes: int = 1000
    layer_norm_epsilon: float = 1e-6
    dtype: Any = jnp.bfloat16
    remat: bool = True

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @property
    def num_patches(self):
        return (self.image_size // self.patch_size) ** 2


VIT_TINY = ViTConfig(image_size=32, patch_size=8, hidden_size=64,
                     intermediate_size=128, num_hidden_layers=2,
                     num_attention_heads=4, num_classes=10)


def init_params(cfg: ViTConfig, key=None, dtype=None) -> Dict:
    dtype = dtype or cfg.dtype
    key = key if key is not None else jax.random.key(0)
    D, F = cfg.hidden_size, cfg.intermediate_size
    L = cfg.num_hidden_layers
    P_, C = cfg.patch_size, cfg.num_channels
    k = jax.random.split(key, 8)

    def nrm(kk, shape):
        return normal_init(kk, shape, dtype=dtype)

    return {
        "patch_w": nrm(k[0], (D, C, P_, P_)),     # OIHW conv kernel
        "patch_b": jnp.zeros((D,), dtype),
        "cls": nrm(k[1], (1, 1, D)),
        "pos_emb": nrm(k[2], (cfg.num_patches + 1, D)),
        "layers": {
            "ln1_w": jnp.ones((L, D), jnp.float32),
            "ln1_b": jnp.zeros((L, D), jnp.float32),
            "qkv": nrm(k[3], (L, D, 3 * D)),
            "qkv_b": jnp.zeros((L, 3 * D), dtype),
            "proj": nrm(k[4], (L, D, D)),
            "proj_b": jnp.zeros((L, D), dtype),
            "ln2_w": jnp.ones((L, D), jnp.float32),
            "ln2_b": jnp.zeros((L, D), jnp.float32),
            "fc": nrm(k[5], (L, D, F)),
            "fc_b": jnp.zeros((L, F), dtype),
            "fc_out": nrm(k[6], (L, F, D)),
            "fc_out_b": jnp.zeros((L, D), dtype),
        },
        "ln_f_w": jnp.ones((D,), jnp.float32),
        "ln_f_b": jnp.zeros((D,), jnp.float32),
        "head_w": nrm(k[7], (D, cfg.num_classes)),
        "head_b": jnp.zeros((cfg.num_classes,), dtype),
    }


def param_shardings(mesh: Mesh, cfg: ViTConfig) -> Dict:
    fsdp, tp = resolve_mesh_axes(mesh)
    s = spec_fn(mesh)

    return {
        "patch_w": s(tp, None, None, None), "patch_b": s(tp),
        "cls": s(None, None, None), "pos_emb": s(None, fsdp),
        "layers": {
            "ln1_w": s(None, None), "ln1_b": s(None, None),
            "qkv": s(None, fsdp, tp), "qkv_b": s(None, tp),
            "proj": s(None, tp, fsdp), "proj_b": s(None, None),
            "ln2_w": s(None, None), "ln2_b": s(None, None),
            "fc": s(None, fsdp, tp), "fc_b": s(None, tp),
            "fc_out": s(None, tp, fsdp), "fc_out_b": s(None, None),
        },
        "ln_f_w": s(None), "ln_f_b": s(None),
        "head_w": s(fsdp, tp), "head_b": s(tp),
    }


def _block(lp, x, cfg: ViTConfig):
    return prenorm_block(lp, x, num_heads=cfg.num_attention_heads,
                         head_dim=cfg.head_dim,
                         eps=cfg.layer_norm_epsilon, causal=False)


def forward(params: Dict, images, cfg: ViTConfig) -> jax.Array:
    """images [B, C, H, W] → logits [B, num_classes]."""
    x = jax.lax.conv_general_dilated(
        images.astype(params["patch_w"].dtype), params["patch_w"],
        window_strides=(cfg.patch_size, cfg.patch_size), padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    b, D, gh, gw = x.shape
    x = x.reshape(b, D, gh * gw).transpose(0, 2, 1) + params["patch_b"]
    cls = jnp.broadcast_to(params["cls"].astype(x.dtype), (b, 1, D))
    x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_emb"][None]

    body = partial(_block, cfg=cfg)
    if cfg.remat:
        body = jax.checkpoint(body)

    def scan_fn(carry, lp):
        return body(lp, carry), None

    x, _ = jax.lax.scan(scan_fn, x, params["layers"])
    x = fused_layer_norm(x, params["ln_f_w"].astype(x.dtype),
                         params["ln_f_b"].astype(x.dtype),
                         cfg.layer_norm_epsilon)
    return x[:, 0] @ params["head_w"] + params["head_b"]


def loss_fn(params: Dict, images, labels, cfg: ViTConfig) -> jax.Array:
    logits = forward(params, images, cfg).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    return -jnp.mean(picked)
