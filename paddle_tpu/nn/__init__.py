"""paddle_tpu.nn (reference: python/paddle/nn/__init__.py)."""
from . import initializer  # noqa: F401
from . import functional  # noqa: F401
from . import quant  # noqa: F401

from .layer.layers import (Layer, Sequential, LayerList, ParameterList,  # noqa
                           ParameterDict, LayerDict)
from .layer.common import *  # noqa: F401,F403
from .layer.activation import *  # noqa: F401,F403
from .layer.conv import *  # noqa: F401,F403
from .layer.norm import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.loss import *  # noqa: F401,F403
from .layer.transformer import *  # noqa: F401,F403
from .layer.rnn import *  # noqa: F401,F403
from .clip import (ClipGradByValue, ClipGradByNorm,  # noqa: F401
                   ClipGradByGlobalNorm, clip_grad_norm_, clip_grad_value_)

from . import utils  # noqa: F401
