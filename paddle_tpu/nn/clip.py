"""Gradient clipping (reference: python/paddle/nn/clip.py).

Same three strategies as the reference; operate on (param, grad) lists.
The hybrid-parallel variant that allreduces partial norms across mesh axes
lives in distributed/fleet/hybrid_parallel_optimizer.py.
"""
from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp

from ..core.tensor import Tensor, no_grad

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
           "clip_grad_norm_", "clip_grad_value_"]


class ClipGradBase:
    def __call__(self, params_grads):
        return self._clip(params_grads)


class ClipGradByValue(ClipGradBase):
    """reference: nn/clip.py ClipGradByValue."""

    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    @no_grad()
    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            need = getattr(getattr(p, "_param_attr", None), "need_clip", True)
            if not need:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._value, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    """Per-tensor L2 norm clip (reference: nn/clip.py ClipGradByNorm)."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    @no_grad()
    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            need = getattr(getattr(p, "_param_attr", None), "need_clip", True)
            if not need:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(
                g._value.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12),
                                1.0)
            out.append((p, Tensor((g._value * scale).astype(g._value.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Global-norm clip (reference: nn/clip.py ClipGradByGlobalNorm). In
    hybrid-parallel runs the squared partial norms are allreduced across
    model-parallel groups before the scale is applied."""

    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    @no_grad()
    def _clip(self, params_grads):
        sq = []
        for p, g in params_grads:
            if g is None:
                continue
            need = getattr(getattr(p, "_param_attr", None), "need_clip", True)
            if not need:
                continue
            sq.append(jnp.sum(jnp.square(g._value.astype(jnp.float32))))
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = jnp.minimum(self.clip_norm /
                            jnp.maximum(global_norm, 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            need = getattr(getattr(p, "_param_attr", None), "need_clip", True)
            if not need:
                out.append((p, g))
                continue
            out.append((p, Tensor((g._value * scale).astype(g._value.dtype))))
        return out


@no_grad()
def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._value))
                                   for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._value.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError("non-finite total norm")
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._replace_value(
                (p.grad._value * scale).astype(p.grad._value.dtype))
    return Tensor(total)


@no_grad()
def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._replace_value(jnp.clip(p.grad._value, -clip_value,
                                           clip_value))
