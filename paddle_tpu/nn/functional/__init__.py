"""paddle_tpu.nn.functional
(reference: python/paddle/nn/functional/__init__.py)."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .attention import *  # noqa: F401,F403
from .vision import *  # noqa: F401,F403

from .activation import (relu, gelu, silu, swish, softmax, log_softmax,  # noqa
                         sigmoid, tanh, swiglu)
from .common import linear, dropout, embedding, interpolate  # noqa: F401
from .conv import conv1d, conv2d, conv3d  # noqa: F401
from .attention import scaled_dot_product_attention  # noqa: F401
