"""Activation functionals (reference: python/paddle/nn/functional/activation.py).

All map to jnp/jax.nn primitives; XLA fuses them into neighbouring matmuls
(the reference needs hand-fused CUDA epilogues for this,
paddle/phi/kernels/fusion/).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch


def _ensure(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _u(name, fn):
    def op(x, name=None):
        return dispatch(fn, (_ensure(x),), name=op.__name__)
    op.__name__ = name
    return op


relu = _u("relu", jax.nn.relu)
relu6 = _u("relu6", jax.nn.relu6)
sigmoid = _u("sigmoid", jax.nn.sigmoid)
tanh = _u("tanh", jnp.tanh)
silu = _u("silu", jax.nn.silu)
swish = silu
mish = _u("mish", lambda v: v * jnp.tanh(jax.nn.softplus(v)))
tanhshrink = _u("tanhshrink", lambda v: v - jnp.tanh(v))
softsign = _u("softsign", jax.nn.soft_sign)


def relu_(x, name=None):
    x._replace_value(jax.nn.relu(x._value))
    return x


def gelu(x, approximate=False, name=None):
    return dispatch(lambda v: jax.nn.gelu(v, approximate=approximate),
                    (_ensure(x),), name="gelu")


def leaky_relu(x, negative_slope=0.01, name=None):
    return dispatch(lambda v: jax.nn.leaky_relu(v, negative_slope),
                    (_ensure(x),), name="leaky_relu")


def elu(x, alpha=1.0, name=None):
    return dispatch(lambda v: jax.nn.elu(v, alpha), (_ensure(x),), name="elu")


def elu_(x, alpha=1.0, name=None):
    x._replace_value(jax.nn.elu(x._value, alpha))
    return x


def celu(x, alpha=1.0, name=None):
    return dispatch(lambda v: jax.nn.celu(v, alpha), (_ensure(x),),
                    name="celu")


def selu(x,
         scale=1.0507009873554804934193349852946,
         alpha=1.6732632423543772848170429916717, name=None):
    return dispatch(lambda v: scale * jnp.where(
        v > 0, v, alpha * jnp.expm1(v)), (_ensure(x),), name="selu")


def prelu(x, weight, data_format="NCHW", name=None):
    def f(v, w):
        if w.size == 1:
            return jnp.where(v > 0, v, w.reshape(()) * v)
        ch_axis = 1 if data_format == "NCHW" else v.ndim - 1
        shape = [1] * v.ndim
        shape[ch_axis] = w.size
        return jnp.where(v > 0, v, w.reshape(shape) * v)
    return dispatch(f, (_ensure(x), _ensure(weight)), name="prelu")


def rrelu(x, lower=1. / 8., upper=1. / 3., training=False, name=None):
    if training:
        from ...core.random import next_key
        def f(v):
            a = jax.random.uniform(next_key(), v.shape, dtype=jnp.float32,
                                   minval=lower, maxval=upper).astype(v.dtype)
            return jnp.where(v >= 0, v, a * v)
        return dispatch(f, (_ensure(x),), name="rrelu")
    mid = (lower + upper) / 2.0
    return dispatch(lambda v: jnp.where(v >= 0, v, mid * v), (_ensure(x),),
                    name="rrelu")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return dispatch(lambda v: jnp.clip(v, min, max), (_ensure(x),),
                    name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return dispatch(lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0),
                    (_ensure(x),), name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    return dispatch(lambda v: jnp.where(
        v > threshold, v - threshold,
        jnp.where(v < -threshold, v + threshold, 0.0)),
        (_ensure(x),), name="softshrink")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return dispatch(lambda v: jnp.clip(slope * v + offset, 0.0, 1.0),
                    (_ensure(x),), name="hardsigmoid")


def hardswish(x, name=None):
    return dispatch(lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0,
                    (_ensure(x),), name="hardswish")


def softplus(x, beta=1, threshold=20, name=None):
    return dispatch(lambda v: jnp.where(
        beta * v > threshold, v, jax.nn.softplus(beta * v) / beta),
        (_ensure(x),), name="softplus")


def logsigmoid(x, name=None):
    return dispatch(jax.nn.log_sigmoid, (_ensure(x),), name="log_sigmoid")


log_sigmoid = logsigmoid


def maxout(x, groups, axis=1, name=None):
    def f(v):
        ax = axis % v.ndim
        c = v.shape[ax]
        new_shape = v.shape[:ax] + (c // groups, groups) + v.shape[ax + 1:]
        return jnp.max(v.reshape(new_shape), axis=ax + 1)
    return dispatch(f, (_ensure(x),), name="maxout")


def softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtypes import convert_dtype
    d = convert_dtype(dtype) if dtype else None

    def f(v):
        if d is not None:
            v = v.astype(d)
        return jax.nn.softmax(v, axis=axis)
    return dispatch(f, (_ensure(x),), name="softmax")


softmax_ = softmax


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtypes import convert_dtype
    d = convert_dtype(dtype) if dtype else None

    def f(v):
        if d is not None:
            v = v.astype(d)
        return jax.nn.log_softmax(v, axis=axis)
    return dispatch(f, (_ensure(x),), name="log_softmax")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core.random import next_key

    def f(v):
        g = jax.random.gumbel(next_key(), v.shape, dtype=v.dtype)
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis,
                                        inplace=False)
            y = jax.lax.stop_gradient(y_hard - y) + y
        return y
    return dispatch(f, (_ensure(x),), name="gumbel_softmax")


def glu(x, axis=-1, name=None):
    return dispatch(lambda v: jax.nn.glu(v, axis=axis), (_ensure(x),),
                    name="glu")


def swiglu(x, y=None, name=None):
    """SwiGLU used by LLaMA MLPs (reference fused op:
    python/paddle/incubate/nn/functional/swiglu.py). Routed to the Pallas
    fused kernel via incubate when FLAGS_use_fused_kernels."""
    if y is None:
        def f(v):
            a, b = jnp.split(v, 2, axis=-1)
            return jax.nn.silu(a) * b
        return dispatch(f, (_ensure(x),), name="swiglu")
    return dispatch(lambda a, b: jax.nn.silu(a) * b,
                    (_ensure(x), _ensure(y)), name="swiglu")


def tanh_(x, name=None):
    x._replace_value(jnp.tanh(x._value))
    return x


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    """reference: ops.yaml thresholded_relu — x where x > threshold,
    else ``value``."""
    return dispatch(lambda v: jnp.where(v > threshold, v, value),
                    (_ensure(x),), name="thresholded_relu")


def hardtanh_(x, min=-1.0, max=1.0, name=None):
    """reference: nn/functional/activation.py hardtanh_ (inplace)."""
    x._replace_value(jnp.clip(x._value, min, max))
    return x


def leaky_relu_(x, negative_slope=0.01, name=None):
    """reference: nn/functional/activation.py leaky_relu_ (inplace)."""
    x._replace_value(jax.nn.leaky_relu(x._value, negative_slope))
    return x


def thresholded_relu_(x, threshold=1.0, value=0.0, name=None):
    """reference: nn/functional/activation.py thresholded_relu_."""
    x._replace_value(jnp.where(x._value > threshold, x._value, value))
    return x
