"""Attention functionals.

``scaled_dot_product_attention`` (reference:
python/paddle/nn/functional/flash_attention.py) routes to the Pallas
flash-attention kernel on TPU (ops/flash_attention.py) and to an XLA
composition elsewhere; numerics are gated in tests.
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch
from ...core.flags import GLOBAL_FLAGS

def _ensure(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _sdpa_ref(q, k, v, mask, dropout_p, is_causal, training, scale=None):
    # q,k,v: [batch, seq, heads, head_dim] (paddle flash-attn layout)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * s
    if is_causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        causal = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(causal, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(jnp.float32)
    p = jax.nn.softmax(logits, axis=-1)
    if dropout_p > 0.0 and training:
        from ...core.random import next_key
        keep = jax.random.bernoulli(next_key(), 1.0 - dropout_p, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Layout [batch, seqlen, num_heads, head_dim] (reference:
    python/paddle/nn/functional/flash_attention.py:scaled_dot_product_attention)."""
    args = [_ensure(query), _ensure(key), _ensure(value)]
    if attn_mask is not None:
        args.append(_ensure(attn_mask))

    use_fused = GLOBAL_FLAGS.get("use_fused_kernels")
    rate = dropout_p if (dropout_p and training) else 0.0

    def f(q, k, v, *m):
        mask = m[0] if m else None
        if use_fused and mask is None:
            # dropout rides in-kernel (position-keyed hash mask)
            from ...ops import flash_attention as fa
            return fa.flash_attention(q, k, v, causal=is_causal,
                                      dropout_rate=rate)
        return _sdpa_ref(q, k, v, mask, dropout_p, is_causal, training)
    return dispatch(f, tuple(args), name="scaled_dot_product_attention")


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """reference: python/paddle/incubate/nn/functional (flash_attention)."""
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training)
    if return_softmax:
        return out, None
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q=None, max_seqlen_k=None, scale=None,
                        dropout=0.0, causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen (packed) flash attention.

    reference: python/paddle/nn/functional/flash_attention.py
    flash_attn_unpadded → paddle/phi/kernels/gpu/flash_attn_kernel.cu:137
    (cu_seqlens varlen kernel). TPU-native: the packed [total, heads, dim]
    tensors are treated as one batch-1 sequence and per-token segment ids
    derived from ``cu_seqlens`` confine attention (and causality) to each
    original sequence inside the Pallas kernel — no unpack/pad round trip.

    query/key/value: [total_q|total_k, num_heads, head_dim];
    cu_seqlens_q/k: [batch+1] int32 cumulative sequence lengths.

    ``causal=True`` requires cu_seqlens_q == cu_seqlens_k: the kernel
    masks on packed positions, which is only the per-sequence causal mask
    when queries and keys share the packing (bottom-right-aligned causal
    for cross-length q/k is not implemented).
    """
    from ...ops.flash_attention import (flash_attention as _fa,
                                        segment_ids_from_cu_seqlens)
    use_dropout = bool(dropout) and dropout > 0.0 and training
    if causal:
        import numpy as _np
        cq_v, ck_v = cu_seqlens_q, cu_seqlens_k
        cq_a = getattr(cq_v, "_value", cq_v)
        ck_a = getattr(ck_v, "_value", ck_v)
        try:
            same = (_np.asarray(cq_a).shape == _np.asarray(ck_a).shape and
                    bool((_np.asarray(cq_a) == _np.asarray(ck_a)).all()))
        except Exception:
            same = True  # traced values: trust the caller
        if not same:
            raise NotImplementedError(
                "flash_attn_unpadded(causal=True) requires "
                "cu_seqlens_q == cu_seqlens_k (self-attention packing)")

    def f(q, k, v, cq, ck):
        tq, tk = q.shape[0], k.shape[0]
        seg_q = segment_ids_from_cu_seqlens(cq, tq)[None]
        seg_k = segment_ids_from_cu_seqlens(ck, tk)[None]
        # dropout rides INSIDE the fused kernel (position-keyed hash
        # mask regenerated by the backward kernels, reference
        # flash_attn_kernel.cu Philox path); 0 disables it statically
        rate = dropout if use_dropout else 0.0
        out = _fa(q[None], k[None], v[None], causal=causal, scale=scale,
                  segment_ids=seg_q, kv_segment_ids=seg_k,
                  dropout_rate=rate)
        return out[0]

    args = tuple(_ensure(a) for a in
                 (query, key, value, cu_seqlens_q, cu_seqlens_k))
    out = dispatch(f, args, name="flash_attn_unpadded")
    return out, None  # softmax is never returned (fused kernel)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    from ...core.dtypes import convert_dtype

    def f(v):
        m = maxlen if maxlen is not None else int(v.max())
        ar = jnp.arange(m)
        return (ar[None, :] < v[..., None]).astype(convert_dtype(dtype))
    return dispatch(f, (_ensure(x),), name="sequence_mask")


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False,
                         return_softmax=False, fixed_seed_offset=None,
                         rng_name="", training=True, name=None):
    """reference: nn/functional/flash_attention.py flash_attn_qkvpacked —
    qkv packed [B, S, H/Hk + 2, Hk, D]: leading slices are the query
    heads (GQA groups), the last two are K and V."""
    qkv = _ensure(qkv)

    def f(p):
        b, s, n, hk, d = p.shape
        g = n - 2
        # ops.flash_attention pairs q head j with kv head j // (H//Hk)
        # (consecutive grouping), so kv-aligned q heads must land
        # consecutively: [B,S,G,Hk,D] -> [B,S,Hk,G,D] -> [B,S,Hk*G,D]
        q = jnp.swapaxes(p[:, :, :-2], 2, 3).reshape(b, s, g * hk, d)
        k = p[:, :, -2]
        v = p[:, :, -1]
        from ...ops.flash_attention import flash_attention as _fa
        rate = dropout if (dropout and training) else 0.0
        return _fa(q, k, v, causal=causal, dropout_rate=rate)

    out = dispatch(f, (qkv,), name="flash_attn_qkvpacked")
    return out, None


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q=None, max_seqlen_k=None,
                                scale=None, dropout=0.0, causal=False,
                                return_softmax=False,
                                fixed_seed_offset=None, rng_name="",
                                training=True, varlen_padded=True,
                                name=None):
    """reference: flash_attention.py flash_attn_varlen_qkvpacked — the
    packed-varlen form: qkv [total, H/Hk + 2, Hk, D] + cu_seqlens."""
    qkv = _ensure(qkv)

    def split(p):
        t_, n_, hk_, d_ = p.shape
        # same consecutive-grouping GQA head order as flash_attn_qkvpacked
        q = jnp.swapaxes(p[:, :-2], 1, 2).reshape(t_, (n_ - 2) * hk_, d_)
        return q, p[:, -2], p[:, -1]

    q, k, v = dispatch(split, (qkv,), name="qkv_unpack",
                       multi_output=True)
    return flash_attn_unpadded(
        q, k, v, cu_seqlens_q, cu_seqlens_k,
        max_seqlen_q=max_seqlen_q, max_seqlen_k=max_seqlen_k, scale=scale,
        dropout=dropout, causal=causal, return_softmax=return_softmax,
        training=training)


def flashmask_attention(query, key, value, startend_row_indices=None,
                        dropout=0.0, causal=False, window_size=None,
                        return_softmax_lse=False, return_seed_offset=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """reference: flash_attention.py flashmask_attention (FlashMask,
    arXiv:2410.01359): column-wise row ranges define the mask.

    startend_row_indices [B, Hk, Sk, L]:
    - L=1 + causal: rows >= LTS[c] are masked for column c;
    - L=2 + causal: rows in [LTS[c], LTE[c]) are masked;
    - L=2 + non-causal: rows >= LTS (lower) and rows < UTE (upper);
    - L=4 + non-causal: rows in [LTS, LTE) and [UTS, UTE) masked.

    TPU-native: the ranges expand to a dense additive mask feeding the
    fused attention (XLA fuses the comparison-generated mask into the
    softmax; the dedicated Pallas block-skip path is the kernels pack's
    autotune territory).
    """
    q, k, v = _ensure(query), _ensure(key), _ensure(value)
    if startend_row_indices is None:
        return flash_attention(q, k, v, dropout=dropout, causal=causal,
                               training=training)[0]
    idx = _ensure(startend_row_indices)

    def f(qv, kv, vv, iv):
        b, sq, h, d = qv.shape
        sk = kv.shape[1]
        hk = iv.shape[1]
        L = iv.shape[-1]
        rows = jnp.arange(sq)[:, None]            # [Sq, 1]
        iv = jnp.swapaxes(iv, 2, 3)               # [B, Hk, L, Sk]
        if causal:
            if L == 1:
                masked = rows >= iv[:, :, 0][:, :, None, :]
            elif L == 2:
                masked = (rows >= iv[:, :, 0][:, :, None, :]) & \
                         (rows < iv[:, :, 1][:, :, None, :])
            else:
                raise NotImplementedError(
                    "causal flashmask expects 1 or 2 indices")
            base = rows < jnp.arange(sk)[None, :]  # future positions
            masked = masked | base[None, None]
        else:
            if L == 2:
                masked = (rows >= iv[:, :, 0][:, :, None, :]) | \
                         (rows < iv[:, :, 1][:, :, None, :])
            elif L == 4:
                masked = ((rows >= iv[:, :, 0][:, :, None, :]) &
                          (rows < iv[:, :, 1][:, :, None, :])) | \
                         ((rows >= iv[:, :, 2][:, :, None, :]) &
                          (rows < iv[:, :, 3][:, :, None, :]))
            else:
                raise NotImplementedError(
                    "non-causal flashmask expects 2 or 4 indices")
        # broadcast Hk mask groups over the query heads
        rep = h // hk
        masked = jnp.repeat(masked, rep, axis=1)   # [B, H, Sq, Sk]
        # finite mask value: a fully-masked query row must not softmax
        # over all -inf (NaN); -1e30 keeps the row defined
        bias = jnp.where(masked, jnp.asarray(-1e30, jnp.float32), 0.0)
        return _sdpa_ref(qv, kv, vv, bias, dropout if training else 0.0,
                         False, training)

    out = dispatch(f, (q, k, v, idx), name="flashmask_attention")
    if return_softmax_lse or return_seed_offset:
        extras = tuple(None for _ in range(
            int(return_softmax_lse) + int(return_seed_offset)))
        return (out,) + extras
    return out


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """reference: nn/functional/sparse_attention.py — block-sparse
    attention with a CSR connectivity pattern per head. q/k/v
    [B, H, S, D]; offset [B, H, S+1]; columns [B, H, nnz]. Positions not
    listed in a row's CSR columns do not attend. Dense-mask lowering
    (the CSR pattern becomes a boolean mask XLA fuses into softmax)."""
    q, k, v = _ensure(query), _ensure(key), _ensure(value)
    off, cols = _ensure(sparse_csr_offset), _ensure(sparse_csr_columns)
    args = [q, k, v, off, cols]
    if key_padding_mask is not None:
        args.append(_ensure(key_padding_mask))

    def f(qv, kv, vv, ov, cv, *kpm):
        b, h, s, d = qv.shape
        nnz = cv.shape[-1]
        # row id of each nnz entry: number of row starts at or before it
        row_of = (jnp.arange(nnz)[None, None, :]
                  >= ov[..., 1:-1, None]).sum(-2)
        mask = jnp.zeros((b, h, s, s), bool)
        bidx = jnp.arange(b)[:, None, None]
        hidx = jnp.arange(h)[None, :, None]
        valid = jnp.arange(nnz)[None, None, :] < ov[..., -1:]
        mask = mask.at[bidx, hidx, row_of, cv.astype(jnp.int32)].max(
            valid)
        scores = jnp.einsum("bhsd,bhtd->bhst", qv.astype(jnp.float32),
                            kv.astype(jnp.float32)) / np.sqrt(d)
        if kpm:
            keep = kpm[0][:, None, None, :] > 0
            mask = mask & keep
        scores = jnp.where(mask, scores, -jnp.inf)
        p = jax.nn.softmax(scores, axis=-1)
        p = jnp.where(jnp.isfinite(
            jnp.max(scores, -1, keepdims=True)), p, 0.0)
        return jnp.einsum("bhst,bhtd->bhsd", p,
                          vv.astype(jnp.float32)).astype(qv.dtype)

    return dispatch(f, tuple(args), name="sparse_attention")
