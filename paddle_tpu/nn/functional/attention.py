"""Attention functionals.

``scaled_dot_product_attention`` (reference:
python/paddle/nn/functional/flash_attention.py) routes to the Pallas
flash-attention kernel on TPU (ops/flash_attention.py) and to an XLA
composition elsewhere; numerics are gated in tests.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch
from ...core.flags import GLOBAL_FLAGS

def _ensure(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _sdpa_ref(q, k, v, mask, dropout_p, is_causal, training, scale=None):
    # q,k,v: [batch, seq, heads, head_dim] (paddle flash-attn layout)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * s
    if is_causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        causal = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(causal, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(jnp.float32)
    p = jax.nn.softmax(logits, axis=-1)
    if dropout_p > 0.0 and training:
        from ...core.random import next_key
        keep = jax.random.bernoulli(next_key(), 1.0 - dropout_p, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Layout [batch, seqlen, num_heads, head_dim] (reference:
    python/paddle/nn/functional/flash_attention.py:scaled_dot_product_attention)."""
    args = [_ensure(query), _ensure(key), _ensure(value)]
    if attn_mask is not None:
        args.append(_ensure(attn_mask))

    use_fused = GLOBAL_FLAGS.get("use_fused_kernels")
    rate = dropout_p if (dropout_p and training) else 0.0

    def f(q, k, v, *m):
        mask = m[0] if m else None
        if use_fused and mask is None:
            # dropout rides in-kernel (position-keyed hash mask)
            from ...ops import flash_attention as fa
            return fa.flash_attention(q, k, v, causal=is_causal,
                                      dropout_rate=rate)
        return _sdpa_ref(q, k, v, mask, dropout_p, is_causal, training)
    return dispatch(f, tuple(args), name="scaled_dot_product_attention")


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """reference: python/paddle/incubate/nn/functional (flash_attention)."""
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training)
    if return_softmax:
        return out, None
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q=None, max_seqlen_k=None, scale=None,
                        dropout=0.0, causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen (packed) flash attention.

    reference: python/paddle/nn/functional/flash_attention.py
    flash_attn_unpadded → paddle/phi/kernels/gpu/flash_attn_kernel.cu:137
    (cu_seqlens varlen kernel). TPU-native: the packed [total, heads, dim]
    tensors are treated as one batch-1 sequence and per-token segment ids
    derived from ``cu_seqlens`` confine attention (and causality) to each
    original sequence inside the Pallas kernel — no unpack/pad round trip.

    query/key/value: [total_q|total_k, num_heads, head_dim];
    cu_seqlens_q/k: [batch+1] int32 cumulative sequence lengths.

    ``causal=True`` requires cu_seqlens_q == cu_seqlens_k: the kernel
    masks on packed positions, which is only the per-sequence causal mask
    when queries and keys share the packing (bottom-right-aligned causal
    for cross-length q/k is not implemented).
    """
    from ...ops.flash_attention import (flash_attention as _fa,
                                        segment_ids_from_cu_seqlens)
    use_dropout = bool(dropout) and dropout > 0.0 and training
    if causal:
        import numpy as _np
        cq_v, ck_v = cu_seqlens_q, cu_seqlens_k
        cq_a = getattr(cq_v, "_value", cq_v)
        ck_a = getattr(ck_v, "_value", ck_v)
        try:
            same = (_np.asarray(cq_a).shape == _np.asarray(ck_a).shape and
                    bool((_np.asarray(cq_a) == _np.asarray(ck_a)).all()))
        except Exception:
            same = True  # traced values: trust the caller
        if not same:
            raise NotImplementedError(
                "flash_attn_unpadded(causal=True) requires "
                "cu_seqlens_q == cu_seqlens_k (self-attention packing)")

    def f(q, k, v, cq, ck):
        tq, tk = q.shape[0], k.shape[0]
        seg_q = segment_ids_from_cu_seqlens(cq, tq)[None]
        seg_k = segment_ids_from_cu_seqlens(ck, tk)[None]
        # dropout rides INSIDE the fused kernel (position-keyed hash
        # mask regenerated by the backward kernels, reference
        # flash_attn_kernel.cu Philox path); 0 disables it statically
        rate = dropout if use_dropout else 0.0
        out = _fa(q[None], k[None], v[None], causal=causal, scale=scale,
                  segment_ids=seg_q, kv_segment_ids=seg_k,
                  dropout_rate=rate)
        return out[0]

    args = tuple(_ensure(a) for a in
                 (query, key, value, cu_seqlens_q, cu_seqlens_k))
    out = dispatch(f, args, name="flash_attn_unpadded")
    return out, None  # softmax is never returned (fused kernel)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    from ...core.dtypes import convert_dtype

    def f(v):
        m = maxlen if maxlen is not None else int(v.max())
        ar = jnp.arange(m)
        return (ar[None, :] < v[..., None]).astype(convert_dtype(dtype))
    return dispatch(f, (_ensure(x),), name="sequence_mask")
