"""Attention functionals.

``scaled_dot_product_attention`` (reference:
python/paddle/nn/functional/flash_attention.py) routes to the Pallas
flash-attention kernel on TPU (ops/flash_attention.py) and to an XLA
composition elsewhere; numerics are gated in tests.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch
from ...core.flags import GLOBAL_FLAGS

# flash_attn_unpadded dropout fallback: query-block size for the chunked
# score materialization, and the once-per-process warning latch.
_DROPOUT_CHUNK = 512
_DROPOUT_FALLBACK_WARNED = False


def _ensure(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _sdpa_ref(q, k, v, mask, dropout_p, is_causal, training, scale=None):
    # q,k,v: [batch, seq, heads, head_dim] (paddle flash-attn layout)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * s
    if is_causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        causal = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(causal, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(jnp.float32)
    p = jax.nn.softmax(logits, axis=-1)
    if dropout_p > 0.0 and training:
        from ...core.random import next_key
        keep = jax.random.bernoulli(next_key(), 1.0 - dropout_p, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Layout [batch, seqlen, num_heads, head_dim] (reference:
    python/paddle/nn/functional/flash_attention.py:scaled_dot_product_attention)."""
    args = [_ensure(query), _ensure(key), _ensure(value)]
    if attn_mask is not None:
        args.append(_ensure(attn_mask))

    use_fused = (GLOBAL_FLAGS.get("use_fused_kernels") and dropout_p == 0.0)

    def f(q, k, v, *m):
        mask = m[0] if m else None
        if use_fused and mask is None:
            from ...ops import flash_attention as fa
            return fa.flash_attention(q, k, v, causal=is_causal)
        return _sdpa_ref(q, k, v, mask, dropout_p, is_causal, training)
    return dispatch(f, tuple(args), name="scaled_dot_product_attention")


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """reference: python/paddle/incubate/nn/functional (flash_attention)."""
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training)
    if return_softmax:
        return out, None
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q=None, max_seqlen_k=None, scale=None,
                        dropout=0.0, causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen (packed) flash attention.

    reference: python/paddle/nn/functional/flash_attention.py
    flash_attn_unpadded → paddle/phi/kernels/gpu/flash_attn_kernel.cu:137
    (cu_seqlens varlen kernel). TPU-native: the packed [total, heads, dim]
    tensors are treated as one batch-1 sequence and per-token segment ids
    derived from ``cu_seqlens`` confine attention (and causality) to each
    original sequence inside the Pallas kernel — no unpack/pad round trip.

    query/key/value: [total_q|total_k, num_heads, head_dim];
    cu_seqlens_q/k: [batch+1] int32 cumulative sequence lengths.

    ``causal=True`` requires cu_seqlens_q == cu_seqlens_k: the kernel
    masks on packed positions, which is only the per-sequence causal mask
    when queries and keys share the packing (bottom-right-aligned causal
    for cross-length q/k is not implemented).
    """
    from ...ops.flash_attention import (flash_attention as _fa,
                                        segment_ids_from_cu_seqlens)
    use_dropout = bool(dropout) and dropout > 0.0 and training
    if causal:
        import numpy as _np
        cq_v, ck_v = cu_seqlens_q, cu_seqlens_k
        cq_a = getattr(cq_v, "_value", cq_v)
        ck_a = getattr(ck_v, "_value", ck_v)
        try:
            same = (_np.asarray(cq_a).shape == _np.asarray(ck_a).shape and
                    bool((_np.asarray(cq_a) == _np.asarray(ck_a)).all()))
        except Exception:
            same = True  # traced values: trust the caller
        if not same:
            raise NotImplementedError(
                "flash_attn_unpadded(causal=True) requires "
                "cu_seqlens_q == cu_seqlens_k (self-attention packing)")

    if use_dropout:
        global _DROPOUT_FALLBACK_WARNED
        if not _DROPOUT_FALLBACK_WARNED:
            _DROPOUT_FALLBACK_WARNED = True
            import warnings
            warnings.warn(
                "flash_attn_unpadded with dropout falls back to a chunked "
                "XLA composition (the fused kernel has no in-kernel RNG): "
                "scores are materialized per query block of "
                f"{_DROPOUT_CHUNK} rows instead of fully fused. Expect "
                "lower throughput than dropout=0. This warning fires once "
                "per process.", stacklevel=2)

    def f(q, k, v, cq, ck):
        tq, tk = q.shape[0], k.shape[0]
        seg_q = segment_ids_from_cu_seqlens(cq, tq)[None]
        seg_k = segment_ids_from_cu_seqlens(ck, tk)[None]
        if not use_dropout:
            out = _fa(q[None], k[None], v[None], causal=causal, scale=scale,
                      segment_ids=seg_q, kv_segment_ids=seg_k)
            return out[0]
        # dropout path: the fused kernel has no in-kernel RNG, so fall
        # back to the XLA composition with the same segment/causal mask
        # (reference keeps dropout inside flash_attn_kernel.cu via a
        # Philox offset). Chunked over query blocks so peak memory is
        # O(heads * chunk * tk) fp32, not the full [tq, tk] score matrix.
        from ...core.random import next_key
        s = scale if scale is not None else q.shape[-1] ** -0.5
        h, d = q.shape[1], q.shape[2]
        kf = jnp.swapaxes(k, 0, 1).astype(jnp.float32)        # [h, tk, d]
        vf = jnp.swapaxes(v, 0, 1).astype(jnp.float32)
        bq = min(_DROPOUT_CHUNK, tq)
        pad = (-tq) % bq
        nq = (tq + pad) // bq
        # Padded rows carry segment id -1 (matches nothing, seg ids >= 0):
        # their logits are all -1e30 -> softmax is uniform (finite, no
        # NaN) and the rows are sliced off below.
        qp = jnp.pad(jnp.swapaxes(q, 0, 1).astype(jnp.float32) * s,
                     ((0, 0), (0, pad), (0, 0)))              # [h, tqp, d]
        segq = jnp.pad(seg_q[0], (0, pad), constant_values=-1)
        qc = qp.reshape(h, nq, bq, d).transpose(1, 0, 2, 3)   # [nq,h,bq,d]
        segc = segq.reshape(nq, bq)
        posc = jnp.arange(nq * bq).reshape(nq, bq)
        keys = jax.random.split(next_key(), nq)
        kpos = jnp.arange(tk)

        def one_chunk(_, xs):
            qi, sgi, pi, ki = xs
            lg = jnp.einsum("hqd,hkd->hqk", qi, kf)
            m = sgi[:, None] == seg_k[0][None, :]
            if causal:
                m &= pi[:, None] >= kpos[None, :]
            lg = jnp.where(m[None], lg, -1e30)
            p = jax.nn.softmax(lg, axis=-1)
            keep = jax.random.bernoulli(ki, 1.0 - dropout, p.shape)
            p = jnp.where(keep, p / (1.0 - dropout), 0.0)
            return None, jnp.einsum("hqk,hkd->hqd", p, vf)

        _, outc = jax.lax.scan(one_chunk, None, (qc, segc, posc, keys))
        out = outc.transpose(0, 2, 1, 3).reshape(nq * bq, h, d)[:tq]
        return out.astype(q.dtype)

    args = tuple(_ensure(a) for a in
                 (query, key, value, cu_seqlens_q, cu_seqlens_k))
    out = dispatch(f, args, name="flash_attn_unpadded")
    return out, None  # softmax is never returned (fused kernel)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    from ...core.dtypes import convert_dtype

    def f(v):
        m = maxlen if maxlen is not None else int(v.max())
        ar = jnp.arange(m)
        return (ar[None, :] < v[..., None]).astype(convert_dtype(dtype))
    return dispatch(f, (_ensure(x),), name="sequence_mask")
