"""Common functionals: linear, dropout, embedding, interpolate, pad…
(reference: python/paddle/nn/functional/common.py)."""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch, to_value
from ...core.random import next_key


def _ensure(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with W shaped [in, out] (reference convention,
    python/paddle/nn/functional/common.py linear). MXU hot path.

    Under an active zero-bubble WeightGradStore, routes through zb_linear
    (backward computes only dX; dW is deferred into the pipeline bubble —
    reference pipeline_zero_bubble.py dW/dX split)."""
    import sys
    zb = sys.modules.get("paddle_tpu.distributed.fleet.zero_bubble")
    if zb is not None and zb.weight_grad_store_enabled():
        return zb.zb_linear(x, weight, bias)
    if bias is None:
        return dispatch(lambda v, w: jnp.matmul(v, w),
                        (_ensure(x), _ensure(weight)), name="linear")
    return dispatch(lambda v, w, b: jnp.matmul(v, w) + b,
                    (_ensure(x), _ensure(weight), _ensure(bias)),
                    name="linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return dispatch(lambda v: v * (1.0 - p), (_ensure(x),),
                            name="dropout_infer")
        return _ensure(x)

    def f(v):
        # key drawn INSIDE the dispatched fn: static.Program replay and
        # to_static re-trace then re-draw per run instead of baking the
        # record-time mask as a constant
        key = next_key()
        shape = list(v.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), 0.0).astype(v.dtype)
        return jnp.where(keep, v, 0.0).astype(v.dtype)
    return dispatch(f, (_ensure(x),), name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return _ensure(x)

    def f(v):
        key = next_key()
        alpha = 1.6732632423543772848170429916717
        scale = 1.0507009873554804934193349852946
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        a = (1.0 / np.sqrt((alpha_p ** 2 * p + 1) * (1 - p))) if p < 1 else 0.
        b = -a * alpha_p * p
        return (jnp.where(keep, v, alpha_p) * a + b).astype(v.dtype)
    return dispatch(f, (_ensure(x),), name="alpha_dropout")


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """reference: nn/functional/common.py feature_alpha_dropout — alpha
    dropout that drops whole channels (dim 1), keeping SELU
    self-normalizing statistics."""
    if not training or p == 0.0:
        return _ensure(x)

    def f(v):
        key = next_key()
        alpha = 1.6732632423543772848170429916717
        scale = 1.0507009873554804934193349852946
        alpha_p = -alpha * scale
        mask_shape = (v.shape[0], v.shape[1]) + (1,) * (v.ndim - 2)
        keep = jax.random.bernoulli(key, 1.0 - p, mask_shape)
        a = (1.0 / np.sqrt((alpha_p ** 2 * p + 1) * (1 - p))) if p < 1 else 0.
        b = -a * alpha_p * p
        return (jnp.where(keep, v, alpha_p) * a + b).astype(v.dtype)
    return dispatch(f, (_ensure(x),), name="feature_alpha_dropout")


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Gather rows of ``weight``; padding_idx rows get zero grad (reference:
    python/paddle/nn/functional/input.py embedding). On TPU the gather lowers
    to one-hot matmul or dynamic-gather as XLA sees fit."""
    def f(ids, w):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return dispatch(f, (_ensure(x), _ensure(weight)), name="embedding")


def one_hot(x, num_classes, name=None):
    return dispatch(lambda v: jax.nn.one_hot(v, num_classes,
                                             dtype=jnp.float32),
                    (_ensure(x),), name="one_hot")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(l, *rest):
        k = l.shape[-1]
        if rest:
            return (1 - epsilon) * l + epsilon * rest[0]
        return (1 - epsilon) * l + epsilon / k
    args = (_ensure(label),)
    if prior_dist is not None:
        args = args + (_ensure(prior_dist),)
    return dispatch(f, args, name="label_smooth")


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def f(a, b):
        an = jnp.sum(a * a, axis=axis)
        bn = jnp.sum(b * b, axis=axis)
        dot = jnp.sum(a * b, axis=axis)
        return dot / jnp.maximum(jnp.sqrt(an * bn), eps)
    return dispatch(f, (_ensure(x1), _ensure(x2)), name="cosine_similarity")


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def f(a, b):
        d = a - b + epsilon
        return jnp.sum(jnp.abs(d) ** p, axis=-1,
                       keepdims=keepdim) ** (1.0 / p)
    return dispatch(f, (_ensure(x), _ensure(y)), name="pairwise_distance")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(v):
        n = jnp.sum(jnp.abs(v) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(n, epsilon)
    return dispatch(f, (_ensure(x),), name="normalize")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW",
        pad_from_left_axis=True, name=None):
    from ...tensor.manipulation import pad as _pad
    # paddle F.pad with len(pad)==2*ndim pads all dims from left axis;
    # otherwise pads spatial dims per data_format
    x = _ensure(x)
    p = list(to_value(pad)) if isinstance(pad, Tensor) else list(pad)
    nd = x.ndim
    if len(p) == 2 * nd and mode == "constant":
        if pad_from_left_axis:
            widths = [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(nd)]
        else:
            widths = [(int(p[2 * (nd - 1 - i)]), int(p[2 * (nd - 1 - i) + 1]))
                      for i in range(nd)]
        return dispatch(lambda v: jnp.pad(v, widths, constant_values=value),
                        (x,), name="pad")
    # spatial pad: p covers last k dims (reversed pairs, torch-style) with
    # channel placement per data_format
    k = len(p) // 2
    if data_format.endswith("C") and data_format.startswith("N"):
        # NHWC-like: spatial dims are 1..nd-2
        widths = [(0, 0)] * nd
        for i in range(k):
            dim = nd - 2 - i
            widths[dim] = (int(p[2 * i]), int(p[2 * i + 1]))
    else:  # NCHW-like: spatial dims are 2..nd-1
        widths = [(0, 0)] * nd
        for i in range(k):
            dim = nd - 1 - i
            widths[dim] = (int(p[2 * i]), int(p[2 * i + 1]))
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]

    def f(v):
        if jmode == "constant":
            return jnp.pad(v, widths, mode=jmode, constant_values=value)
        return jnp.pad(v, widths, mode=jmode)
    return dispatch(f, (x,), name="pad")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0,
               data_format=data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    ks = _pair(kernel_sizes)
    st = _pair(strides)
    pd = _quad(paddings)
    dl = _pair(dilations)

    def f(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, [(0, 0), (0, 0), (pd[0], pd[1]), (pd[2], pd[3])])
        oh = (v.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (v.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        patches = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                sl = v[:, :, i * dl[0]: i * dl[0] + oh * st[0]: st[0],
                       j * dl[1]: j * dl[1] + ow * st[1]: st[1]]
                patches.append(sl)
        out = jnp.stack(patches, axis=2)  # [n, c, kh*kw, oh, ow]
        return out.reshape(n, c * ks[0] * ks[1], oh * ow)
    return dispatch(f, (_ensure(x),), name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    os_ = _pair(output_sizes)
    ks = _pair(kernel_sizes)
    st = _pair(strides)
    pd = _quad(paddings)
    dl = _pair(dilations)

    def f(v):
        n, ckk, L = v.shape
        c = ckk // (ks[0] * ks[1])
        ph, pw = os_[0] + pd[0] + pd[1], os_[1] + pd[2] + pd[3]
        oh = (ph - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (pw - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        v = v.reshape(n, c, ks[0], ks[1], oh, ow)
        out = jnp.zeros((n, c, ph, pw), dtype=v.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                out = out.at[:, :, i * dl[0]: i * dl[0] + oh * st[0]: st[0],
                             j * dl[1]: j * dl[1] + ow * st[1]: st[1]].add(
                    v[:, :, i, j])
        return out[:, :, pd[0]: ph - pd[1], pd[2]: pw - pd[3]]
    return dispatch(f, (_ensure(x),), name="fold")


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in v)
    return (int(v), int(v))


def _quad(v):
    if isinstance(v, (list, tuple)):
        if len(v) == 2:
            return (int(v[0]), int(v[0]), int(v[1]), int(v[1]))
        return tuple(int(i) for i in v)
    return (int(v),) * 4


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    """reference: python/paddle/nn/functional/common.py interpolate.
    Uses jax.image.resize; 'nearest'/'bilinear'/'bicubic'/'trilinear'/'area'."""
    x = _ensure(x)
    nd = x.ndim
    channel_last = data_format.endswith("C")
    spatial = list(range(1, nd - 1)) if channel_last else list(range(2, nd))
    in_spatial = [x.shape[i] for i in spatial]
    if size is not None:
        if isinstance(size, Tensor):
            size = [int(s) for s in size.numpy()]
        out_spatial = [int(to_value(s)) if isinstance(s, Tensor) else int(s)
                       for s in (size if isinstance(size, (list, tuple))
                                 else [size])]
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
            else [scale_factor] * len(spatial)
        out_spatial = [int(np.floor(s * float(f)))
                       for s, f in zip(in_spatial, sf)]
    method = {"nearest": "nearest", "bilinear": "bilinear", "area": "linear",
              "bicubic": "cubic", "trilinear": "trilinear",
              "linear": "linear"}[mode.lower()]
    if method == "trilinear":
        method = "trilinear" if hasattr(jax.image.ResizeMethod, "TRILINEAR") \
            else "linear"

    def f(v):
        out_shape = list(v.shape)
        for i, d in enumerate(spatial):
            out_shape[d] = out_spatial[i]
        if mode.lower() in ("bilinear", "bicubic", "linear", "trilinear") \
                and align_corners:
            # jax.image.resize has no align_corners; emulate with map_coords
            return _resize_align_corners(v, out_shape, spatial, mode.lower())
        m = "linear" if method in ("bilinear", "trilinear") else method
        return jax.image.resize(v, out_shape, method=m)
    return dispatch(f, (x,), name="interpolate")


def _resize_align_corners(v, out_shape, spatial, mode):
    order = 1 if mode in ("bilinear", "linear", "trilinear") else 3
    coords = []
    for d in range(v.ndim):
        n_out = out_shape[d]
        n_in = v.shape[d]
        if d in spatial and n_out != n_in:
            if n_out == 1:
                c = jnp.zeros((n_out,))
            else:
                c = jnp.linspace(0, n_in - 1, n_out)
        else:
            c = jnp.arange(n_out, dtype=jnp.float32)
        coords.append(c)
    grid = jnp.meshgrid(*coords, indexing="ij")
    from jax.scipy.ndimage import map_coordinates
    return map_coordinates(v.astype(jnp.float32), grid, order=min(order, 1)
                           ).astype(v.dtype)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW",
             name=None):
    return interpolate(x, size, scale_factor, mode, align_corners,
                       align_mode, data_format)


def bilinear(x1, x2, weight, bias=None, name=None):
    def f(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out
    args = (_ensure(x1), _ensure(x2), _ensure(weight))
    if bias is not None:
        args += (_ensure(bias),)
    return dispatch(f, args, name="bilinear")


def pdist(x, p=2.0, name=None):
    """Pairwise p-norm distance between row vectors (reference:
    python/paddle/nn/functional/distance.py:119 — upper-triangle flat
    output of length N(N-1)/2)."""
    def f(v):
        assert v.ndim == 2, "pdist: x must be 2-D"
        n = v.shape[0]
        # gather only the N(N-1)/2 unique pairs up front — half the
        # compute and peak memory of the full N x N x D difference
        iu, ju = jnp.triu_indices(n, k=1)
        diff = jnp.abs(v[iu] - v[ju])              # [n(n-1)/2, D]
        if p == 0:
            return jnp.sum((diff != 0).astype(v.dtype), axis=-1)
        if p == float("inf"):
            return jnp.max(diff, axis=-1)
        # stable p-norm: factor out the row max so diff**p can't
        # overflow for large values
        m = jnp.max(diff, axis=-1, keepdims=True)
        safe = jnp.where(m > 0, diff / jnp.where(m > 0, m, 1), 0.0)
        return m[..., 0] * jnp.sum(safe ** p, axis=-1) ** (1.0 / p)
    return dispatch(f, (_ensure(x),), name="pdist")
