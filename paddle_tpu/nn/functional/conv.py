"""Convolution functionals (reference: python/paddle/nn/functional/conv.py).

All convs lower to ``lax.conv_general_dilated`` — XLA maps them onto the MXU
directly (the reference needs cuDNN algo search + autotune,
paddle/phi/kernels/gpudnn/conv_kernel.cu; XLA's conv emitter replaces that).
Weight layout follows the reference: [out_c, in_c/groups, *spatial].
"""
from __future__ import annotations

from typing import Sequence, Union

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...core.tensor import Tensor, dispatch


def _ensure(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(i) for i in v)
        if len(v) == 2 * n:  # paddle allows per-side padding
            return tuple(int(i) for i in v)
        return tuple(int(v[0]) for _ in range(n))
    return tuple(int(v) for _ in range(n))


def _padding(padding, n, data_format):
    """Normalise paddle padding spec to lax [(lo, hi)] * n or 'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)) and padding and \
            isinstance(padding[0], (list, tuple)):
        # [[0,0],[0,0],[h0,h1],[w0,w1]] form: extract spatial entries
        spatial = [p for p in padding if list(p) != [0, 0]]
        if len(spatial) == n:
            return [tuple(int(i) for i in p) for p in spatial]
        idx = (2, 2 + n) if data_format.startswith("NC") else (1, 1 + n)
        return [tuple(int(i) for i in p) for p in padding[idx[0]:idx[1]]]
    p = _tuple(padding, n)
    if len(p) == 2 * n:
        return [(p[2 * i], p[2 * i + 1]) for i in range(n)]
    return [(pi, pi) for pi in p]


def _dims(n, channel_last):
    if n == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if n == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last \
            else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last \
        else ("NCDHW", "OIDHW", "NCDHW")


import os as _os

# Internally compute channel-first convs in channels-last layout (transpose
# in/out; XLA cancels back-to-back transposes between conv layers). On TPU
# the MXU wants the channel dim minor-most — this is the analog of the
# reference's cuDNN NHWC autotune choice (paddle/phi/kernels/gpudnn/).
_INTERNAL_CHANNELS_LAST = _os.environ.get(
    "PADDLE_TPU_CONV_CHANNELS_LAST", "1") not in ("0", "false", "False")


def _conv(x, weight, bias, stride, padding, dilation, groups, n,
          data_format, name):
    channel_last = data_format.endswith("C")
    st = _tuple(stride, n)[:n]
    dl = _tuple(dilation, n)[:n]
    pd = _padding(padding, n, data_format)
    to_nhwc = _INTERNAL_CHANNELS_LAST and not channel_last
    lhs_spec, rhs_spec, out_spec = _dims(n, channel_last or to_nhwc)

    def f(v, w, *rest):
        if to_nhwc:
            v = jnp.transpose(v, (0,) + tuple(range(2, n + 2)) + (1,))
        # weight arrives in paddle layout OI*; transpose to rhs_spec
        if rhs_spec != "OI" + rhs_spec[2:]:
            # e.g. HWIO: move O,I to the back
            perm = [2 + i for i in range(n)] + [1, 0]
            w = jnp.transpose(w, perm)
        # no preferred_element_type: the TPU MXU accumulates bf16 convs in
        # fp32 natively, and mixed preferred dtypes break the transpose rule
        out = lax.conv_general_dilated(
            v, w, window_strides=st, padding=pd,
            lhs_dilation=(1,) * n, rhs_dilation=dl,
            dimension_numbers=(lhs_spec, rhs_spec, out_spec),
            feature_group_count=groups)
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[out_spec.index("C")] = b.size
            out = out + b.reshape(shape)
        if to_nhwc:
            out = jnp.transpose(out, (0, n + 1) + tuple(range(1, n + 1)))
        return out
    args = (_ensure(x), _ensure(weight))
    if bias is not None:
        args += (_ensure(bias),)
    return dispatch(f, args, name=name)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    df = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, df,
                 "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format, "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format, "conv3d")


def _conv_transpose(x, weight, bias, stride, padding, output_padding,
                    dilation, groups, n, data_format, output_size, name):
    channel_last = data_format.endswith("C")
    st = _tuple(stride, n)[:n]
    dl = _tuple(dilation, n)[:n]
    pd = _padding(padding, n, data_format)
    op = _tuple(output_padding, n)[:n] if output_padding is not None \
        else (0,) * n
    lhs_spec, rhs_spec, out_spec = _dims(n, channel_last)

    def f(v, w, *rest):
        # paddle transpose-conv weight layout: [in_c, out_c/groups, *spatial]
        # grad-of-conv formulation: lhs_dilation = stride
        if isinstance(pd, str):
            pads = pd
        else:
            # transposed conv padding: k-1-p on each side (plus out padding hi)
            pads = []
            k = [w.shape[2 + i] for i in range(n)]
            for i in range(n):
                eff_k = dl[i] * (k[i] - 1) + 1
                lo = eff_k - 1 - pd[i][0]
                hi = eff_k - 1 - pd[i][1] + op[i]
                pads.append((lo, hi))
        # weight: IO* -> flip spatial, swap I/O per group
        wt = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        if groups > 1:
            ic = wt.shape[0]
            ocg = wt.shape[1]
            wt = wt.reshape((groups, ic // groups, ocg) + wt.shape[2:])
            wt = jnp.swapaxes(wt, 1, 2)
            wt = wt.reshape((groups * ocg, ic // groups) + wt.shape[3:])
        else:
            wt = jnp.swapaxes(wt, 0, 1)
        if rhs_spec != "OI" + rhs_spec[2:]:
            perm = [2 + i for i in range(n)] + [1, 0]
            wt = jnp.transpose(wt, perm)
        out = lax.conv_general_dilated(
            v, wt, window_strides=(1,) * n, padding=pads,
            lhs_dilation=st, rhs_dilation=dl,
            dimension_numbers=(lhs_spec, rhs_spec, out_spec),
            feature_group_count=groups)
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[out_spec.index("C")] = b.size
            out = out + b.reshape(shape)
        return out
    args = (_ensure(x), _ensure(weight))
    if bias is not None:
        args += (_ensure(bias),)
    return dispatch(f, args, name=name)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    df = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, df, output_size,
                           "conv1d_transpose")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format, output_size,
                           "conv2d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format, output_size,
                           "conv3d_transpose")
