"""Import-path alias (reference:
python/paddle/nn/functional/flash_attention.py) — ported scripts do
``from paddle.nn.functional.flash_attention import flash_attention``;
the implementations live in nn/functional/attention.py here."""
from .attention import (flash_attention,  # noqa: F401
                        flash_attn_qkvpacked, flash_attn_unpadded,
                        flash_attn_varlen_qkvpacked,
                        flashmask_attention,
                        scaled_dot_product_attention,
                        sparse_attention)

# reference spells the varlen entry both ways across releases
flash_attn_varlen_func = flash_attn_unpadded
