"""Loss functionals (reference: python/paddle/nn/functional/loss.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch, to_value


def _ensure(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    """reference: python/paddle/nn/functional/loss.py cross_entropy.
    The TP vocab-sharded variant lives in distributed.fleet
    (ParallelCrossEntropy)."""
    args = (_ensure(input), _ensure(label))
    if weight is not None:
        args += (_ensure(weight),)

    def f(logits, label, *w):
        lg = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(lg, axis=axis) if use_softmax else \
            jnp.log(jnp.maximum(lg, 1e-30))
        if soft_label or (label.ndim == logits.ndim
                          and label.shape == logits.shape
                          and jnp.issubdtype(label.dtype, jnp.floating)):
            tgt = label.astype(jnp.float32)
            if label_smoothing > 0:
                k = logits.shape[axis]
                tgt = (1 - label_smoothing) * tgt + label_smoothing / k
            loss = -jnp.sum(tgt * logp, axis=axis)
            return _reduce(loss, reduction)
        lbl = label
        if lbl.ndim == logits.ndim and lbl.shape[axis] == 1:
            lbl = jnp.squeeze(lbl, axis=axis)
        lbl = lbl.astype(jnp.int32)
        valid = lbl != ignore_index
        safe = jnp.where(valid, lbl, 0)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe, axis), axis=axis)
        picked = jnp.squeeze(picked, axis=axis)
        if label_smoothing > 0:
            k = logits.shape[axis]
            smooth = jnp.mean(logp, axis=axis)
            picked = (1 - label_smoothing) * picked + label_smoothing * smooth
        loss = jnp.where(valid, -picked, 0.0)
        if w:
            wv = jnp.take(w[0], safe)
            wv = jnp.where(valid, wv, 0.0)
            loss = loss * wv
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(wv), 1e-12)
        if reduction == "mean":
            cnt = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
            return jnp.sum(loss) / cnt
        return _reduce(loss, reduction)
    return dispatch(f, args, name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    from .activation import softmax
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    args = (_ensure(input), _ensure(label))
    if weight is not None:
        args += (_ensure(weight),)

    def f(logp, lbl, *w):
        lbl = lbl.astype(jnp.int32)
        valid = lbl != ignore_index
        safe = jnp.where(valid, lbl, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1)
        picked = jnp.squeeze(picked, axis=1)
        loss = jnp.where(valid, -picked, 0.0)
        if w:
            wv = jnp.where(valid, jnp.take(w[0], safe), 0.0)
            loss = loss * wv
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(wv), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(valid.astype(jnp.float32)), 1.0)
        return _reduce(loss, reduction)
    return dispatch(f, args, name="nll_loss")


def mse_loss(input, label, reduction="mean", name=None):
    return dispatch(lambda a, b: _reduce((a - b) ** 2, reduction),
                    (_ensure(input), _ensure(label)), name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return dispatch(lambda a, b: _reduce(jnp.abs(a - b), reduction),
                    (_ensure(input), _ensure(label)), name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)
    return dispatch(f, (_ensure(input), _ensure(label)),
                    name="smooth_l1_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    args = (_ensure(input), _ensure(label))
    if weight is not None:
        args += (_ensure(weight),)

    def f(p, y, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)
    return dispatch(f, args, name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    args = (_ensure(logit), _ensure(label))
    if weight is not None:
        args += (_ensure(weight),)
    if pos_weight is not None:
        args += (_ensure(pos_weight),)

    def f(z, y, *rest):
        # numerically-stable BCE-with-logits
        log_sig = jax.nn.log_sigmoid(z)
        log_sig_neg = jax.nn.log_sigmoid(-z)
        i = 0
        pw = None
        w = None
        if weight is not None:
            w = rest[i]; i += 1
        if pos_weight is not None:
            pw = rest[i]
        if pw is not None:
            loss = -(pw * y * log_sig + (1 - y) * log_sig_neg)
        else:
            loss = -(y * log_sig + (1 - y) * log_sig_neg)
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    return dispatch(f, args, name="bce_with_logits")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def f(logp, q):
        if log_target:
            loss = jnp.exp(q) * (q - logp)
        else:
            loss = jnp.where(q > 0, q * (jnp.log(jnp.maximum(q, 1e-30))
                                         - logp), 0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return dispatch(f, (_ensure(input), _ensure(label)), name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def f(a, b, y):
        return _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction)
    return dispatch(f, (_ensure(input), _ensure(other), _ensure(label)),
                    name="margin_ranking_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    def f(x, y):
        loss = jnp.where(y == 1.0, x, jnp.maximum(0.0, margin - x))
        return _reduce(loss, reduction)
    return dispatch(f, (_ensure(input), _ensure(label)),
                    name="hinge_embedding_loss")


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    def f(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return dispatch(f, (_ensure(input1), _ensure(input2), _ensure(label)),
                    name="cosine_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean",
                        name=None):
    def f(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v + epsilon) ** p, -1) ** (1 / p)
        d_ap = dist(a, pos)
        d_an = dist(a, neg)
        if swap:
            d_an = jnp.minimum(d_an, dist(pos, neg))
        return _reduce(jnp.maximum(0.0, d_ap - d_an + margin), reduction)
    return dispatch(f, (_ensure(input), _ensure(positive), _ensure(negative)),
                    name="triplet_margin_loss")


def log_loss(input, label, epsilon=1e-4, name=None):
    def f(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)
    return dispatch(f, (_ensure(input), _ensure(label)), name="log_loss")


def square_error_cost(input, label):
    return dispatch(lambda a, b: (a - b) ** 2,
                    (_ensure(input), _ensure(label)),
                    name="square_error_cost")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard forward algorithm in log space (lax.scan over
    time). reference: warpctc kernel paddle/phi/kernels/gpu/warpctc_kernel.cu."""
    args = (_ensure(log_probs), _ensure(labels), _ensure(input_lengths),
            _ensure(label_lengths))

    def f(lp, lab, in_len, lab_len):
        # lp: [T, B, C] logits (paddle convention); make log-probs
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        T, B, C = lp.shape
        S = lab.shape[1]
        ext = jnp.full((B, 2 * S + 1), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        L = 2 * lab_len.astype(jnp.int32) + 1
        neg_inf = -1e30
        alpha0 = jnp.full((B, 2 * S + 1), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        alpha0 = alpha0.at[:, 1].set(
            jnp.take_along_axis(lp[0], ext[:, 1:2], axis=1)[:, 0])

        same = jnp.concatenate(
            [jnp.zeros((B, 2), bool),
             ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, t):
            a1 = alpha
            a2 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]],
                                 axis=1)
            a3 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]],
                                 axis=1)
            a3 = jnp.where(same | (ext == blank), neg_inf, a3)
            m = jnp.maximum(jnp.maximum(a1, a2), a3)
            new = m + jnp.log(jnp.exp(a1 - m) + jnp.exp(a2 - m)
                              + jnp.exp(a3 - m) + 1e-30)
            emit = jnp.take_along_axis(lp[t], ext, axis=1)
            new = new + emit
            # freeze once past input length
            new = jnp.where(t < in_len[:, None], new, alpha)
            return new, None
        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        idx_last = L - 1
        idx_prev = jnp.maximum(L - 2, 0)
        a_last = jnp.take_along_axis(alpha, idx_last[:, None], axis=1)[:, 0]
        a_prev = jnp.take_along_axis(alpha, idx_prev[:, None], axis=1)[:, 0]
        m = jnp.maximum(a_last, a_prev)
        ll = m + jnp.log(jnp.exp(a_last - m) + jnp.exp(a_prev - m) + 1e-30)
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(jnp.float32),
                                               1.0))
        return _reduce(loss, reduction)
    return dispatch(f, args, name="ctc_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    args = (_ensure(logit), _ensure(label))
    if normalizer is not None:
        args += (_ensure(normalizer),)

    def f(z, y, *n):
        p = jax.nn.sigmoid(z)
        ce = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)
    return dispatch(f, args, name="sigmoid_focal_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    def f(p, y):
        y1 = jax.nn.one_hot(jnp.squeeze(y, -1), p.shape[-1], dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = jnp.sum(p * y1, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(y1, axis=reduce_dims)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return dispatch(f, (_ensure(input), _ensure(label)), name="dice_loss")


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def f(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(
                2 * np.pi * (y + epsilon))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)
    return dispatch(f, (_ensure(input), _ensure(label)),
                    name="poisson_nll_loss")


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def f(mu, y, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            loss = loss + 0.5 * np.log(2 * np.pi)
        return _reduce(loss, reduction)
    return dispatch(f, (_ensure(input), _ensure(label), _ensure(variance)),
                    name="gaussian_nll_loss")


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    args = (_ensure(input), _ensure(label))
    if weight is not None:
        args += (_ensure(weight),)

    def f(x, y, *w):
        loss = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        if w:
            loss = loss * w[0]
        loss = jnp.mean(loss, axis=-1)
        return _reduce(loss, reduction)
    return dispatch(f, args, name="multi_label_soft_margin_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):
    def f(x, y):
        return _reduce(jnp.log1p(jnp.exp(-y * x)), reduction)
    return dispatch(f, (_ensure(input), _ensure(label)),
                    name="soft_margin_loss")


def hinge_loss(input, label, name=None):
    """reference: ops.yaml hinge_loss / funcs/eigen/loss.cc:112 —
    elementwise max(0, 1 - pred * (2*label - 1)); labels in {0, 1}."""
    return dispatch(
        lambda x, y: jnp.maximum(0.0, 1.0 - x * (2.0 * y - 1.0)),
        (_ensure(input), _ensure(label)), name="hinge_loss")


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    """reference: ops.yaml huber_loss — quadratic within +-delta, linear
    outside."""
    def f(x, y):
        d = jnp.abs(x - y)
        quad = 0.5 * d * d
        lin = delta * (d - 0.5 * delta)
        out = jnp.where(d <= delta, quad, lin)
        return _reduce(out, reduction)
    return dispatch(f, (_ensure(input), _ensure(label)), name="huber_loss")


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """reference: nn/functional/loss.py multi_margin_loss — multi-class
    hinge: mean_j max(0, margin - x_y + x_j)^p over j != y."""
    args = (_ensure(input), _ensure(label)) + \
        ((_ensure(weight),) if weight is not None else ())

    def f(x, y, *w):
        n, c = x.shape
        y = y.astype(jnp.int32).reshape(-1)
        x_y = jnp.take_along_axis(x, y[:, None], axis=1)
        m = jnp.maximum(0.0, margin - x_y + x) ** p
        if w:
            m = m * jnp.take(w[0], y)[:, None]
        m = m * (1 - jax.nn.one_hot(y, c, dtype=x.dtype))  # skip j == y
        return _reduce(jnp.sum(m, axis=1) / c, reduction)

    return dispatch(f, args, name="multi_margin_loss")


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """reference: nn/functional/loss.py triplet_margin_with_distance_loss
    — triplet loss with a user distance; default pairwise L2."""
    from ...core.tensor import Tensor as _T

    def dist(a, b):
        if distance_function is not None:
            out = distance_function(_T(a), _T(b))
            from ...core.tensor import to_value
            return to_value(out)
        return jnp.sqrt(jnp.sum((a - b) ** 2, axis=-1) + 1e-12)

    def f(a, pos, neg):
        d_pos = dist(a, pos)
        d_neg = dist(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dist(pos, neg))
        return _reduce(jnp.maximum(0.0, d_pos - d_neg + margin), reduction)

    return dispatch(f, (_ensure(input), _ensure(positive),
                        _ensure(negative)),
                    name="triplet_margin_with_distance_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """reference: nn/functional/loss.py npair_loss (N-pair loss, NIPS16):
    cross entropy over anchor . positive^T similarities + L2 on
    embeddings."""
    def f(a, pos, y):
        y = y.reshape(-1)
        sim = a @ pos.T                     # [B, B]
        same = (y[:, None] == y[None, :]).astype(a.dtype)
        tgt = same / jnp.sum(same, axis=1, keepdims=True)
        lsm = jax.nn.log_softmax(sim, axis=1)
        ce = -jnp.mean(jnp.sum(tgt * lsm, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, 1))
                        + jnp.mean(jnp.sum(pos * pos, 1))) * 0.25
        return ce + reg

    return dispatch(f, (_ensure(anchor), _ensure(positive),
                        _ensure(labels)), name="npair_loss")


def _default_tree_paths(num_classes):
    """Complete-binary-tree paths for default hsigmoid (reference
    HierarchicalSigmoid default mode, phi/kernels/cpu/hsigmoid_loss_
    kernel.cc via matrix_bit_code): leaf for class c is heap node
    c + num_classes - 1; internal nodes 0..num_classes-2; code bit 1 for
    the RIGHT child on the way down."""
    depth = int(np.ceil(np.log2(max(num_classes, 2))))
    tables, codes = [], []
    for c in range(num_classes):
        node = c + num_classes - 1
        path, code = [], []
        while node > 0:
            parent = (node - 1) // 2
            path.append(parent)
            code.append(node == 2 * parent + 2)   # right child -> 1
            node = parent
        path = path[::-1][:depth]
        code = code[::-1][:depth]
        pad = depth - len(path)
        tables.append(path + [-1] * pad)
        codes.append([float(b) for b in code] + [0.0] * pad)
    return (np.asarray(tables, np.int64), np.asarray(codes, np.float32))


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """reference: nn/functional/loss.py hsigmoid_loss — hierarchical
    sigmoid: sum over the class's tree path of
    softplus((1 - 2*code) * (w_node . x + b_node)); O(log C) per sample
    instead of a C-way softmax."""
    if path_table is None or path_code is None:
        tbl, code = _default_tree_paths(int(num_classes))
        pt = jnp.asarray(tbl)
        pc = jnp.asarray(code)
        gather_label = True
    else:
        pt = jnp.asarray(to_value(_ensure(path_table)), jnp.int64)
        pc = jnp.asarray(to_value(_ensure(path_code)), jnp.float32)
        gather_label = False
    args = (_ensure(input), _ensure(label), _ensure(weight)) + \
        ((_ensure(bias),) if bias is not None else ())

    def f(x, y, w, *b):
        y = y.astype(jnp.int32).reshape(-1)
        if gather_label:
            paths = pt[y]            # [N, depth]
            codes = pc[y]
        else:
            paths, codes = pt, pc    # custom: already per-sample
        valid = paths >= 0
        idx = jnp.maximum(paths, 0)
        wn = w[idx]                  # [N, depth, D]
        logits = jnp.einsum("nd,ntd->nt", x.astype(jnp.float32),
                            wn.astype(jnp.float32))
        if b:
            logits = logits + b[0].reshape(-1)[idx]
        # reference sign convention: code bit 1 keeps the logit,
        # 0 negates — loss = softplus(logit) - code*logit summed on path
        per = jax.nn.softplus(logits) - codes * logits
        per = jnp.where(valid, per, 0.0)
        return jnp.sum(per, axis=1, keepdims=True)

    return dispatch(f, args, name="hsigmoid_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean",
                         name=None):
    """reference: nn/functional/loss.py margin_cross_entropy — combined
    ArcFace/CosFace margin on the target logit:
    cos(m1*theta + m2) - m3, all logits scaled by s."""
    def f(lg, y):
        y = y.astype(jnp.int32).reshape(-1)
        lg = jnp.clip(lg.astype(jnp.float32), -1.0, 1.0)
        tgt = jnp.take_along_axis(lg, y[:, None], 1)[:, 0]
        theta = jnp.arccos(tgt)
        new_tgt = jnp.cos(margin1 * theta + margin2) - margin3
        oh = jax.nn.one_hot(y, lg.shape[-1], dtype=lg.dtype)
        out = (lg * (1 - oh) + new_tgt[:, None] * oh) * scale
        lsm = jax.nn.log_softmax(out, axis=-1)
        loss = -jnp.take_along_axis(lsm, y[:, None], 1)
        red = _reduce(loss, reduction)
        return (red, jnp.exp(lsm)) if return_softmax else red

    out = dispatch(f, (_ensure(logits), _ensure(label)),
                   name="margin_cross_entropy",
                   multi_output=return_softmax)
    return out


def class_center_sample(label, num_classes, num_samples, group=None,
                        name=None):
    """reference: nn/functional/common.py class_center_sample — sample
    the positive class centers plus negatives up to num_samples; returns
    (remapped_label, sampled_class_center). Host-side (the sampled set
    is data-dependent), like the reference's CPU path."""
    y = np.asarray(to_value(_ensure(label))).astype(np.int64).ravel()
    pos = np.unique(y)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        rest = np.setdiff1d(np.arange(num_classes), pos,
                            assume_unique=True)
        extra = np.random.default_rng().choice(
            rest, num_samples - len(pos), replace=False)
        sampled = np.sort(np.concatenate([pos, extra]))
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return Tensor(remap[y]), Tensor(sampled)


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """reference: nn/functional/loss.py adaptive_log_softmax_with_loss
    (Grave et al. efficient softmax): head covers the frequent classes +
    one logit per tail cluster; each tail projects down then classifies
    within its cluster. Returns (target log-probs, mean NLL loss)."""
    cutoffs = [int(c) for c in cutoffs]
    args = (_ensure(input), _ensure(label), _ensure(head_weight)) + \
        tuple(_ensure(w) for pair in tail_weights for w in pair) + \
        ((_ensure(head_bias),) if head_bias is not None else ())
    n_tails = len(tail_weights)
    has_bias = head_bias is not None

    def f(x, y, hw, *rest):
        tails = [(rest[2 * i], rest[2 * i + 1]) for i in range(n_tails)]
        hb = rest[2 * n_tails] if has_bias else None
        y = y.astype(jnp.int32).reshape(-1)
        head_logits = x @ hw
        if hb is not None:
            head_logits = head_logits + hb
        head_lsm = jax.nn.log_softmax(head_logits, -1)
        shortlist = cutoffs[0]
        out = jnp.where(
            y < shortlist,
            jnp.take_along_axis(head_lsm,
                                jnp.minimum(y, shortlist - 1)[:, None],
                                1)[:, 0],
            0.0)
        for i, (proj, cls) in enumerate(tails):
            lo = cutoffs[i]
            hi = cutoffs[i + 1] if i + 1 < len(cutoffs) else None
            in_cluster = (y >= lo) & ((y < hi) if hi is not None
                                      else jnp.full_like(y, True,
                                                         dtype=bool))
            cluster_lsm = jax.nn.log_softmax(
                (x @ proj) @ cls, -1)
            rel = jnp.clip(y - lo, 0, cls.shape[-1] - 1)
            lp = head_lsm[:, shortlist + i] + \
                jnp.take_along_axis(cluster_lsm, rel[:, None], 1)[:, 0]
            out = jnp.where(in_cluster, lp, out)
        return out, -jnp.mean(out)

    return dispatch(f, args, name="adaptive_log_softmax_with_loss",
                    multi_output=True)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean", name=None):
    """reference: nn/functional/loss.py rnnt_loss (RNN transducer,
    Graves 2012): forward-variable DP over the (T, U+1) lattice in log
    space, vectorized as a lax.scan over T with a cumulative-logsumexp
    sweep over U inside each step.

    FastEmit regularization (Yu et al. 2021, the reference defaults to
    lambda=0.001) is applied at the gradient level, exactly as the
    reference's warprnnt kernel does: gradients flowing through the
    *label*-emission probabilities are scaled by (1 + lambda) while
    blank-emission gradients are untouched, and the reported loss value
    stays -log P(y|x). Implemented with a stop-gradient identity on the
    label log-probs: lab + lambda * (lab - stop_grad(lab)) has the same
    value as lab but d/dlab = 1 + lambda, so one lattice DP yields the
    FastEmit-scaled gradient at zero extra compute."""
    args = (_ensure(input), _ensure(label), _ensure(input_lengths),
            _ensure(label_lengths))

    def f(logits, y, t_len, u_len):
        b, t_max, u_max, v = logits.shape       # u_max = U + 1
        lsm = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        blank_lp = lsm[..., blank]              # [B, T, U+1]
        y = y.astype(jnp.int32)
        # label emission log-probs: lab_lp[b, t, u] = lsm[b,t,u,y[b,u]]
        yy = jnp.minimum(y, v - 1)
        lab_lp = jnp.take_along_axis(
            lsm[:, :, :u_max - 1],
            jnp.broadcast_to(yy[:, None, :, None],
                             (b, t_max, u_max - 1, 1)),
            axis=-1)[..., 0]                    # [B, T, U]
        neg_inf = jnp.asarray(-1e30, jnp.float32)

        def lattice_ll(blank_lp, lab_lp):
            def step(alpha, t):
                # alpha: [B, U+1] forward vars at time t
                # emit transitions within the same t: u-1 -> u
                blank_t = blank_lp[:, t]        # [B, U+1]
                lab_t = lab_lp[:, t]            # [B, U]

                def emit_scan(carry, u):
                    prev = carry                 # alpha_new[u-1]
                    cur = jnp.logaddexp(alpha[:, u],
                                        prev + lab_t[:, u - 1])
                    return cur, cur

                first = alpha[:, 0]
                _, rest = jax.lax.scan(
                    emit_scan, first, jnp.arange(1, u_max))
                alpha_e = jnp.concatenate(
                    [first[:, None], jnp.moveaxis(rest, 0, 1)], axis=1)
                # advance time with a blank from every u
                alpha_next = alpha_e + blank_t
                return alpha_next, alpha_e

            alpha0 = jnp.full((b, u_max), neg_inf).at[:, 0].set(0.0)
            _, alphas = jax.lax.scan(step, alpha0, jnp.arange(t_max))
            alphas = jnp.moveaxis(alphas, 0, 1)  # [B, T, U+1] (pre-blank)
            # total log-prob: alpha[t_len-1, u_len] + blank at the corner
            ti = jnp.clip(t_len.astype(jnp.int32) - 1, 0, t_max - 1)
            ui = jnp.clip(u_len.astype(jnp.int32), 0, u_max - 1)
            bidx = jnp.arange(b)
            return alphas[bidx, ti, ui] + blank_lp[bidx, ti, ui]

        if fastemit_lambda:
            lab_lp = lab_lp + fastemit_lambda * (
                lab_lp - jax.lax.stop_gradient(lab_lp))
        loss = -lattice_ll(blank_lp, lab_lp)
        return _reduce(loss, reduction)

    return dispatch(f, args, name="rnnt_loss")
