"""Loss functionals (reference: python/paddle/nn/functional/loss.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch


def _ensure(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    """reference: python/paddle/nn/functional/loss.py cross_entropy.
    The TP vocab-sharded variant lives in distributed.fleet
    (ParallelCrossEntropy)."""
    args = (_ensure(input), _ensure(label))
    if weight is not None:
        args += (_ensure(weight),)

    def f(logits, label, *w):
        lg = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(lg, axis=axis) if use_softmax else \
            jnp.log(jnp.maximum(lg, 1e-30))
        if soft_label or (label.ndim == logits.ndim
                          and label.shape == logits.shape
                          and jnp.issubdtype(label.dtype, jnp.floating)):
            tgt = label.astype(jnp.float32)
            if label_smoothing > 0:
                k = logits.shape[axis]
                tgt = (1 - label_smoothing) * tgt + label_smoothing / k
            loss = -jnp.sum(tgt * logp, axis=axis)
            return _reduce(loss, reduction)
        lbl = label
        if lbl.ndim == logits.ndim and lbl.shape[axis] == 1:
            lbl = jnp.squeeze(lbl, axis=axis)
        lbl = lbl.astype(jnp.int32)
        valid = lbl != ignore_index
        safe = jnp.where(valid, lbl, 0)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe, axis), axis=axis)
        picked = jnp.squeeze(picked, axis=axis)
        if label_smoothing > 0:
            k = logits.shape[axis]
            smooth = jnp.mean(logp, axis=axis)
            picked = (1 - label_smoothing) * picked + label_smoothing * smooth
        loss = jnp.where(valid, -picked, 0.0)
        if w:
            wv = jnp.take(w[0], safe)
            wv = jnp.where(valid, wv, 0.0)
            loss = loss * wv
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(wv), 1e-12)
        if reduction == "mean":
            cnt = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
            return jnp.sum(loss) / cnt
        return _reduce(loss, reduction)
    return dispatch(f, args, name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    from .activation import softmax
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    args = (_ensure(input), _ensure(label))
    if weight is not None:
        args += (_ensure(weight),)

    def f(logp, lbl, *w):
        lbl = lbl.astype(jnp.int32)
        valid = lbl != ignore_index
        safe = jnp.where(valid, lbl, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1)
        picked = jnp.squeeze(picked, axis=1)
        loss = jnp.where(valid, -picked, 0.0)
        if w:
            wv = jnp.where(valid, jnp.take(w[0], safe), 0.0)
            loss = loss * wv
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(wv), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(valid.astype(jnp.float32)), 1.0)
        return _reduce(loss, reduction)
    return dispatch(f, args, name="nll_loss")


def mse_loss(input, label, reduction="mean", name=None):
    return dispatch(lambda a, b: _reduce((a - b) ** 2, reduction),
                    (_ensure(input), _ensure(label)), name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return dispatch(lambda a, b: _reduce(jnp.abs(a - b), reduction),
                    (_ensure(input), _ensure(label)), name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)
    return dispatch(f, (_ensure(input), _ensure(label)),
                    name="smooth_l1_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    args = (_ensure(input), _ensure(label))
    if weight is not None:
        args += (_ensure(weight),)

    def f(p, y, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)
    return dispatch(f, args, name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    args = (_ensure(logit), _ensure(label))
    if weight is not None:
        args += (_ensure(weight),)
    if pos_weight is not None:
        args += (_ensure(pos_weight),)

    def f(z, y, *rest):
        # numerically-stable BCE-with-logits
        log_sig = jax.nn.log_sigmoid(z)
        log_sig_neg = jax.nn.log_sigmoid(-z)
        i = 0
        pw = None
        w = None
        if weight is not None:
            w = rest[i]; i += 1
        if pos_weight is not None:
            pw = rest[i]
        if pw is not None:
            loss = -(pw * y * log_sig + (1 - y) * log_sig_neg)
        else:
            loss = -(y * log_sig + (1 - y) * log_sig_neg)
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    return dispatch(f, args, name="bce_with_logits")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def f(logp, q):
        if log_target:
            loss = jnp.exp(q) * (q - logp)
        else:
            loss = jnp.where(q > 0, q * (jnp.log(jnp.maximum(q, 1e-30))
                                         - logp), 0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return dispatch(f, (_ensure(input), _ensure(label)), name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def f(a, b, y):
        return _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction)
    return dispatch(f, (_ensure(input), _ensure(other), _ensure(label)),
                    name="margin_ranking_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    def f(x, y):
        loss = jnp.where(y == 1.0, x, jnp.maximum(0.0, margin - x))
        return _reduce(loss, reduction)
    return dispatch(f, (_ensure(input), _ensure(label)),
                    name="hinge_embedding_loss")


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    def f(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return dispatch(f, (_ensure(input1), _ensure(input2), _ensure(label)),
                    name="cosine_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean",
                        name=None):
    def f(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v + epsilon) ** p, -1) ** (1 / p)
        d_ap = dist(a, pos)
        d_an = dist(a, neg)
        if swap:
            d_an = jnp.minimum(d_an, dist(pos, neg))
        return _reduce(jnp.maximum(0.0, d_ap - d_an + margin), reduction)
    return dispatch(f, (_ensure(input), _ensure(positive), _ensure(negative)),
                    name="triplet_margin_loss")


def log_loss(input, label, epsilon=1e-4, name=None):
    def f(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)
    return dispatch(f, (_ensure(input), _ensure(label)), name="log_loss")


def square_error_cost(input, label):
    return dispatch(lambda a, b: (a - b) ** 2,
                    (_ensure(input), _ensure(label)),
                    name="square_error_cost")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard forward algorithm in log space (lax.scan over
    time). reference: warpctc kernel paddle/phi/kernels/gpu/warpctc_kernel.cu."""
    args = (_ensure(log_probs), _ensure(labels), _ensure(input_lengths),
            _ensure(label_lengths))

    def f(lp, lab, in_len, lab_len):
        # lp: [T, B, C] logits (paddle convention); make log-probs
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        T, B, C = lp.shape
        S = lab.shape[1]
        ext = jnp.full((B, 2 * S + 1), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        L = 2 * lab_len.astype(jnp.int32) + 1
        neg_inf = -1e30
        alpha0 = jnp.full((B, 2 * S + 1), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        alpha0 = alpha0.at[:, 1].set(
            jnp.take_along_axis(lp[0], ext[:, 1:2], axis=1)[:, 0])

        same = jnp.concatenate(
            [jnp.zeros((B, 2), bool),
             ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, t):
            a1 = alpha
            a2 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]],
                                 axis=1)
            a3 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]],
                                 axis=1)
            a3 = jnp.where(same | (ext == blank), neg_inf, a3)
            m = jnp.maximum(jnp.maximum(a1, a2), a3)
            new = m + jnp.log(jnp.exp(a1 - m) + jnp.exp(a2 - m)
                              + jnp.exp(a3 - m) + 1e-30)
            emit = jnp.take_along_axis(lp[t], ext, axis=1)
            new = new + emit
            # freeze once past input length
            new = jnp.where(t < in_len[:, None], new, alpha)
            return new, None
        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        idx_last = L - 1
        idx_prev = jnp.maximum(L - 2, 0)
        a_last = jnp.take_along_axis(alpha, idx_last[:, None], axis=1)[:, 0]
        a_prev = jnp.take_along_axis(alpha, idx_prev[:, None], axis=1)[:, 0]
        m = jnp.maximum(a_last, a_prev)
        ll = m + jnp.log(jnp.exp(a_last - m) + jnp.exp(a_prev - m) + 1e-30)
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(jnp.float32),
                                               1.0))
        return _reduce(loss, reduction)
    return dispatch(f, args, name="ctc_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    args = (_ensure(logit), _ensure(label))
    if normalizer is not None:
        args += (_ensure(normalizer),)

    def f(z, y, *n):
        p = jax.nn.sigmoid(z)
        ce = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)
    return dispatch(f, args, name="sigmoid_focal_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    def f(p, y):
        y1 = jax.nn.one_hot(jnp.squeeze(y, -1), p.shape[-1], dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = jnp.sum(p * y1, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(y1, axis=reduce_dims)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return dispatch(f, (_ensure(input), _ensure(label)), name="dice_loss")


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def f(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(
                2 * np.pi * (y + epsilon))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)
    return dispatch(f, (_ensure(input), _ensure(label)),
                    name="poisson_nll_loss")


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def f(mu, y, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            loss = loss + 0.5 * np.log(2 * np.pi)
        return _reduce(loss, reduction)
    return dispatch(f, (_ensure(input), _ensure(label), _ensure(variance)),
                    name="gaussian_nll_loss")


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    args = (_ensure(input), _ensure(label))
    if weight is not None:
        args += (_ensure(weight),)

    def f(x, y, *w):
        loss = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        if w:
            loss = loss * w[0]
        loss = jnp.mean(loss, axis=-1)
        return _reduce(loss, reduction)
    return dispatch(f, args, name="multi_label_soft_margin_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):
    def f(x, y):
        return _reduce(jnp.log1p(jnp.exp(-y * x)), reduction)
    return dispatch(f, (_ensure(input), _ensure(label)),
                    name="soft_margin_loss")


def hinge_loss(input, label, name=None):
    """reference: ops.yaml hinge_loss / funcs/eigen/loss.cc:112 —
    elementwise max(0, 1 - pred * (2*label - 1)); labels in {0, 1}."""
    return dispatch(
        lambda x, y: jnp.maximum(0.0, 1.0 - x * (2.0 * y - 1.0)),
        (_ensure(input), _ensure(label)), name="hinge_loss")


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    """reference: ops.yaml huber_loss — quadratic within +-delta, linear
    outside."""
    def f(x, y):
        d = jnp.abs(x - y)
        quad = 0.5 * d * d
        lin = delta * (d - 0.5 * delta)
        out = jnp.where(d <= delta, quad, lin)
        return _reduce(out, reduction)
    return dispatch(f, (_ensure(input), _ensure(label)), name="huber_loss")
