"""Normalization functionals (reference: python/paddle/nn/functional/norm.py).

layer_norm / rms_norm route to the Pallas fused kernels on TPU when
FLAGS_use_fused_kernels (ops/ package); the jnp compositions here are the
reference-numerics fallback and the grad path.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch, to_value


def _ensure(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format="NCHW", use_global_stats=None, name=None):
    """reference: python/paddle/nn/functional/norm.py batch_norm.
    In training mode also *updates* running stats in-place (buffer rebind)."""
    x = _ensure(x)
    rm, rv = _ensure(running_mean), _ensure(running_var)
    ch_axis = _channel_axis(x.ndim, data_format)
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    use_batch_stats = training and not use_global_stats

    if use_batch_stats:
        # compute batch stats eagerly (outside tape) for the running update.
        # Stats accumulate in fp32 regardless of activation dtype; the data
        # path stays in the input dtype — the TPU analog of cuDNN's fused BN
        # (bf16 in/out, fp32 statistics). Two-pass mean/var: the one-pass
        # E[x^2]-E[x]^2 form catastrophically cancels when |mean| >> std.
        n = int(np.prod([x.shape[i] for i in reduce_axes]))
        unbiased = n / max(n - 1, 1)

        # rm/rv enter through dispatch so (a) to_static's discovery pass
        # registers them as buffers (save/restore on an aborted trace —
        # otherwise a failed whole-graph trace leaks tracers into the
        # running stats) and (b) the SOT segment recorder captures them
        # as externals whose mutation marks the recording replay-unsafe
        def f(v, rmv, rvv, *wb):
            v32 = v.astype(jnp.float32)
            mean = jnp.mean(v32, axis=reduce_axes)
            var = jnp.var(v32, axis=reduce_axes)
            out = _affine(v, mean, var, wb, ch_axis, epsilon,
                          weight is not None, bias is not None)
            new_rm = momentum * rmv + (1 - momentum) * mean.astype(rmv.dtype)
            new_rv = momentum * rvv + \
                (1 - momentum) * (var * unbiased).astype(rvv.dtype)
            return out, new_rm, new_rv
        args = (x, rm, rv) + _wb_args(weight, bias)
        out, new_rm, new_rv = dispatch(f, args, name="batch_norm",
                                       multi_output=True)
        # running stat update (no grad; buffer rebind)
        rm._replace_value(new_rm._value)
        rv._replace_value(new_rv._value)
        return out

    def f(v, m, va, *wb):
        return _affine(v, m, va, wb, ch_axis, epsilon,
                       weight is not None, bias is not None)
    args = (x, rm, rv) + _wb_args(weight, bias)
    return dispatch(f, args, name="batch_norm")


def _wb_args(weight, bias):
    args = ()
    if weight is not None:
        args += (_ensure(weight),)
    if bias is not None:
        args += (_ensure(bias),)
    return args


def _affine(v, mean, var, wb, ch_axis, epsilon, has_weight, has_bias):
    """y = x*scale + shift with the per-channel scalars folded in fp32 and
    the (large) activation math done in the activation dtype — no whole-
    tensor fp32 round trip. ``wb`` holds (weight?, bias?) per the explicit
    presence flags (a lone bias must not be taken for the weight)."""
    shape = [1] * v.ndim
    shape[ch_axis] = v.shape[ch_axis]
    mean32 = mean.astype(jnp.float32)
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + epsilon)
    scale = inv
    i = 0
    if has_weight:
        scale = scale * wb[i].astype(jnp.float32)
        i += 1
    shift = -mean32 * scale
    if has_bias:
        shift = shift + wb[i].astype(jnp.float32)
    return (v * scale.reshape(shape).astype(v.dtype)
            + shift.reshape(shape).astype(v.dtype))


def _channel_axis(ndim, data_format):
    return ndim - 1 if data_format.endswith("C") else 1


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05,
               name=None):
    x = _ensure(x)
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(list(normalized_shape))
    axes = tuple(range(x.ndim - n_axes, x.ndim))

    from ...core.flags import GLOBAL_FLAGS
    if (GLOBAL_FLAGS.get("use_fused_kernels") and weight is not None
            and n_axes == 1):
        from ...ops import layer_norm as fused_ln
        args = (x, _ensure(weight)) + ((_ensure(bias),)
                                       if bias is not None else ())
        return dispatch(lambda v, w, *b: fused_ln(
            v, w, b[0] if b else None, epsilon), args, name="layer_norm")

    def f(v, *wb):
        mean = jnp.mean(v.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(v.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((v.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)
               ).astype(v.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(out.dtype)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(out.dtype)
        return out
    args = (x,) + _wb_args(weight, bias)
    return dispatch(f, args, name="layer_norm")


def rms_norm(x, weight, epsilon=1e-6, name=None):
    """RMSNorm (reference fused op:
    python/paddle/incubate/nn/functional/fused_rms_norm.py)."""
    from ...core.flags import GLOBAL_FLAGS
    if GLOBAL_FLAGS.get("use_fused_kernels"):
        from ...ops import rms_norm as fused
        return dispatch(lambda v, w: fused(v, w, epsilon),
                        (_ensure(x), _ensure(weight)), name="rms_norm")

    def f(v, w):
        ms = jnp.mean(jnp.square(v.astype(jnp.float32)), axis=-1,
                      keepdims=True)
        return (v.astype(jnp.float32) * jax.lax.rsqrt(ms + epsilon)
                ).astype(v.dtype) * w
    return dispatch(f, (_ensure(x), _ensure(weight)), name="rms_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9,
                  eps=1e-05, data_format="NCHW", name=None):
    x = _ensure(x)
    ch_axis = _channel_axis(x.ndim, data_format)
    spatial = tuple(i for i in range(x.ndim) if i not in (0, ch_axis))

    def f(v, *wb):
        # fp32 statistics, activation-dtype data path (see batch_norm)
        v32 = v.astype(jnp.float32)
        mean = jnp.mean(v32, axis=spatial, keepdims=True)
        var = jnp.var(v32, axis=spatial, keepdims=True)
        out = ((v32 - mean) * jax.lax.rsqrt(var + eps)).astype(v.dtype)
        shape = [1] * v.ndim
        shape[ch_axis] = v.shape[ch_axis]
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape).astype(out.dtype)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape).astype(out.dtype)
        return out
    args = (x,) + _wb_args(weight, bias)
    return dispatch(f, args, name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    x = _ensure(x)
    ch_axis = _channel_axis(x.ndim, data_format)

    def f(v, *wb):
        if ch_axis != 1:
            v_t = jnp.moveaxis(v, ch_axis, 1)
        else:
            v_t = v
        n, c = v_t.shape[0], v_t.shape[1]
        g = v_t.reshape((n, num_groups, c // num_groups) + v_t.shape[2:])
        axes = tuple(range(2, g.ndim))
        # fp32 statistics, activation-dtype data path (see batch_norm)
        g32 = g.astype(jnp.float32)
        mean = jnp.mean(g32, axis=axes, keepdims=True)
        var = jnp.var(g32, axis=axes, keepdims=True)
        out = ((g32 - mean) * jax.lax.rsqrt(var + epsilon)
               ).astype(v.dtype).reshape(v_t.shape)
        shape = [1, c] + [1] * (v_t.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape).astype(out.dtype)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape).astype(out.dtype)
        if ch_axis != 1:
            out = jnp.moveaxis(out, 1, ch_axis)
        return out
    args = (x,) + _wb_args(weight, bias)
    return dispatch(f, args, name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    x = _ensure(x)
    ch_axis = _channel_axis(x.ndim, data_format)

    def f(v):
        sq = jnp.square(v)
        c = v.shape[ch_axis]
        sq_m = jnp.moveaxis(sq, ch_axis, -1)
        pad_lo = (size - 1) // 2
        pad_hi = size - 1 - pad_lo
        padded = jnp.pad(sq_m, [(0, 0)] * (v.ndim - 1) + [(pad_lo, pad_hi)])
        windows = jnp.stack([padded[..., i:i + c] for i in range(size)],
                            axis=0).sum(0)
        denom = (k + alpha * windows) ** beta
        return v / jnp.moveaxis(denom, -1, ch_axis)
    return dispatch(f, (x,), name="local_response_norm")


def spectral_norm(weight, weight_u, weight_v, dim=0, power_iters=1,
                  eps=1e-12, name=None):
    def f(w, u, v):
        wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        for _ in range(power_iters):
            v = wm.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = wm @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ wm @ v
        return w / sigma
    return dispatch(f, (_ensure(weight), _ensure(weight_u), _ensure(weight_v)),
                    name="spectral_norm")
