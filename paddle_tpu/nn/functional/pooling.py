"""Pooling functionals (reference: python/paddle/nn/functional/pooling.py).
All lower to lax.reduce_window."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...core.tensor import Tensor, dispatch


def _ensure(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in v)[:n] if len(v) >= n else \
            tuple(int(v[0]) for _ in range(n))
    return tuple(int(v) for _ in range(n))


def _pool_pad(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    p = padding if isinstance(padding, (list, tuple)) else [padding] * n
    p = [int(i) for i in p]
    if len(p) == n:
        return [(i, i) for i in p]
    if len(p) == 2 * n:
        return [(p[2 * i], p[2 * i + 1]) for i in range(n)]
    return [(p[0], p[0])] * n


def _reduce_window(v, init, op, window, strides, pads, ch_last, n):
    dims = (1,) + window + (1,) if ch_last else (1, 1) + window
    strd = (1,) + strides + (1,) if ch_last else (1, 1) + strides
    if isinstance(pads, str):
        pad_cfg = pads
    else:
        pad_cfg = ([(0, 0)] + list(pads) + [(0, 0)]) if ch_last \
            else [(0, 0), (0, 0)] + list(pads)
    return lax.reduce_window(v, init, op, dims, strd, pad_cfg)


def _max_pool(x, kernel_size, stride, padding, ceil_mode, return_mask,
              data_format, n, name):
    x = _ensure(x)
    ch_last = data_format.endswith("C")
    ks = _tuple(kernel_size, n)
    st = _tuple(stride, n) if stride is not None else ks
    pd = _pool_pad(padding, n)
    if ceil_mode and not isinstance(pd, str):
        spatial = x.shape[1:1 + n] if ch_last else x.shape[2:2 + n]
        pd = [(lo, hi + _ceil_extra(s, k, s2, lo + hi))
              for (lo, hi), s, k, s2 in zip(pd, spatial, ks, st)]

    def f(v):
        # -inf init => JAX recognises the max-pool pattern and provides the
        # reverse-mode rule (finfo.min would block autodiff)
        neg = (-jnp.inf if jnp.issubdtype(v.dtype, jnp.floating)
               else jnp.iinfo(v.dtype).min)
        out = _reduce_window(v, neg, lax.max, ks, st, pd, ch_last, n)
        if not return_mask:
            return out
        # index pooling: argmax over the window via same-window reduce on
        # linearised indices
        spatial = v.shape[1:1 + n] if ch_last else v.shape[2:2 + n]
        lin = jnp.arange(int(np.prod(spatial))).reshape(spatial)
        shape = ((1,) + spatial + (1,)) if ch_last else ((1, 1) + spatial)
        lin = jnp.broadcast_to(lin.reshape(shape), v.shape)

        def argmax_op(a, b):
            av, ai = a
            bv, bi = b
            take_b = bv > av
            return (jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai))
        dims = (1,) + ks + (1,) if ch_last else (1, 1) + ks
        strd = (1,) + st + (1,) if ch_last else (1, 1) + st
        pad_cfg = pd if isinstance(pd, str) else (
            ([(0, 0)] + list(pd) + [(0, 0)]) if ch_last
            else [(0, 0), (0, 0)] + list(pd))
        vals, idx = lax.reduce_window(
            (v, lin), (jnp.asarray(neg, v.dtype),
                       jnp.asarray(-1, lin.dtype)), argmax_op,
            dims, strd, pad_cfg)
        return vals, idx.astype(jnp.int32)
    if return_mask:
        return dispatch(f, (x,), name=name, multi_output=True)
    return dispatch(f, (x,), name=name)


def _ceil_extra(size, k, stride, pad_both):
    import math
    out_floor = (size + pad_both - k) // stride + 1
    out_ceil = math.ceil((size + pad_both - k) / stride) + 1
    return (out_ceil - out_floor) * stride


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NWC" if data_format in ("NLC",) else "NCW"
    return _max_pool(x, kernel_size, stride, padding, ceil_mode, return_mask,
                     df, 1, "max_pool1d")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _max_pool(x, kernel_size, stride, padding, ceil_mode, return_mask,
                     data_format, 2, "max_pool2d")


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _max_pool(x, kernel_size, stride, padding, ceil_mode, return_mask,
                     data_format, 3, "max_pool3d")


def _avg_pool(x, kernel_size, stride, padding, ceil_mode, exclusive,
              divisor_override, data_format, n, name):
    x = _ensure(x)
    ch_last = data_format.endswith("C")
    ks = _tuple(kernel_size, n)
    st = _tuple(stride, n) if stride is not None else ks
    pd = _pool_pad(padding, n)
    if ceil_mode and not isinstance(pd, str):
        spatial = x.shape[1:1 + n] if ch_last else x.shape[2:2 + n]
        pd = [(lo, hi + _ceil_extra(s, k, s2, lo + hi))
              for (lo, hi), s, k, s2 in zip(pd, spatial, ks, st)]

    def f(v):
        s = _reduce_window(v.astype(jnp.float32), 0.0, lax.add, ks, st, pd,
                           ch_last, n)
        if divisor_override:
            return (s / divisor_override).astype(v.dtype)
        if exclusive and not isinstance(pd, str):
            ones = jnp.ones_like(v, dtype=jnp.float32)
            cnt = _reduce_window(ones, 0.0, lax.add, ks, st, pd, ch_last, n)
            return (s / cnt).astype(v.dtype)
        return (s / float(np.prod(ks))).astype(v.dtype)
    return dispatch(f, (x,), name=name)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NWC" if data_format in ("NLC",) else "NCW"
    return _avg_pool(x, kernel_size, stride, padding, ceil_mode, exclusive,
                     None, df, 1, "avg_pool1d")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _avg_pool(x, kernel_size, stride, padding, ceil_mode, exclusive,
                     divisor_override, data_format, 2, "avg_pool2d")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _avg_pool(x, kernel_size, stride, padding, ceil_mode, exclusive,
                     divisor_override, data_format, 3, "avg_pool3d")


def _adaptive_out(in_size, out_size):
    # adaptive pooling boundaries (same math as reference's kernel)
    starts = (np.arange(out_size) * in_size) // out_size
    ends = -(-((np.arange(out_size) + 1) * in_size) // out_size)
    return starts, ends


def _adaptive_pool(x, output_size, data_format, n, op, name):
    x = _ensure(x)
    ch_last = data_format.endswith("C")
    os_ = _tuple(output_size, n)

    def f(v):
        spatial_off = 1 if ch_last else 2
        out = v
        for d in range(n):
            in_size = out.shape[spatial_off + d]
            o = os_[d]
            if o == in_size:
                continue
            starts, ends = _adaptive_out(in_size, o)
            slices = []
            for s, e in zip(starts, ends):
                sl = jnp.take(out, jnp.arange(s, e), axis=spatial_off + d)
                red = (jnp.max if op == "max" else jnp.mean)(
                    sl, axis=spatial_off + d, keepdims=True)
                slices.append(red)
            out = jnp.concatenate(slices, axis=spatial_off + d)
        return out
    return dispatch(f, (x,), name=name)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, "NCW", 1, "avg",
                          "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, data_format, 2, "avg",
                          "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, data_format, 3, "avg",
                          "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, "NCW", 1, "max",
                          "adaptive_max_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, "NCHW", 2, "max",
                          "adaptive_max_pool2d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, "NCDHW", 3, "max",
                          "adaptive_max_pool3d")


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    x = _ensure(x)
    p = float(norm_type)
    ks = _tuple(kernel_size, 1)
    st = _tuple(stride, 1) if stride is not None else ks
    pd = _pool_pad(padding, 1)

    def f(v):
        s = _reduce_window(jnp.abs(v.astype(jnp.float32)) ** p, 0.0, lax.add,
                           ks, st, pd, False, 1)
        return (s ** (1.0 / p)).astype(v.dtype)
    return dispatch(f, (x,), name="lp_pool1d")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    x = _ensure(x)
    p = float(norm_type)
    ks = _tuple(kernel_size, 2)
    st = _tuple(stride, 2) if stride is not None else ks
    pd = _pool_pad(padding, 2)

    def f(v):
        s = _reduce_window(jnp.abs(v.astype(jnp.float32)) ** p, 0.0, lax.add,
                           ks, st, pd, data_format.endswith("C"), 2)
        return (s ** (1.0 / p)).astype(v.dtype)
    return dispatch(f, (x,), name="lp_pool2d")


def _max_unpool(x, indices, kernel_size, stride, padding, output_size,
                data_format, n, name):
    """Scatter pooled values back to the positions recorded by
    ``return_mask`` (reference: ops.yaml unpool/unpool3d). ``indices``
    are flat per-(N, C) spatial indices, exactly what max_poolNd
    returns."""
    x = _ensure(x)
    idx = _ensure(indices)
    if data_format.endswith("C"):
        raise NotImplementedError("max_unpool: channels-last unsupported")
    ks = _tuple(kernel_size, n)
    st = _tuple(stride, n) if stride is not None else ks
    pd = _tuple(padding, n)
    if output_size is None:
        spatial = x.shape[2:2 + n]
        output_size = tuple((s - 1) * st[i] - 2 * pd[i] + ks[i]
                            for i, s in enumerate(spatial))
    else:
        output_size = tuple(output_size)[-n:]

    def f(v, iv):
        N, C = v.shape[0], v.shape[1]
        flat = v.reshape(N, C, -1)
        ifl = iv.reshape(N, C, -1).astype(jnp.int32)
        hw = int(np.prod(output_size))
        out = jnp.zeros((N, C, hw), v.dtype)
        out = out.at[jnp.arange(N)[:, None, None],
                     jnp.arange(C)[None, :, None], ifl].set(flat)
        return out.reshape((N, C) + output_size)
    return dispatch(f, (x, idx), name=name)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding,
                       output_size, data_format, 1, "max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding,
                       output_size, data_format, 2, "max_unpool2d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding,
                       output_size, data_format, 3, "max_unpool3d")


def _fractional_bins(in_size, out_size, u, pool_size):
    """Start/end indices per output cell (reference funcs/pooling.h
    FractionalRationalU/StartIndex/EndIndex)."""
    alpha = in_size / out_size
    if pool_size and pool_size > 0:
        uu = u
    else:
        base = in_size // out_size
        u_max1 = (base + 2) / alpha - 1
        u_max2 = (in_size + 1 - base) / alpha - (out_size - 1)
        uu = u * min(u_max1, u_max2)
    bins = []
    off = int(uu * alpha)
    for i in range(out_size):
        s = int((i + uu) * alpha) - off
        if pool_size and pool_size > 0:
            e = s + pool_size
        else:
            e = int((i + 1 + uu) * alpha) - off
        s = max(0, min(s, in_size - 1))
        e = max(s + 1, min(e, in_size))
        bins.append((s, e))
    return bins


def _fractional_max_pool(x, output_size, kernel_size, random_u,
                         return_mask, n, name):
    x = _ensure(x)
    if isinstance(output_size, int):
        output_size = (output_size,) * n
    ks = (None,) * n if kernel_size is None else (
        (kernel_size,) * n if isinstance(kernel_size, int)
        else tuple(kernel_size))
    if random_u is None:
        from ...core.random import next_key
        import jax as _jax
        random_u = float(_jax.random.uniform(next_key(), ()))
    assert 0.0 < random_u < 1.0, "random_u must be in (0, 1)"
    spatial = x.shape[2:2 + n]
    axes_bins = [
        _fractional_bins(spatial[a], output_size[a], random_u,
                         ks[a] or 0) for a in range(n)]

    def f(v):
        lin = jnp.arange(int(np.prod(spatial))).reshape(spatial)
        outs, idxs = [], []
        import itertools
        for cells in itertools.product(*[range(o) for o in output_size]):
            sl = (Ellipsis,) + tuple(
                slice(*axes_bins[a][cells[a]]) for a in range(n))
            patch = v[sl].reshape(v.shape[:2] + (-1,))
            outs.append(jnp.max(patch, axis=-1))
            if return_mask:
                win = lin[tuple(slice(*axes_bins[a][cells[a]])
                                for a in range(n))].reshape(-1)
                idxs.append(win[jnp.argmax(patch, axis=-1)])
        out = jnp.stack(outs, -1).reshape(v.shape[:2] + tuple(output_size))
        if not return_mask:
            return out
        idx = jnp.stack(idxs, -1).reshape(
            v.shape[:2] + tuple(output_size)).astype(jnp.int32)
        return out, idx
    if return_mask:
        return dispatch(f, (x,), name=name, multi_output=True)
    return dispatch(f, (x,), name=name)


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """reference: ops.yaml fractional_max_pool2d (funcs/pooling.h
    fractional index math); ``random_u`` fixes the pseudo-random grid,
    else one is drawn from the framework RNG."""
    return _fractional_max_pool(x, output_size, kernel_size, random_u,
                                return_mask, 2, "fractional_max_pool2d")


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    return _fractional_max_pool(x, output_size, kernel_size, random_u,
                                return_mask, 3, "fractional_max_pool3d")
