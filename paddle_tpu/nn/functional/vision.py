"""Vision functionals (reference: python/paddle/nn/functional/vision.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch


def _ensure(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def f(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = jnp.transpose(v, (0, 1, 4, 2, 5, 3))
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = jnp.transpose(v, (0, 1, 3, 2, 4, 5))
        return v.reshape(n, h * r, w * r, c // (r * r))
    return dispatch(f, (_ensure(x),), name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def f(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c, h // r, r, w // r, r)
            v = jnp.transpose(v, (0, 1, 3, 5, 2, 4))
            return v.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = v.shape
        v = v.reshape(n, h // r, r, w // r, r, c)
        v = jnp.transpose(v, (0, 1, 3, 2, 4, 5))
        return v.reshape(n, h // r, w // r, c * r * r)
    return dispatch(f, (_ensure(x),), name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def f(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, groups, c // groups, h, w)
            v = jnp.swapaxes(v, 1, 2)
            return v.reshape(n, c, h, w)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, groups, c // groups)
        v = jnp.swapaxes(v, 3, 4)
        return v.reshape(n, h, w, c)
    return dispatch(f, (_ensure(x),), name="channel_shuffle")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    if isinstance(out_shape, Tensor):
        out_shape = [int(s) for s in out_shape.numpy()]

    def f(th):
        n, _, h, w = out_shape
        if align_corners:
            xs = jnp.linspace(-1, 1, w)
            ys = jnp.linspace(-1, 1, h)
        else:
            xs = jnp.linspace(-1 + 1.0 / w, 1 - 1.0 / w, w)
            ys = jnp.linspace(-1 + 1.0 / h, 1 - 1.0 / h, h)
        gx, gy = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # [h, w, 3]
        return jnp.einsum("hwk,njk->nhwj", base, th)
    return dispatch(f, (_ensure(theta),), name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    def f(v, g):
        n, c, h, w = v.shape
        gx = g[..., 0]
        gy = g[..., 1]
        if align_corners:
            ix = (gx + 1) * (w - 1) / 2
            iy = (gy + 1) * (h - 1) / 2
        else:
            ix = ((gx + 1) * w - 1) / 2
            iy = ((gy + 1) * h - 1) / 2

        def sample(img, yy, xx):
            # img: [c, h, w]
            if padding_mode == "border":
                yy = jnp.clip(yy, 0, h - 1)
                xx = jnp.clip(xx, 0, w - 1)
            elif padding_mode == "reflection":
                yy = jnp.abs(jnp.mod(yy, 2 * (h - 1)))
                yy = jnp.where(yy > h - 1, 2 * (h - 1) - yy, yy)
                xx = jnp.abs(jnp.mod(xx, 2 * (w - 1)))
                xx = jnp.where(xx > w - 1, 2 * (w - 1) - xx, xx)
            valid = ((yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1))
            yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            out = img[:, yc, xc]
            return jnp.where(valid[None], out, 0.0)

        if mode == "nearest":
            out = jax.vmap(lambda img, yy, xx: sample(
                img, jnp.round(yy), jnp.round(xx)))(v, iy, ix)
            return out
        x0 = jnp.floor(ix)
        y0 = jnp.floor(iy)
        x1, y1 = x0 + 1, y0 + 1
        wa = (x1 - ix) * (y1 - iy)
        wb = (x1 - ix) * (iy - y0)
        wc = (ix - x0) * (y1 - iy)
        wd = (ix - x0) * (iy - y0)

        def bil(img, yy0, xx0, yy1, xx1, wa, wb, wc, wd):
            va = sample(img, yy0, xx0)
            vb = sample(img, yy1, xx0)
            vc = sample(img, yy0, xx1)
            vd = sample(img, yy1, xx1)
            return va * wa[None] + vb * wb[None] + vc * wc[None] + vd * wd[None]
        out = jax.vmap(bil)(v, y0, x0, y1, x1, wa, wb, wc, wd)
        return out
    return dispatch(f, (_ensure(x), _ensure(grid)), name="grid_sample")


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    def f(v):
        if data_format == "NHWC":
            v = jnp.transpose(v, (0, 3, 1, 2))
        nt, c, h, w = v.shape
        n = nt // seg_num
        v = v.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([v[:, 1:, :fold], jnp.zeros_like(
            v[:, :1, :fold])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(v[:, :1, fold:2 * fold]),
                                 v[:, :-1, fold:2 * fold]], axis=1)
        rest = v[:, :, 2 * fold:]
        out = jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out
    return dispatch(f, (_ensure(x),), name="temporal_shift")
