"""Weight initializers (reference: python/paddle/nn/initializer/)."""
from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ...core.random import next_key
from ...core.dtypes import convert_dtype

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain",
    "Bilinear", "set_global_initializer",
]


def calculate_gain(nonlinearity: str, param=None) -> float:
    recipes = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
        "conv3d_transpose": 1.0, "tanh": 5.0 / 3.0,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None
                                            else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    if nonlinearity not in recipes:
        raise ValueError(f"unsupported nonlinearity {nonlinearity}")
    return recipes[nonlinearity]


def _fans(shape: Sequence[int]):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: paddle layout [out_c, in_c, *spatial]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(tuple(shape), self.value, dtype=convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        d = convert_dtype(dtype)
        return (jax.random.normal(next_key(), tuple(shape),
                                  dtype=jnp.float32) * self.std
                + self.mean).astype(d)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        d = convert_dtype(dtype)
        z = jax.random.truncated_normal(next_key(), self.a, self.b,
                                        tuple(shape), dtype=jnp.float32)
        return (z * self.std + self.mean).astype(d)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        d = convert_dtype(dtype)
        return jax.random.uniform(next_key(), tuple(shape), dtype=jnp.float32,
                                  minval=self.low,
                                  maxval=self.high).astype(d)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return Uniform(-limit, limit)(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0,
                 nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return Normal(0.0, std)(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0,
                 nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return Uniform(-limit, limit)(shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        from ...core.tensor import to_value
        v = jnp.asarray(np.asarray(to_value(self.value)))
        return v.reshape(tuple(shape)).astype(convert_dtype(dtype))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        d = convert_dtype(dtype)
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        flat = jax.random.normal(next_key(), (max(rows, cols),
                                              min(rows, cols)),
                                 dtype=jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(d)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        d = convert_dtype(dtype)
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        spatial_center = tuple(s // 2 for s in shape[2:])
        per_group = oc // self.groups
        for g in range(self.groups):
            for i in range(min(per_group, ic)):
                out[(g * per_group + i, i) + spatial_center] = 1.0
        return jnp.asarray(out).astype(d)


class Bilinear(Initializer):
    """reference: nn/initializer/Bilinear — upsampling-kernel init for
    transposed convs (weight [C_out, C_in, k, k])."""

    def __call__(self, shape, dtype):
        import numpy as np
        if len(shape) < 3:
            raise ValueError("Bilinear init expects a conv weight rank>=3")
        k = shape[-1]
        factor = (k + 1) // 2
        center = factor - 1 if k % 2 == 1 else factor - 0.5
        og = np.ogrid[tuple(slice(0, s) for s in shape[2:])]
        filt = np.ones(shape[2:], np.float64)
        for g in og:
            filt = filt * (1 - np.abs(g - center) / factor)
        w = np.zeros(shape, np.float64)
        w[...] = filt
        import jax.numpy as jnp
        return jnp.asarray(w, dtype)


def set_global_initializer(weight_init, bias_init=None):
    """reference: nn/initializer/set_global_initializer — default
    initializers for subsequently-created parameters; pass None, None
    to reset."""
    _GLOBAL_INIT[0] = weight_init
    _GLOBAL_INIT[1] = bias_init


_GLOBAL_INIT = [None, None]
