from . import layers, common, activation, conv, norm, pooling, loss  # noqa
from . import transformer, rnn  # noqa: F401
