"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["ReLU", "ReLU6", "GELU", "Sigmoid", "Tanh", "Softmax",
           "LogSoftmax", "LeakyReLU", "ELU", "CELU", "SELU", "Silu", "Swish",
           "Mish", "Hardtanh", "Hardshrink", "Softshrink", "Hardsigmoid",
           "Hardswish", "Softplus", "Softsign", "LogSigmoid", "Tanhshrink",
           "ThresholdedReLU", "Maxout", "PReLU", "RReLU", "GLU", "Softmax2D"]


def _simple(name, fn_name, **fixed):
    def __init__(self, name=None, **kwargs):
        Layer.__init__(self)
        self._kwargs = {**fixed, **kwargs}

    def forward(self, x):
        return getattr(F, fn_name)(x, **self._kwargs)

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


ReLU = _simple("ReLU", "relu")
ReLU6 = _simple("ReLU6", "relu6")
Sigmoid = _simple("Sigmoid", "sigmoid")
Tanh = _simple("Tanh", "tanh")
Silu = _simple("Silu", "silu")
Swish = _simple("Swish", "swish")
Mish = _simple("Mish", "mish")
Hardswish = _simple("Hardswish", "hardswish")
Softsign = _simple("Softsign", "softsign")
LogSigmoid = _simple("LogSigmoid", "logsigmoid")
Tanhshrink = _simple("Tanhshrink", "tanhshrink")


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return F.gelu(x, approximate=self.approximate)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self.axis)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.elu(x, self.alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.celu(x, self.alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554804934193349852946,
                 alpha=1.6732632423543772848170429916717, name=None):
        super().__init__()
        self.scale = scale
        self.alpha = alpha

    def forward(self, x):
        return F.selu(x, self.scale, self.alpha)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F.hardtanh(x, self.min, self.max)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self.threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self.threshold)


class Hardsigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.hardsigmoid(x)


class Softplus(Layer):
    def __init__(self, beta=1, threshold=20, name=None):
        super().__init__()
        self.beta, self.threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self.beta, self.threshold)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, value=0.0, name=None):
        super().__init__()
        self.threshold, self.value = threshold, value

    def forward(self, x):
        import jax.numpy as jnp
        from ...core.tensor import dispatch
        return dispatch(lambda v: jnp.where(v > self.threshold, v,
                                            self.value),
                        (x,), name="thresholded_relu")


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self.data_format)


class RReLU(Layer):
    def __init__(self, lower=1. / 8., upper=1. / 3., name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, self.axis)


class Softmax2D(Layer):
    """reference: nn/layer/activation.py Softmax2D — softmax over the
    channel dim of NCHW inputs."""

    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        assert len(x.shape) in (3, 4), "Softmax2D expects 3D/4D input"
        return F.softmax(x, axis=-3)
