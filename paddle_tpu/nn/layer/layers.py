"""``Layer``: module base class.

TPU-native re-design of reference ``paddle.nn.Layer``
(python/paddle/nn/layer/layers.py:353): same surface — parameter/buffer/
sublayer registries, hooks, ``state_dict``/``set_state_dict``, ``train/eval``,
``to()`` — but the parameter store is a pytree so any Layer can be
functionalised for ``jax.jit``/``jax.grad``/``pjit`` via
``Layer.functional()`` (used by jit.to_static and the distributed trainers).
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np
import jax

from ...core.tensor import Tensor, no_grad, to_value
from ...core.dtypes import convert_dtype, get_default_dtype
from ...framework import Parameter, ParamAttr
from .. import initializer as I

__all__ = ["Layer", "Sequential", "LayerList", "ParameterList", "LayerDict"]

# per-name-scope instance counters for paddle-style parameter names
_scope_counters: Dict[str, int] = {}


class HookRemoveHelper:
    def __init__(self, hooks, key):
        self._hooks = hooks
        self._key = key

    def remove(self):
        self._hooks.pop(self._key, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype=None):
        self.training = True
        self._dtype = convert_dtype(dtype) if dtype else get_default_dtype()
        self._parameters: Dict[str, Optional[Parameter]] = \
            collections.OrderedDict()
        self._buffers: Dict[str, Optional[Tensor]] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._sub_layers: Dict[str, Optional["Layer"]] = \
            collections.OrderedDict()
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = \
            collections.OrderedDict()
        self._hook_id = [0]
        self._name_scope = name_scope or self.__class__.__name__.lower()
        self._casted_dtype = None

    # -- construction --------------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None,
                         is_bias=False, default_initializer=None
                         ) -> Optional[Parameter]:
        """reference: layers.py create_parameter."""
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = convert_dtype(dtype) if dtype else self._dtype
        # precedence (reference set_global_initializer semantics): the
        # per-param attr wins; else the global initializer overrides the
        # layer's built-in default; else framework fallback
        init = attr.initializer \
            or I._GLOBAL_INIT[1 if is_bias else 0] \
            or default_initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierUniform()
        from ...framework import _LAZY_INIT
        if _LAZY_INIT[0]:
            # LazyGuard active: defer the initializer (its compute + RNG
            # draw); Parameter.initialize() materializes later
            import jax.numpy as jnp
            p = Parameter(jnp.zeros(tuple(shape), dtype),
                          name=attr.name or self._auto_param_name(is_bias),
                          trainable=attr.trainable)
            p._lazy_spec = (init, shape, dtype)
        else:
            p = Parameter(init(shape, dtype),
                          name=attr.name or self._auto_param_name(is_bias),
                          trainable=attr.trainable)
        p._param_attr = attr
        return p

    def _auto_param_name(self, is_bias: bool) -> str:
        """Paddle-style default name "linear_0.w_0" / "linear_0.b_0" so
        name-based policies (AdamW apply_decay_param_fun, need_clip
        filters) have something meaningful to match on (reference:
        unique_name generator in python/paddle/base/unique_name.py)."""
        scope = self._name_scope
        idx = getattr(self, "_unique_scope_idx", None)
        if idx is None:
            idx = _scope_counters[scope] = _scope_counters.get(scope, -1) + 1
            self._unique_scope_idx = idx
        kind = "b" if is_bias else "w"
        k = f"_n_{kind}"
        n = self.__dict__[k] = self.__dict__.get(k, -1) + 1
        return f"{scope}_{idx}.{kind}_{n}"

    def create_tensor(self, name=None, persistable=False, dtype=None):
        import jax.numpy as jnp
        t = Tensor(jnp.zeros((), dtype=convert_dtype(dtype)
                             if dtype else self._dtype), name=name)
        t.persistable = persistable
        return t

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        object.__getattribute__(self, "_parameters")[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: Optional["Layer"]):
        object.__getattribute__(self, "_sub_layers")[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        self._buffers[name] = tensor
        if tensor is not None:
            # mark the tensor itself (reference: buffers are persistable
            # Variables) — to_static's discovery pass keys on this flag
            tensor.persistable = persistable
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- attribute routing ---------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        sublayers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning "
                                   "parameters")
            for registry in (sublayers, buffers):
                if registry is not None:
                    registry.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if sublayers is None:
                raise RuntimeError("call Layer.__init__ before assigning "
                                   "sublayers")
            for registry in (params, buffers):
                if registry is not None:
                    registry.pop(name, None)
            sublayers[name] = value
        elif buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
            else:
                object.__setattr__(self, name, value)
        elif params is not None and name in params and value is None:
            params[name] = None
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        d = self.__dict__
        for registry in ("_parameters", "_buffers", "_sub_layers"):
            reg = d.get(registry)
            if reg is not None and name in reg:
                return reg[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for registry in (self._parameters, self._buffers, self._sub_layers):
            if name in registry:
                del registry[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._buffers) + list(self._sub_layers)

    # -- call ----------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            o = hook(self, inputs, outputs)
            if o is not None:
                outputs = o
        return outputs

    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_id[0] += 1
        self._forward_pre_hooks[self._hook_id[0]] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id[0])

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_id[0] += 1
        self._forward_post_hooks[self._hook_id[0]] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id[0])

    # -- traversal -----------------------------------------------------------
    def named_sublayers(self, prefix="", include_self=False, layers_set=None
                        ) -> Iterator[Tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None or id(layer) in layers_set:
                continue
            p = f"{prefix}.{name}" if prefix else name
            yield from layer.named_sublayers(prefix=p, include_self=True,
                                             layers_set=layers_set)

    def sublayers(self, include_self=False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        seen = set()
        for name, layer in self._sub_layers.items():
            if layer is not None and id(layer) not in seen:
                seen.add(id(layer))
                yield name, layer

    def named_parameters(self, prefix="", include_sublayers=True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        layers = self.named_sublayers(prefix=prefix, include_self=True) \
            if include_sublayers else [(prefix, self)]
        for lp, layer in layers:
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{lp}.{name}" if lp else name), p

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in
                self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True
                      ) -> Iterator[Tuple[str, Tensor]]:
        seen = set()
        layers = self.named_sublayers(prefix=prefix, include_self=True) \
            if include_sublayers else [(prefix, self)]
        for lp, layer in layers:
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{lp}.{name}" if lp else name), b

    def buffers(self, include_sublayers=True) -> List[Tensor]:
        return [b for _, b in
                self.named_buffers(include_sublayers=include_sublayers)]

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # -- modes ---------------------------------------------------------------
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # -- state ---------------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True
                   ) -> Dict[str, Tensor]:
        dest = destination if destination is not None else \
            collections.OrderedDict()
        for name, p in self.named_parameters(
                prefix=structured_name_prefix.rstrip("."),
                include_sublayers=include_sublayers):
            dest[name] = p
        for name, b in self.named_buffers(
                prefix=structured_name_prefix.rstrip("."),
                include_sublayers=include_sublayers):
            shortname = name.rsplit(".", 1)[-1]
            owner = self
            if "." in name:
                # find owner to check persistability
                for ln, l in self.named_sublayers(include_self=True):
                    if ln == name.rsplit(".", 1)[0]:
                        owner = l
                        break
            if shortname not in owner._non_persistable_buffer_names:
                dest[name] = b
        return dest

    @no_grad()
    def set_state_dict(self, state_dict, use_structured_name=True):
        """reference: layers.py set_state_dict; returns (missing, unexpected)."""
        own = self.state_dict()
        missing, matched = [], set()
        for name, target in own.items():
            if name in state_dict:
                src = state_dict[name]
                v = src.numpy() if isinstance(src, Tensor) else np.asarray(src)
                if list(v.shape) != list(target.shape):
                    raise ValueError(
                        f"shape mismatch for {name}: checkpoint "
                        f"{list(v.shape)} vs layer {list(target.shape)}")
                new_v = jax.numpy.asarray(v, dtype=target._value.dtype)
                # preserve the target's device/sharding (TP/PP placement)
                old = target._value
                if isinstance(old, jax.Array) and hasattr(old, "sharding"):
                    new_v = jax.device_put(new_v, old.sharding)
                target._replace_value(new_v)
                matched.add(name)
            else:
                missing.append(name)
        unexpected = [k for k in state_dict if k not in own]
        return missing, unexpected

    load_dict = set_state_dict

    # -- dtype/device movement ----------------------------------------------
    def _transform(self, fn):
        with no_grad():
            for l in self.sublayers(include_self=True):
                for k, p in list(l._parameters.items()):
                    if p is not None:
                        p._replace_value(fn(p._value))
                        if p.grad is not None:
                            p.grad._replace_value(fn(p.grad._value))
                for k, b in list(l._buffers.items()):
                    if b is not None:
                        b._replace_value(fn(b._value))
        return self

    def to(self, device=None, dtype=None, blocking=None):
        def fn(v):
            if device is not None:
                from ...device import _str_to_place, Place
                p = device if isinstance(device, Place) else \
                    _str_to_place(str(device))
                v = jax.device_put(v, p.jax_device)
            if dtype is not None and jax.numpy.issubdtype(
                    v.dtype, jax.numpy.floating):
                v = v.astype(convert_dtype(dtype))
            return v
        if dtype is not None:
            self._dtype = convert_dtype(dtype)
        return self._transform(fn)

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # -- functionalisation (TPU-native; no reference analog needed) ----------
    def functional(self):
        """Return ``(pure_fn, params, buffers)`` where
        ``pure_fn(params, buffers, *args, **kwargs) -> (out, new_buffers)``
        is jit/grad/pjit-safe. ``params`` and ``buffers`` are flat
        name->value dicts of raw jax arrays."""
        param_objs = dict(self.named_parameters())
        buffer_objs = dict(self.named_buffers())
        params = {k: to_value(v) for k, v in param_objs.items()}
        buffers = {k: to_value(v) for k, v in buffer_objs.items()}

        def pure_fn(params, buffers, *args, **kwargs):
            saved = {}
            for k, obj in param_objs.items():
                saved[k] = obj._value
                obj._value = params[k]
            saved_b = {}
            for k, obj in buffer_objs.items():
                saved_b[k] = obj._value
                obj._value = buffers[k]
            try:
                wrapped = [Tensor(a, stop_gradient=True)
                           if isinstance(a, (jax.Array, jax.core.Tracer))
                           else a for a in args]
                out = self(*wrapped, **kwargs)
                new_buffers = {k: obj._value for k, obj in buffer_objs.items()}
                out_vals = jax.tree_util.tree_map(
                    lambda t: to_value(t) if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda t: isinstance(t, Tensor))
                return out_vals, new_buffers
            finally:
                for k, obj in param_objs.items():
                    obj._value = saved[k]
                for k, obj in buffer_objs.items():
                    obj._value = saved_b[k]

        return pure_fn, params, buffers

    def _sync_buffers(self, new_buffers):
        for k, obj in self.named_buffers():
            if k in new_buffers:
                obj._value = new_buffers[k]

    # -- misc ----------------------------------------------------------------
    def full_name(self):
        return self._name_scope

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            mod_str = repr(layer)
            mod_str = _addindent(mod_str, 2)
            lines.append(f"({name}): {mod_str}")
        main = self.__class__.__name__ + "("
        if extra:
            main += extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"


def _addindent(s, n):
    lines = s.split("\n")
    if len(lines) == 1:
        return s
    return lines[0] + "\n" + "\n".join(" " * n + l for l in lines[1:])


class Sequential(Layer):
    """reference: python/paddle/nn/layer/container.py Sequential."""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0],
                                           collections.OrderedDict):
            for name, layer in layers[0].items():
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                if isinstance(layer, tuple):
                    self.add_sublayer(layer[0], layer[1])
                else:
                    self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers.keys())
        return self._sub_layers[keys[idx]]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return self._sub_layers[str(idx if idx >= 0 else
                                    idx + len(self))]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return self._parameters[str(idx if idx >= 0 else idx + len(self))]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self)), parameter)
        return self


class ParameterDict(Layer):
    """reference: nn/layer/container.py ParameterDict."""

    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            self.update(parameters)

    def __getitem__(self, key):
        return self._parameters[key]

    def __setitem__(self, key, parameter):
        self.add_parameter(key, parameter)

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters)

    def __contains__(self, key):
        return key in self._parameters

    def keys(self):
        return self._parameters.keys()

    def values(self):
        return self._parameters.values()

    def items(self):
        return self._parameters.items()

    def update(self, parameters):
        items = parameters.items() if hasattr(parameters, "items") \
            else parameters
        for k, p in items:
            self.add_parameter(k, p)
        return self


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()

    def update(self, sublayers):
        items = sublayers.items() if isinstance(sublayers, dict) else sublayers
        for k, v in items:
            self[k] = v
        return self

    def clear(self):
        self._sub_layers.clear()

    def pop(self, key):
        layer = self._sub_layers.pop(key)
        return layer
