"""Loss layers (reference: python/paddle/nn/layer/loss.py)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = ["CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
           "BCEWithLogitsLoss", "KLDivLoss", "SmoothL1Loss",
           "MarginRankingLoss", "CTCLoss", "HingeEmbeddingLoss",
           "CosineEmbeddingLoss", "TripletMarginLoss", "PoissonNLLLoss",
           "GaussianNLLLoss", "MultiLabelSoftMarginLoss", "SoftMarginLoss", "MultiMarginLoss",
           "TripletMarginWithDistanceLoss", "HSigmoidLoss",
           "AdaptiveLogSoftmaxWithLoss", "RNNTLoss"]


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(input, label, self.weight, self.ignore_index,
                               self.reduction, self.soft_label, self.axis,
                               self.use_softmax, self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight,
                                      self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__()
        self.reduction = reduction
        self.log_target = log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin,
                                      self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.kw = dict(margin=margin, p=p, epsilon=epsilon, swap=swap,
                       reduction=reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, **self.kw)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.kw = dict(log_input=log_input, full=full, epsilon=epsilon,
                       reduction=reduction)

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, **self.kw)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self.kw = dict(full=full, epsilon=epsilon, reduction=reduction)

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, **self.kw)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class MultiMarginLoss(Layer):
    """reference: nn/layer/loss.py MultiMarginLoss."""

    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p, self.margin = p, margin
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, p=self.p,
                                   margin=self.margin, weight=self.weight,
                                   reduction=self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    """reference: nn/layer/loss.py TripletMarginWithDistanceLoss."""

    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin, self.swap = margin, swap
        self.reduction = reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative,
            distance_function=self.distance_function, margin=self.margin,
            swap=self.swap, reduction=self.reduction)


class HSigmoidLoss(Layer):
    """reference: nn/layer/loss.py HSigmoidLoss — holds the tree-node
    weight [num_classes-1, D] (+bias) and applies F.hsigmoid_loss."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if num_classes < 2:
            raise ValueError("num_classes must be >= 2")
        self.num_classes = num_classes
        self.is_custom = is_custom
        n_nodes = num_classes - 1
        self.weight = self.create_parameter([n_nodes, feature_size],
                                            attr=weight_attr)
        self.bias = self.create_parameter([n_nodes], attr=bias_attr,
                                          is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes,
                               self.weight, bias=self.bias,
                               path_table=path_table,
                               path_code=path_code)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """reference: nn/layer/loss.py AdaptiveLogSoftmaxWithLoss — head +
    per-cluster down-projected tails (Grave et al.)."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        cutoffs = [int(c) for c in cutoffs]
        if cutoffs != sorted(set(cutoffs)) or not cutoffs or \
                cutoffs[-1] > n_classes:
            raise ValueError(f"invalid cutoffs {cutoffs}")
        if cutoffs[-1] == n_classes:
            cutoffs = cutoffs[:-1]
        self.cutoffs = cutoffs + [n_classes]
        self.n_clusters = len(self.cutoffs) - 1
        head_size = cutoffs[0] + self.n_clusters
        self.head_weight = self.create_parameter([in_features, head_size])
        self.head_bias = self.create_parameter([head_size], is_bias=True) \
            if head_bias else None
        self.tail_weights = []
        for i in range(self.n_clusters):
            hsz = max(int(in_features / (div_value ** (i + 1))), 1)
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            proj = self.create_parameter([in_features, hsz])
            cls = self.create_parameter([hsz, osz])
            self.add_parameter(f"tail_{i}_proj", proj)
            self.add_parameter(f"tail_{i}_cls", cls)
            self.tail_weights.append([proj, cls])

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            self.cutoffs, head_bias=self.head_bias)


class RNNTLoss(Layer):
    """reference: nn/layer/loss.py RNNTLoss."""

    def __init__(self, blank=0, fastemit_lambda=0.0, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=self.blank,
                           fastemit_lambda=self.fastemit_lambda,
                           reduction=self.reduction)
