"""Norm layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor
from ...framework import ParamAttr
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "SyncBatchNorm", "LayerNorm", "RMSNorm", "GroupNorm",
           "InstanceNorm1D", "InstanceNorm2D", "InstanceNorm3D",
           "LocalResponseNorm", "SpectralNorm"]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)
        import jax.numpy as jnp
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return (f"num_features={self._num_features}, "
                f"momentum={self._momentum}, epsilon={self._epsilon}")


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCHW" if data_format == "NCDHW"
                         else data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batchnorm. Inside pjit/shard_map the batch axis is a mesh
    axis and the mean/var reductions become psums automatically under GSPMD;
    this class exists for API parity with reference
    python/paddle/nn/layer/norm.py SyncBatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for l in layer.sublayers(include_self=True):
            for name, sub in list(l._sub_layers.items()):
                if isinstance(sub, _BatchNormBase) and \
                        not isinstance(sub, SyncBatchNorm):
                    new = SyncBatchNorm(sub._num_features, sub._momentum,
                                        sub._epsilon,
                                        data_format=sub._data_format)
                    new.weight = sub.weight
                    new.bias = sub.bias
                    new._mean = sub._mean
                    new._variance = sub._variance
                    l._sub_layers[name] = new
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = (None if weight_attr is False else
                       self.create_parameter(
                           self._normalized_shape, attr=weight_attr,
                           default_initializer=I.Constant(1.0)))
        self.bias = (None if bias_attr is False else
                     self.create_parameter(self._normalized_shape,
                                           attr=bias_attr, is_bias=True))

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """TPU-native first-class RMSNorm (reference only has the fused op
    python/paddle/incubate/nn/functional/fused_rms_norm.py)."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            list(normalized_shape), attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = (None if weight_attr is False else
                       self.create_parameter(
                           [num_channels], attr=weight_attr,
                           default_initializer=I.Constant(1.0)))
        self.bias = (None if bias_attr is False else
                     self.create_parameter([num_channels], attr=bias_attr,
                                           is_bias=True))

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon,
                               data_format=self._data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            [h], default_initializer=I.Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            [w], default_initializer=I.Normal(0.0, 1.0))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        return F.spectral_norm(weight, self.weight_u, self.weight_v,
                               self._dim, self._power_iters, self._epsilon)
