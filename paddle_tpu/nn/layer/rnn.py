"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py).

TPU-native design: the time loop is a ``lax.scan`` inside one dispatch —
XLA compiles the whole unrolled-free recurrence (the reference uses cuDNN RNN
descriptors, paddle/phi/kernels/gpu/rnn_kernel.cu; scan is the TPU analog).
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch
from .. import functional as F
from .. import initializer as I
from .layers import Layer, LayerList

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "SimpleRNN",
           "LSTM", "GRU", "BiRNN", "RNNCellBase", "BeamSearchDecoder", "dynamic_decode"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ...tensor.creation import full
        b = batch_ref.shape[batch_dim_idx]
        return full([b, self.hidden_size], init_value,
                    dtype=dtype or "float32")


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter([hidden_size], bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=u)
        self.bias_hh = self.create_parameter([hidden_size], bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, dtype=inputs.dtype)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def f(x, h, wi, wh, bi, bh):
            out = act(x @ wi.T + bi + h @ wh.T + bh)
            return out
        out = dispatch(f, (inputs, states, self.weight_ih, self.weight_hh,
                           self.bias_ih, self.bias_hh), name="rnn_cell")
        return out, out

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=0, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter([4 * hidden_size], bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=u)
        self.bias_hh = self.create_parameter([4 * hidden_size], bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs, dtype=inputs.dtype)
            states = (h, h)
        h0, c0 = states

        def f(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, fgt, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            fgt = jax.nn.sigmoid(fgt)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c_new = fgt * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new
        h1, c1 = dispatch(f, (inputs, h0, c0, self.weight_ih, self.weight_hh,
                              self.bias_ih, self.bias_hh), name="lstm_cell",
                          multi_output=True)
        return h1, (h1, c1)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter([3 * hidden_size], bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=u)
        self.bias_hh = self.create_parameter([3 * hidden_size], bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, dtype=inputs.dtype)

        def f(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
            h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(i_r + h_r)
            z = jax.nn.sigmoid(i_z + h_z)
            n = jnp.tanh(i_n + r * h_n)
            return (1.0 - z) * n + z * h
        out = dispatch(f, (inputs, states, self.weight_ih, self.weight_hh,
                           self.bias_ih, self.bias_hh), name="gru_cell")
        return out, out

    @property
    def state_shape(self):
        return (self.hidden_size,)


class RNN(Layer):
    """Wraps a cell into a scan over time
    (reference: python/paddle/nn/layer/rnn.py RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import flip, transpose, unbind, stack
        x = inputs
        if not self.time_major:
            x = transpose(x, [1, 0, 2])
        if self.is_reverse:
            x = flip(x, [0])
        T = x.shape[0]
        states = initial_states
        outs = []
        for t in range(T):
            o, states = self.cell(x[t], states)
            outs.append(o)
        out = stack(outs, axis=0)
        if self.is_reverse:
            out = flip(out, [0])
        if not self.time_major:
            out = transpose(out, [1, 0, 2])
        return out, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import concat
        s_fw, s_bw = (initial_states if initial_states is not None
                      else (None, None))
        o_fw, s_fw = self.rnn_fw(inputs, s_fw)
        o_bw, s_bw = self.rnn_bw(inputs, s_bw)
        return concat([o_fw, o_bw], axis=-1), (s_fw, s_bw)


class _RNNBase(Layer):
    """Multi-layer (bi)directional RNN driven by a single lax.scan per
    layer/direction — the whole stack compiles to one XLA loop."""

    MODE = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        num_dir = 2 if self.bidirect else 1
        self.num_directions = num_dir
        gate_mult = {"RNN_TANH": 1, "RNN_RELU": 1, "LSTM": 4, "GRU": 3}[
            self.MODE]
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self._all_weights = []
        for layer in range(num_layers):
            for d in range(num_dir):
                in_sz = input_size if layer == 0 else hidden_size * num_dir
                suffix = "_reverse" if d == 1 else ""
                wih = self.create_parameter([gate_mult * hidden_size, in_sz],
                                            weight_ih_attr,
                                            default_initializer=u)
                whh = self.create_parameter(
                    [gate_mult * hidden_size, hidden_size], weight_hh_attr,
                    default_initializer=u)
                bih = self.create_parameter([gate_mult * hidden_size],
                                            bias_ih_attr, is_bias=True,
                                            default_initializer=u)
                bhh = self.create_parameter([gate_mult * hidden_size],
                                            bias_hh_attr, is_bias=True,
                                            default_initializer=u)
                self.add_parameter(f"weight_ih_l{layer}{suffix}", wih)
                self.add_parameter(f"weight_hh_l{layer}{suffix}", whh)
                self.add_parameter(f"bias_ih_l{layer}{suffix}", bih)
                self.add_parameter(f"bias_hh_l{layer}{suffix}", bhh)
                self._all_weights.append((wih, whh, bih, bhh))

    def _cell_step(self, mode):
        if mode in ("RNN_TANH", "RNN_RELU"):
            act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu

            def step(carry, x, wi, wh, bi, bh):
                h = carry[0]
                h_new = act(x @ wi.T + bi + h @ wh.T + bh)
                return (h_new,), h_new
            return step
        if mode == "LSTM":
            def step(carry, x, wi, wh, bi, bh):
                h, c = carry
                gates = x @ wi.T + bi + h @ wh.T + bh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                c_new = jax.nn.sigmoid(f) * c + \
                    jax.nn.sigmoid(i) * jnp.tanh(g)
                h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
                return (h_new, c_new), h_new
            return step

        def step(carry, x, wi, wh, bi, bh):  # GRU
            h = carry[0]
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
            h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(i_r + h_r)
            z = jax.nn.sigmoid(i_z + h_z)
            n = jnp.tanh(i_n + r * h_n)
            h_new = (1.0 - z) * n + z * h
            return (h_new,), h_new
        return step

    def forward(self, inputs, initial_states=None, sequence_length=None):
        mode = self.MODE
        num_dir = self.num_directions
        nl = self.num_layers
        hs = self.hidden_size
        step = self._cell_step(mode)
        n_state = 2 if mode == "LSTM" else 1
        weights = [w for tpl in self._all_weights for w in tpl]

        init_given = initial_states is not None
        init_tensors = []
        if init_given:
            init_tensors = list(initial_states) if isinstance(
                initial_states, (tuple, list)) else [initial_states]

        def f(x, *flat):
            ws = flat[:len(weights)]
            inits = flat[len(weights):]
            if not self.time_major:
                x = jnp.swapaxes(x, 0, 1)
            T, B = x.shape[0], x.shape[1]
            if inits:
                init_hs = [jnp.swapaxes(i, 0, 0) for i in inits]
            out = x
            final_states = []
            wi_idx = 0
            for layer in range(nl):
                dir_outs = []
                for d in range(num_dir):
                    wi, wh, bi, bh = ws[4 * wi_idx: 4 * wi_idx + 4]
                    wi_idx += 1
                    if inits:
                        carry = tuple(
                            inits[s][layer * num_dir + d]
                            for s in range(n_state))
                    else:
                        carry = tuple(
                            jnp.zeros((B, hs), dtype=x.dtype)
                            for _ in range(n_state))
                    seq = jnp.flip(out, 0) if d == 1 else out

                    def scan_fn(c, xt, wi=wi, wh=wh, bi=bi, bh=bh):
                        return step(c, xt, wi, wh, bi, bh)
                    carry, ys = jax.lax.scan(scan_fn, carry, seq)
                    if d == 1:
                        ys = jnp.flip(ys, 0)
                    dir_outs.append(ys)
                    final_states.append(carry)
                out = jnp.concatenate(dir_outs, axis=-1) if num_dir == 2 \
                    else dir_outs[0]
            # final states: [n_state][num_layers*num_dir, B, hs]
            finals = []
            for s in range(n_state):
                finals.append(jnp.stack([fs[s] for fs in final_states],
                                        axis=0))
            if not self.time_major:
                out = jnp.swapaxes(out, 0, 1)
            return tuple([out] + finals)
        args = (inputs,) + tuple(weights) + tuple(init_tensors)
        outs = dispatch(f, args, name=f"rnn_{mode.lower()}",
                        multi_output=True)
        out = outs[0]
        if mode == "LSTM":
            return out, (outs[1], outs[2])
        return out, outs[1]


class SimpleRNN(_RNNBase):
    MODE = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        self.MODE = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class LSTM(_RNNBase):
    MODE = "LSTM"


class GRU(_RNNBase):
    MODE = "GRU"


class BeamSearchDecoder:
    """reference: nn/decode.py BeamSearchDecoder — beam search over an
    RNN cell: expand beam_size x vocab candidates per step, keep the
    top beam_size by accumulated log-prob, track parent beams for
    backtracking. Eager implementation (decode loops are host-driven in
    dygraph; the compiled generate path lives in inference/generation).
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # helpers over raw values -------------------------------------------
    @staticmethod
    def _tile_beam(v, beam):
        v = np.asarray(v)
        return np.repeat(v[:, None], beam, axis=1).reshape(
            (-1,) + v.shape[1:])

    def initialize(self, initial_states):
        from ...core.tensor import Tensor, to_value
        states = jax.tree_util.tree_map(
            lambda t: Tensor(self._tile_beam(
                np.asarray(to_value(t)), self.beam_size)),
            initial_states,
            is_leaf=lambda t: isinstance(t, Tensor))
        leaves = jax.tree_util.tree_leaves(
            initial_states, is_leaf=lambda t: isinstance(t, Tensor))
        batch = np.asarray(to_value(leaves[0])).shape[0]
        ids = np.full((batch * self.beam_size,), self.start_token,
                      np.int64)
        # only beam 0 live at t=0 (others -inf so the first top-k
        # doesn't pick duplicates)
        log_probs = np.full((batch, self.beam_size), -1e30, np.float32)
        log_probs[:, 0] = 0.0
        finished = np.zeros((batch, self.beam_size), bool)
        return ids, states, log_probs, finished

    def step(self, ids, states, log_probs, finished):
        from ...core.tensor import Tensor, to_value
        batch = log_probs.shape[0]
        beam, K = self.beam_size, self.beam_size
        inp = Tensor(ids) if self.embedding_fn is None \
            else self.embedding_fn(Tensor(ids))
        out, new_states = self.cell(inp, states)
        logits = out if self.output_fn is None else self.output_fn(out)
        lv = np.asarray(to_value(logits), np.float32)   # [B*beam, V]
        v = lv.shape[-1]
        step_lp = lv - np.log(np.exp(lv - lv.max(-1, keepdims=True))
                              .sum(-1, keepdims=True)) \
            - lv.max(-1, keepdims=True)
        step_lp = step_lp.reshape(batch, beam, v)
        # finished beams only extend with end_token at no cost
        fin_mask = np.full((v,), -1e30, np.float32)
        fin_mask[self.end_token] = 0.0
        step_lp = np.where(finished[:, :, None], fin_mask[None, None],
                           step_lp)
        total = log_probs[:, :, None] + step_lp        # [B, beam, V]
        flat = total.reshape(batch, beam * v)
        top = np.argsort(-flat, axis=1)[:, :K]
        new_lp = np.take_along_axis(flat, top, 1)
        parent = top // v                               # [B, K]
        token = top % v
        # gather states along the beam dim
        gather = (np.arange(batch)[:, None] * beam + parent).reshape(-1)
        new_states = jax.tree_util.tree_map(
            lambda t: Tensor(np.asarray(to_value(t))[gather]),
            new_states, is_leaf=lambda t: isinstance(t, Tensor))
        new_finished = np.take_along_axis(finished, parent, 1) | \
            (token == self.end_token)
        return (token.reshape(-1).astype(np.int64), new_states,
                new_lp, new_finished, parent)


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """reference: nn/decode.py dynamic_decode — drive a decoder until
    every beam finishes or ``max_step_num``. Returns (ids [B, beam, T],
    scores [B, beam]) (+ lengths)."""
    from ...core.tensor import Tensor
    ids, states, log_probs, finished = decoder.initialize(inits)
    max_steps = max_step_num or 256
    batch = log_probs.shape[0]
    beam = decoder.beam_size
    tokens_hist, parents_hist = [], []
    for _ in range(max_steps):
        ids, states, log_probs, finished, parent = decoder.step(
            ids, states, log_probs, finished)
        tokens_hist.append(ids.reshape(batch, beam))
        parents_hist.append(parent)
        if bool(finished.all()):
            break
    # backtrack parent pointers into full sequences
    T = len(tokens_hist)
    seqs = np.zeros((batch, beam, T), np.int64)
    beam_idx = np.tile(np.arange(beam), (batch, 1))
    for t in range(T - 1, -1, -1):
        seqs[:, :, t] = np.take_along_axis(tokens_hist[t], beam_idx, 1)
        beam_idx = np.take_along_axis(parents_hist[t], beam_idx, 1)
    ids_out = Tensor(seqs)
    scores = Tensor(log_probs)
    if return_length:
        lengths = (seqs != decoder.end_token).sum(-1)
        return ids_out, scores, Tensor(lengths.astype(np.int64))
    return ids_out, scores
