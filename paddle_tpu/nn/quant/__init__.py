"""paddle.nn.quant — weight-only quantization for LLM serving.

Reference: python/paddle/nn/quant/quantized_linear.py (weight_quantize:64,
weight_dequantize:131, weight_only_linear:191, llm_int8_linear:285,
apply_per_channel_scale:351) — CUTLASS int8/int4 GEMM epilogues behind
_C_ops.

TPU-native design: the MXU has no int4/int8×bf16 mixed GEMM, but
weight-only quantization is a MEMORY optimization, not a compute one —
serving decode is HBM-bound on weight streaming, so storing weights
int8/int4 (2-4x less HBM traffic) and dequantizing into the matmul's
bf16 operand (XLA fuses the `convert+mul` into the GEMM's operand read)
captures the same win the CUDA kernels target. No ``arch`` gating: any
TPU works; the argument is accepted and ignored for API compatibility.

int4 packing: two signed nibbles per int8 byte along the input-dim axis
(lo nibble = even k, hi nibble = odd k), weight stored transposed
[n, k] like the reference (int4: [n, k/2]).
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch

__all__ = ["Stub", "weight_quantize", "weight_dequantize",
           "weight_only_linear", "llm_int8_linear",
           "apply_per_channel_scale"]


from ..layer.layers import Layer as _Layer


class Stub(_Layer):
    """Quantization insertion-point placeholder (reference:
    nn/quant/stub.py:29): marks where an observer/quanter should be
    swapped in before PTQ/QAT when the quantized op is a functional
    call inside a layer's forward. Identity until an observer is
    attached by a quantization pass."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, x):
        if self._observer is not None and callable(self._observer):
            return self._observer(x)
        return x

_ALGOS = ("weight_only_int8", "weight_only_int4", "llm.int8")


def _check(algo, group_size):
    if algo not in _ALGOS:
        raise ValueError(f"algo must be one of {_ALGOS}, got {algo!r}")
    if group_size not in (-1, 64, 128):
        raise ValueError(
            f"group_size must be -1, 64 or 128, got {group_size}")


def _group_absmax(xt, group_size):
    """xt [n, k] -> scale: per-channel [n] (group_size=-1) or grouped
    [k // group_size, n] (reference layout)."""
    if group_size == -1:
        return jnp.max(jnp.abs(xt), axis=1)
    n, k = xt.shape
    g = xt.reshape(n, k // group_size, group_size)
    return jnp.max(jnp.abs(g), axis=2).T          # [k/gs, n]


def _expand_scale(scale, n, k, group_size, dtype):
    """Scale broadcastable against the [n, k] transposed weight."""
    if group_size == -1:
        return scale.reshape(n, 1).astype(dtype)
    return jnp.repeat(scale.T.astype(dtype), group_size,
                      axis=1).reshape(n, k)


def weight_quantize(x, algo: str = "weight_only_int8", arch=None,
                    group_size: int = -1):
    """[k, n] float weight -> (quantized [n, k] int8 (int4: [n, k/2]),
    scale). Per-channel absmax (or per-group along k)."""
    _check(algo, group_size)
    x = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    k = x.shape[0]
    if algo == "weight_only_int4" and k % 2:
        raise ValueError(
            f"weight_only_int4 packs two values per byte along the "
            f"input dim: k must be even, got {k}")
    if group_size != -1 and k % group_size:
        raise ValueError(
            f"k={k} must be divisible by group_size={group_size}")

    def f(v):
        xt = v.astype(jnp.float32).T              # [n, k]
        n, k = xt.shape
        qmax = 7.0 if algo == "weight_only_int4" else 127.0
        scale = _group_absmax(xt, group_size) / qmax
        scale = jnp.maximum(scale, 1e-10)
        full = _expand_scale(scale, n, k, group_size, jnp.float32)
        q = jnp.clip(jnp.round(xt / full), -qmax, qmax).astype(jnp.int8)
        if algo == "weight_only_int4":
            lo = q[:, 0::2] & 0x0F
            hi = (q[:, 1::2] & 0x0F) << 4
            q = (lo | hi).astype(jnp.int8)        # [n, k/2]
        return q, scale.astype(jnp.float32)

    return dispatch(f, (x,), name="weight_quantize", multi_output=True)


def _unpack_int4(q):
    """[n, k/2] packed -> [n, k] signed int8 in [-8, 7]."""
    lo = (q & 0x0F).astype(jnp.int8)
    lo = jnp.where(lo > 7, lo - 16, lo)
    hi = ((q >> 4) & 0x0F).astype(jnp.int8)
    hi = jnp.where(hi > 7, hi - 16, hi)
    return jnp.stack([lo, hi], axis=2).reshape(q.shape[0],
                                               q.shape[1] * 2)


def weight_dequantize(x, scale, algo: str = "weight_only_int8",
                      out_dtype="float16", group_size: int = -1):
    """Inverse of weight_quantize: back to the [k, n] float layout.
    Parameter order matches the reference (quantized_linear.py:131):
    (x, scale, algo, out_dtype, group_size) — positional callers
    ported from Paddle must keep working."""
    _check(algo, group_size)
    x = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    scale = scale if isinstance(scale, Tensor) else Tensor(
        jnp.asarray(scale))
    odt = jnp.dtype(out_dtype)

    def f(q, s):
        if algo == "weight_only_int4":
            q = _unpack_int4(q)
        n, k = q.shape
        full = _expand_scale(s, n, k, group_size, jnp.float32)
        return (q.astype(jnp.float32) * full).T.astype(odt)

    return dispatch(f, (x, scale), name="weight_dequantize")


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype: str = "int8", arch=None,
                       group_size: int = -1):
    """x [..., k] @ dequant(weight [n, k]) + bias -> [..., n].

    The dequant (convert + scale multiply) sits directly on the GEMM's
    weight operand so XLA fuses it into the operand read — HBM sees the
    int8/int4 bytes, the MXU sees bf16/f16 (the reference's fused
    dequant GEMM epilogue, minus the custom kernel)."""
    if weight_dtype not in ("int8", "int4"):
        raise ValueError(f"weight_dtype must be int8|int4: {weight_dtype}")
    _check("weight_only_int4" if weight_dtype == "int4"
           else "weight_only_int8", group_size)
    args = [t if isinstance(t, Tensor) else Tensor(jnp.asarray(t))
            for t in (x, weight)
            + ((weight_scale,) if weight_scale is not None else ())
            + ((bias,) if bias is not None else ())]
    has_scale = weight_scale is not None
    has_bias = bias is not None

    def f(v, q, *rest):
        s = rest[0] if has_scale else None
        b = rest[-1] if has_bias else None
        if weight_dtype == "int4":
            q = _unpack_int4(q)
        n, k = q.shape
        w = q.astype(v.dtype)
        if s is not None:
            w = w * _expand_scale(s, n, k, group_size, v.dtype)
        out = jnp.einsum("...k,nk->...n", v, w)
        if b is not None:
            out = out + b.astype(out.dtype)
        return out

    return dispatch(f, tuple(args), name="weight_only_linear")


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold: float = 6.0):
    """LLM.int8() decomposition (reference :285): activation channels
    whose absmax exceeds ``threshold`` (the outliers) run in the
    original float precision; the rest run through the int8 weight.
    out = x_outlier @ W_dequant_outlier + x_regular @ W_dequant."""
    args = [t if isinstance(t, Tensor) else Tensor(jnp.asarray(t))
            for t in (x, weight)
            + ((weight_scale,) if weight_scale is not None else ())
            + ((bias,) if bias is not None else ())]
    has_scale = weight_scale is not None
    has_bias = bias is not None

    def f(v, q, *rest):
        s = rest[0] if has_scale else None
        b = rest[-1] if has_bias else None
        n, k = q.shape
        v32 = v.astype(jnp.float32)
        ws = (s.reshape(n).astype(jnp.float32) if s is not None
              else jnp.ones((n,), jnp.float32))
        # outlier input features (per-feature absmax over all tokens)
        amax = jnp.max(jnp.abs(v32), axis=tuple(range(v.ndim - 1)))
        outlier = amax >= threshold                       # [k]
        # float path: outlier features only, against dequant weight
        v_out = jnp.where(outlier, v32, 0.0)
        w32 = q.astype(jnp.float32) * ws[:, None]
        out_f = jnp.einsum("...k,nk->...n", v_out, w32)
        # int8 path: regular features, per-token absmax activation
        # quantization, int8 x int8 GEMM with int32 accumulation on the
        # MXU, one rescale (the LLM.int8() decomposition)
        v_reg = jnp.where(outlier, 0.0, v32)
        a_s = jnp.maximum(
            jnp.max(jnp.abs(v_reg), axis=-1, keepdims=True) / 127.0,
            1e-10)
        vq = jnp.clip(jnp.round(v_reg / a_s), -127, 127).astype(jnp.int8)
        # shared int8 GEMM helper: one rescale convention repo-wide
        from ...quantization.quanters import int8_matmul
        out_i = int8_matmul(vq, q.T, a_s, ws)
        out = out_f + out_i
        if b is not None:
            out = out + b.astype(jnp.float32)
        return out.astype(v.dtype)

    return dispatch(f, tuple(args), name="llm_int8_linear")


def apply_per_channel_scale(x, scales):
    """x [..., k] * scales [k] (reference :351 — smooth-quant style
    activation pre-scaling before a quantized matmul)."""
    x = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    scales = scales if isinstance(scales, Tensor) else Tensor(
        jnp.asarray(scales))
    return dispatch(lambda v, s: v * s.astype(v.dtype), (x, scales),
                    name="apply_per_channel_scale")
