"""nn.utils (reference: python/paddle/nn/utils/)."""
from __future__ import annotations

from typing import List

import jax.numpy as jnp

from ...core.tensor import Tensor, no_grad

__all__ = ["parameters_to_vector", "vector_to_parameters", "weight_norm",
           "remove_weight_norm", "spectral_norm"]


@no_grad()
def parameters_to_vector(parameters, name=None) -> Tensor:
    return Tensor(jnp.concatenate(
        [p._value.reshape(-1) for p in parameters]))


@no_grad()
def vector_to_parameters(vec: Tensor, parameters, name=None):
    offset = 0
    v = vec._value
    for p in parameters:
        n = p.size
        p._replace_value(v[offset:offset + n].reshape(p._value.shape)
                         .astype(p._value.dtype))
        offset += n


def weight_norm(layer, name="weight", dim=0):
    """Reparameterise weight = g * v/||v|| (reference:
    python/paddle/nn/utils/weight_norm_hook.py)."""
    import numpy as np
    from ...framework import Parameter
    w = getattr(layer, name)
    if dim is None:
        dim = -1
    axes = tuple(i for i in range(w.ndim) if i != (dim % w.ndim)) \
        if dim != -1 else tuple(range(w.ndim))
    g_val = jnp.sqrt(jnp.sum(jnp.square(w._value), axis=axes, keepdims=False))
    g = Parameter(g_val, name=f"{name}_g")
    v = Parameter(w._value, name=f"{name}_v")
    del layer._parameters[name]
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)

    def compute(layer):
        from ...core.tensor import dispatch
        def f(gv, vv):
            if dim == -1:
                n = jnp.sqrt(jnp.sum(jnp.square(vv)))
                return gv * vv / jnp.maximum(n, 1e-12)
            n = jnp.sqrt(jnp.sum(jnp.square(vv), axis=axes, keepdims=True))
            shape = [1] * vv.ndim
            shape[dim % vv.ndim] = -1
            return gv.reshape(shape) * vv / jnp.maximum(n, 1e-12)
        return dispatch(f, (g, v), name="weight_norm")

    def pre_hook(l, inputs):
        object.__setattr__(l, name, compute(l))
        return None
    handle = layer.register_forward_pre_hook(pre_hook)
    layer._weight_norm_hook = (handle, name, dim)
    object.__setattr__(layer, name, compute(layer))
    return layer


def remove_weight_norm(layer, name="weight"):
    handle, nm, dim = layer._weight_norm_hook
    handle.remove()
    from ...framework import Parameter
    w = getattr(layer, nm)
    g = layer._parameters.pop(nm + "_g")
    v = layer._parameters.pop(nm + "_v")
    layer.add_parameter(nm, Parameter(w._value if isinstance(w, Tensor)
                                      else w, name=nm))
    del layer._weight_norm_hook
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    from ..layer.norm import SpectralNorm as _SN
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    sn = _SN(list(w.shape), dim=dim, power_iters=n_power_iterations,
             epsilon=eps)
    layer.add_sublayer(name + "_sn", sn)
    orig = layer._parameters[name]

    def pre_hook(l, inputs):
        object.__setattr__(l, name, sn(orig))
        return None
    layer.register_forward_pre_hook(pre_hook)
    return layer
