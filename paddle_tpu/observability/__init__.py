"""paddle_tpu.observability — metrics, tracing and stall diagnostics
for the serving AND training/multichip stacks.

One lightweight harness threaded through the serving path (and usable
standalone around ``generate_paged``) and, since r9, through the
hybrid-parallel ``Trainer`` and the collective flight recorder: a
metrics registry (counters + gauges + streaming histograms with
p50/p95/p99 export), lifecycle timelines in a bounded ring buffer
(chrome-trace export through ``profiler/``), compile telemetry
(``compile.py``: compile wall time, retrace counts, cost-analysis MFU,
memory-analysis HBM breakdown, host-vs-device gap detection), a
retrace watchdog, and flight-recorder stall dumps. Everything here is
host-side bookkeeping: recording an event is a timestamp + a deque
append, and **no code path issues a device sync** — the owning
component decides its sync points (the engine's one per-step d2h read;
the observed trainer's one per-step metrics sync). When disabled the
component holds no harness at all (``None``), so the disabled hot
loop allocates zero event objects.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Dict, Optional, Sequence

from .compile import (CompileWatcher, HostGapDetector, device_peak_flops,
                      device_peak_hbm_bw, live_hbm_bytes)
from .metrics import Gauge, Histogram, MetricsRegistry
from .roofline import (capture_kernel_costs, decode_roofline,
                       decode_step_bytes, kernel_cost, roofline_point)
from .stall import dump_path_for, dump_stall
from .telemetry import (TelemetryConfig, TelemetryPlane, flatten_metrics,
                        lint_exposition, render_exposition)
from .timeline import Timeline, TimelineEvent
from .watchdog import RetraceWatchdog

__all__ = ["Observability", "MetricsRegistry", "Histogram", "Gauge",
           "Timeline", "TimelineEvent", "RetraceWatchdog", "dump_stall",
           "CompileWatcher", "HostGapDetector", "device_peak_flops",
           "device_peak_hbm_bw", "live_hbm_bytes", "kernel_cost",
           "roofline_point", "capture_kernel_costs", "decode_step_bytes",
           "decode_roofline", "LATENCY_HISTOGRAMS", "TRAIN_HISTOGRAMS",
           "TelemetryConfig", "TelemetryPlane", "flatten_metrics",
           "render_exposition", "lint_exposition"]

# the latency histograms every engine window reports (schema-stable:
# tests freeze this set — extend deliberately, never ad hoc)
LATENCY_HISTOGRAMS = ("ttft_ms", "tpot_ms", "queue_wait_ms", "e2e_ms",
                      "prefill_chunk_ms", "decode_step_ms", "step_ms")

# the per-step phase histograms every trainer window reports (same
# contract): stage = batch h2d staging, dispatch = the compiled call
# returning (host work under async dispatch), sync = the wait for the
# device, compile = AOT compile wall time
TRAIN_HISTOGRAMS = ("step_ms", "stage_ms", "dispatch_ms", "sync_ms",
                    "compile_ms")


class Observability:
    """Per-component observability harness.

    Owns one :class:`MetricsRegistry`, one :class:`Timeline` ring, one
    :class:`RetraceWatchdog` and the stall-dump plumbing. The component
    holds either an instance (enabled) or ``None`` (disabled — zero
    overhead, no event objects ever allocated). ``histograms`` selects
    the pre-created latency set: :data:`LATENCY_HISTOGRAMS` (serving,
    default) or :data:`TRAIN_HISTOGRAMS` (trainer).
    """

    def __init__(self, ring_capacity: int = 4096,
                 gauge_window: int = 512,
                 step_deadline_s: Optional[float] = None,
                 stall_dump_path: Optional[str] = None,
                 warn_on_retrace: bool = True,
                 max_request_records: int = 2048,
                 max_stall_dumps: int = 8,
                 histograms: Sequence[str] = LATENCY_HISTOGRAMS):
        self.registry = MetricsRegistry()
        self.timeline = Timeline(ring_capacity)
        self.watchdog = RetraceWatchdog(warn=warn_on_retrace)
        self.gauge_window = int(gauge_window)
        self.step_deadline_s = step_deadline_s
        self.stall_dump_path = stall_dump_path
        self.max_stall_dumps = int(max_stall_dumps)
        # bounded log of (reason, path): with a path configured only
        # written files land here (<= max_stall_dumps); the stderr
        # route is uncapped by design, so the deque bounds a flapping
        # trigger's memory
        self.stall_dumps: deque = deque(
            maxlen=max(64, self.max_stall_dumps))
        self.stall_dumps_suppressed = 0
        self.request_records: deque = deque(maxlen=max_request_records)
        self._flight = None            # bound FlightRecorder, if any
        self._hist_names = tuple(histograms)
        for name in self._hist_names:
            self.registry.histogram(name, unit="ms")

    # -- recording shortcuts ------------------------------------------
    def hist(self, name: str) -> Histogram:
        return self.registry.histogram(name)

    def ensure_histograms(self, names: Sequence[str]):
        """Extend the reported latency set (e.g. an engine feature —
        the KV offload tier's spill_ms/restore_ms — adds its own
        distributions): the names join ``latency_snapshot()``'s output
        and survive ``reset_window()`` like the built-in set."""
        for name in names:
            if name not in self._hist_names:
                self._hist_names += (name,)
            self.registry.histogram(name, unit="ms")

    def sample_gauges(self, t: float, values: Dict[str, float]):
        for name, v in values.items():
            self.registry.gauge(name, self.gauge_window).set(v, t)

    def observe_request(self, record: Dict, stale: bool = False):
        """One finished request: feed the latency histograms and keep
        the record for JSONL export. ``queue_wait_ms`` is observed at
        admission (not here) so requests parked in the queue still
        count the moment they admit. ``stale=True`` (the request was
        submitted before the last window reset, so its latencies span
        the warmup) keeps the record but skips the histograms —
        matching the ttft_ms_mean/max warmup exclusion."""
        if not stale:
            for key in ("ttft_ms", "tpot_ms", "e2e_ms"):
                v = record.get(key)
                if v is not None:
                    self.hist(key).observe(v)
        else:
            record = dict(record, warmup=True)
        self.request_records.append(record)

    # -- flight recorder binding --------------------------------------
    def bind_flight_recorder(self, recorder):
        """Unify a collective :class:`FlightRecorder` with this
        harness: completed collectives feed per-(op, axis) latency
        histograms + bytes-moved counters into this registry, hang
        dumps share the stall-dump retention policy, and chrome-trace
        export gains the recorder's per-rank collective tracks."""
        recorder.bind(registry=self.registry, clock=self.now)
        self._flight = recorder
        return recorder

    # -- stall diagnostics --------------------------------------------
    def stall_dump(self, reason: str, scheduler: Dict,
                   metrics: Optional[Dict] = None) -> str:
        path, suppressed = dump_path_for(
            self.stall_dump_path,
            sum(1 for _, p in self.stall_dumps if p),
            self.max_stall_dumps)
        if suppressed:
            # file-retention bound hit: count, don't append — a
            # flapping trigger past the cap must not grow the log
            # without bound (stderr-routed dumps are never capped —
            # dump_path_for)
            self.stall_dumps_suppressed += 1
            self.timeline.record("stall", reason=reason, suppressed=True)
            return ""
        self.timeline.record("stall", reason=reason)
        written = dump_stall(reason, scheduler, self.timeline.tail(),
                             metrics=metrics, path=path)
        self.stall_dumps.append((reason, written))
        return written

    # -- reporting ----------------------------------------------------
    def reset_window(self):
        """Restart the distribution window (after compile warmup):
        histograms and per-request records clear, the timeline ring and
        gauge series keep rolling (history is cheap and useful)."""
        self.registry.reset_histograms()
        self.request_records.clear()

    def latency_snapshot(self, names: Optional[Sequence[str]] = None
                         ) -> Dict:
        names = self._hist_names if names is None else names
        return {name: self.registry.histogram(name).snapshot()
                for name in names}

    def gauges_snapshot(self) -> Dict:
        return {name: g.snapshot()
                for name, g in sorted(self.registry.gauges.items())}

    def export_chrome(self, path: str,
                      process_name: str = "paddle_tpu serving",
                      extra_events=None) -> str:
        extra = None
        if self._flight is not None:
            extra = self._flight.to_host_events()
        return self.timeline.export_chrome(
            path, gauges=self.registry.gauges,
            process_name=process_name, extra_host_events=extra,
            extra_events=extra_events)

    def write_jsonl(self, path: str, header: Optional[Dict] = None
                    ) -> str:
        return self.timeline.write_jsonl(
            path, request_records=list(self.request_records),
            header=header)

    @staticmethod
    def now() -> float:
        return time.perf_counter()
