"""paddle_tpu.observability — serving-stack metrics, tracing and stall
diagnostics.

One lightweight harness threaded through the serving path (and usable
standalone around ``generate_paged``): a metrics registry (counters +
gauges + streaming histograms with p50/p95/p99 export), per-request
lifecycle timelines in a bounded ring buffer (chrome-trace export
through ``profiler/``), a retrace watchdog, and flight-recorder stall
dumps. Everything here is host-side bookkeeping: recording an event is
a timestamp + a deque append, and **no code path issues a device sync**
— the engine's one per-step d2h read stays the only synchronization
point. When disabled the engine holds no harness at all (``None``), so
the disabled hot loop allocates zero event objects.
"""
from __future__ import annotations

import os
import time
from collections import deque
from typing import Dict, Optional

from .metrics import Gauge, Histogram, MetricsRegistry
from .stall import dump_stall
from .timeline import Timeline, TimelineEvent
from .watchdog import RetraceWatchdog

__all__ = ["Observability", "MetricsRegistry", "Histogram", "Gauge",
           "Timeline", "TimelineEvent", "RetraceWatchdog", "dump_stall"]

# the latency histograms every engine window reports (schema-stable:
# tests freeze this set — extend deliberately, never ad hoc)
LATENCY_HISTOGRAMS = ("ttft_ms", "tpot_ms", "queue_wait_ms", "e2e_ms",
                      "prefill_chunk_ms", "decode_step_ms", "step_ms")


class Observability:
    """Per-component observability harness.

    Owns one :class:`MetricsRegistry`, one :class:`Timeline` ring, one
    :class:`RetraceWatchdog` and the stall-dump plumbing. The engine
    holds either an instance (enabled) or ``None`` (disabled — zero
    overhead, no event objects ever allocated).
    """

    def __init__(self, ring_capacity: int = 4096,
                 gauge_window: int = 512,
                 step_deadline_s: Optional[float] = None,
                 stall_dump_path: Optional[str] = None,
                 warn_on_retrace: bool = True,
                 max_request_records: int = 2048):
        self.registry = MetricsRegistry()
        self.timeline = Timeline(ring_capacity)
        self.watchdog = RetraceWatchdog(warn=warn_on_retrace)
        self.gauge_window = int(gauge_window)
        self.step_deadline_s = step_deadline_s
        self.stall_dump_path = stall_dump_path
        self.stall_dumps = []          # [(reason, path)]
        self.request_records: deque = deque(maxlen=max_request_records)
        for name in LATENCY_HISTOGRAMS:
            self.registry.histogram(name, unit="ms")

    # -- recording shortcuts ------------------------------------------
    def hist(self, name: str) -> Histogram:
        return self.registry.histogram(name)

    def sample_gauges(self, t: float, values: Dict[str, float]):
        for name, v in values.items():
            self.registry.gauge(name, self.gauge_window).set(v, t)

    def observe_request(self, record: Dict, stale: bool = False):
        """One finished request: feed the latency histograms and keep
        the record for JSONL export. ``queue_wait_ms`` is observed at
        admission (not here) so requests parked in the queue still
        count the moment they admit. ``stale=True`` (the request was
        submitted before the last window reset, so its latencies span
        the warmup) keeps the record but skips the histograms —
        matching the ttft_ms_mean/max warmup exclusion."""
        if not stale:
            for key in ("ttft_ms", "tpot_ms", "e2e_ms"):
                v = record.get(key)
                if v is not None:
                    self.hist(key).observe(v)
        else:
            record = dict(record, warmup=True)
        self.request_records.append(record)

    # -- stall diagnostics --------------------------------------------
    def stall_dump(self, reason: str, scheduler: Dict,
                   metrics: Optional[Dict] = None) -> str:
        path = self.stall_dump_path
        if path and self.stall_dumps:
            # successive dumps must not clobber the first report
            # (splitext, not rpartition: a dot in a parent directory
            # must not get the counter spliced into it)
            base, ext = os.path.splitext(path)
            path = f"{base}.{len(self.stall_dumps)}{ext}"
        self.timeline.record("stall", reason=reason)
        written = dump_stall(reason, scheduler, self.timeline.tail(),
                             metrics=metrics, path=path)
        self.stall_dumps.append((reason, written))
        return written

    # -- reporting ----------------------------------------------------
    def reset_window(self):
        """Restart the distribution window (after compile warmup):
        histograms and per-request records clear, the timeline ring and
        gauge series keep rolling (history is cheap and useful)."""
        self.registry.reset_histograms()
        self.request_records.clear()

    def latency_snapshot(self) -> Dict:
        return {name: self.registry.histogram(name).snapshot()
                for name in LATENCY_HISTOGRAMS}

    def gauges_snapshot(self) -> Dict:
        return {name: g.snapshot()
                for name, g in sorted(self.registry.gauges.items())}

    def export_chrome(self, path: str) -> str:
        return self.timeline.export_chrome(
            path, gauges=self.registry.gauges)

    def write_jsonl(self, path: str, header: Optional[Dict] = None
                    ) -> str:
        return self.timeline.write_jsonl(
            path, request_records=list(self.request_records),
            header=header)

    @staticmethod
    def now() -> float:
        return time.perf_counter()
