"""Compile telemetry + host-vs-device gap detection for jitted programs.

The training half of the framework was dark: the llama bench's 3.2 s
host-side h2d residual (vs ~200 ms of device compute) had to be
diagnosed by hand with XPlane, and MFU was hand-derived from a flops
formula. This module captures, for every jitted program routed through
it:

- **compile wall time + retrace counts** — an AOT ``lower().compile()``
  wrapped in a timer, keyed by the program's abstract input signature,
  so a shape/dtype leak shows up as a counted (and, once armed, warned)
  recompile instead of a silent seconds-long stall;
- **``cost_analysis()``** FLOPs / bytes-accessed per execution — the
  numerator of an *automatic* MFU (no hand-derived flops formula);
- **``memory_analysis()``** HBM breakdown (arguments / outputs / temps
  / generated code) plus a live-HBM gauge where the backend exposes
  ``memory_stats()``.

It also hosts the :class:`HostGapDetector`: per-step phase timings
(stage/h2d, compiled dispatch, host sync) are compared and a
flight-recorder-style dump fires when host-side time dwarfs the time
actually spent waiting on the device — the exact llama-residual
failure mode, detected automatically this time.
"""
from __future__ import annotations

import os
import time
import warnings
from typing import Dict, Optional

__all__ = ["CompileWatcher", "HostGapDetector", "device_peak_flops",
           "device_peak_hbm_bw", "live_hbm_bytes"]

# nominal per-chip peaks by TPU generation: dense-matmul FLOPs/s (bf16)
# and HBM bandwidth (bytes/s). The ONE peak table pair — bench.py's
# formula MFU and its bandwidth-utilisation column both delegate here,
# so no two roofline denominators in a capture can ever disagree. The
# DEFAULTS (v5e: 197 TFLOP/s, 819 GB/s) live in the tables too, keyed
# by _DEFAULT_GEN, so the labelled-default contract reads the same
# numbers the generation match does.
_DEFAULT_GEN = "v5e"
_PEAK_FLOPS = {"v4": 275e12, "v5e": 197e12, "v5litepod": 197e12,
               "v5p": 459e12, "v6e": 918e12}
_PEAK_HBM_BW = {"v4": 1228e9, "v5e": 819e9, "v5litepod": 819e9,
                "v5p": 2765e9, "v6e": 1640e9}


def _device_peak(table, env_var):
    """Shared peak-lookup contract: ``(value, source)`` in the order
    env override (exact hardware known to the operator) >
    ``PALLAS_AXON_TPU_GEN`` generation table > the labelled v5e
    default. The source string rides into ``metrics()`` and the
    roofline reports so a fraction computed against an *assumed* peak
    is labelled as such."""
    env = os.environ.get(env_var)
    if env:
        try:
            return float(env), "env"
        except ValueError:
            pass
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    for k, v in table.items():
        if gen.startswith(k):
            return v, f"gen:{k}"
    return table[_DEFAULT_GEN], f"default:{_DEFAULT_GEN}"


def device_peak_flops(default: float = None):
    """Best-effort peak FLOPs/s per chip: ``(value, source)``.

    Order: ``PADDLE_TPU_PEAK_FLOPS`` env override >
    ``PALLAS_AXON_TPU_GEN`` generation table > the labelled v5e
    default (``default``, when given, overrides the table default —
    the historic signature)."""
    val, source = _device_peak(_PEAK_FLOPS, "PADDLE_TPU_PEAK_FLOPS")
    if default is not None and source.startswith("default"):
        return float(default), source
    return val, source


def device_peak_hbm_bw():
    """Best-effort peak HBM bandwidth per chip in bytes/s:
    ``(value, source)``. Same contract as :func:`device_peak_flops`
    with the ``PADDLE_TPU_PEAK_HBM_BW`` env override."""
    return _device_peak(_PEAK_HBM_BW, "PADDLE_TPU_PEAK_HBM_BW")


def live_hbm_bytes(device=None) -> Optional[int]:
    """Bytes currently allocated on ``device`` via PjRt
    ``memory_stats()``; None where the backend does not report (CPU)."""
    try:
        if device is None:
            import jax
            device = jax.devices()[0]
        stats = device.memory_stats()
        if stats:
            return int(stats.get("bytes_in_use", 0)) or None
    except Exception:  # noqa: BLE001 — telemetry must never raise
        pass
    return None


def _cost_dict(compiled) -> Optional[Dict]:
    """Flatten ``compiled.cost_analysis()`` to {flops, bytes_accessed}.

    jax returns a list of per-computation dicts on some versions, a
    plain dict on others; either way only the well-known keys are kept
    (the full dict carries per-operand entries with unstable names).
    """
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — unsupported backend
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {}
    for key in ("flops", "bytes accessed"):
        v = ca.get(key)
        if v is not None:
            out[key.replace(" ", "_")] = float(v)
    return out or None


def _memory_dict(compiled) -> Optional[Dict]:
    """``compiled.memory_analysis()`` → HBM breakdown in bytes."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001
        return None
    if ma is None:
        return None
    fields = ("argument_size_in_bytes", "output_size_in_bytes",
              "temp_size_in_bytes", "alias_size_in_bytes",
              "generated_code_size_in_bytes")
    out = {}
    for f in fields:
        v = getattr(ma, f, None)
        if v is not None:
            out[f.replace("_size_in_bytes", "_bytes")] = int(v)
    if not out:
        return None
    # aliased bytes are donated inputs — they overlap outputs, so the
    # peak estimate counts them once
    out["total_bytes"] = (out.get("argument_bytes", 0)
                          + out.get("output_bytes", 0)
                          + out.get("temp_bytes", 0)
                          + out.get("generated_code_bytes", 0)
                          - out.get("alias_bytes", 0))
    return out


class CompileWatcher:
    """Per-program compile telemetry with a retrace watchdog.

    ``compile(name, jitted, *args)`` runs the AOT ``lower().compile()``
    path, times it, counts it, and extracts cost/memory analysis. Once
    :meth:`arm` is called (the warmup→reset idiom the serving watchdog
    established), any further compile of an armed program warns — a
    steady-state train loop must run ONE program.
    """

    def __init__(self, registry=None, timeline=None, warn: bool = True):
        self.registry = registry
        self.timeline = timeline
        self.warn = warn
        self.programs: Dict[str, Dict] = {}
        self.retrace_events: list = []
        self._armed = False

    def compile(self, name: str, jitted, *args, **kwargs):
        """AOT-compile ``jitted`` for ``args`` and record the event;
        returns the compiled executable."""
        t0 = time.perf_counter()
        compiled = jitted.lower(*args, **kwargs).compile()
        wall_s = time.perf_counter() - t0
        rec = self.programs.get(name)
        if rec is None:
            rec = self.programs[name] = {
                "count": 0, "wall_s_total": 0.0, "wall_s_last": 0.0,
                "cost": None, "memory": None}
        rec["count"] += 1
        rec["wall_s_total"] += wall_s
        rec["wall_s_last"] = wall_s
        # cost/memory reflect the LAST compile: a retrace changed the
        # program, so the stale analysis would misprice MFU
        rec["cost"] = _cost_dict(compiled)
        rec["memory"] = _memory_dict(compiled)
        if self.registry is not None:
            self.registry.histogram("compile_ms").observe(wall_s * 1e3)
        if self.timeline is not None:
            self.timeline.record("compile", program=name,
                                 dur_ms=wall_s * 1e3,
                                 count=rec["count"])
        if self._armed:
            finding = {"program": name, "traces": 1,
                       "compile_ms": round(wall_s * 1e3, 3)}
            self.retrace_events.append(finding)
            if self.warn:
                warnings.warn(
                    f"compile of {name!r} after warmup "
                    f"({wall_s * 1e3:.1f} ms) — a steady-state train "
                    "loop must reuse one compiled program; a shape or "
                    "dtype leak in the batch stream retraces every "
                    "occurrence", RuntimeWarning, stacklevel=3)
        return compiled

    def arm(self):
        """Declare warmup complete: further compiles warn + count.
        Re-arming restarts the retrace window — a fixed leak's old
        warnings must not haunt the next window's snapshot (the
        compile_ms histogram resets alongside, via reset_window)."""
        self._armed = True
        self.retrace_events = []

    @property
    def armed(self) -> bool:
        return self._armed

    @property
    def total_compiles(self) -> int:
        return sum(r["count"] for r in self.programs.values())

    def flops_per_step(self, name: str) -> Optional[float]:
        rec = self.programs.get(name)
        if rec and rec.get("cost"):
            return rec["cost"].get("flops")
        return None

    def mfu(self, name: str, steps: int, wall_s: float) -> Optional[Dict]:
        """Cost-analysis-derived MFU over a measured window.

        ``cost_analysis()`` reports PER-DEVICE FLOPs for an SPMD-
        partitioned program (verified: a matmul sharded 4 ways reports
        whole/4), so per-device flops over the per-chip peak IS the
        per-chip MFU — no device-count factor on either side."""
        flops = self.flops_per_step(name)
        if not flops or steps <= 0 or wall_s <= 0:
            return None
        peak, source = device_peak_flops()
        return {"mfu": round(flops * steps / (wall_s * peak), 4),
                "flops_per_step_per_device": flops,
                "peak_flops_per_chip": peak, "peak_source": source}

    def snapshot(self) -> Dict:
        progs = {}
        for name, r in self.programs.items():
            progs[name] = {
                "count": r["count"],
                "wall_ms_total": round(r["wall_s_total"] * 1e3, 3),
                "wall_ms_last": round(r["wall_s_last"] * 1e3, 3),
                **({"cost": r["cost"]} if r["cost"] else {}),
                **({"memory": r["memory"]} if r["memory"] else {}),
            }
        return {"count": self.total_compiles,
                "retraces_after_warmup": len(self.retrace_events),
                "programs": progs}


class HostGapDetector:
    """Detect steps where host-side time dwarfs device-wait time.

    Per step the trainer hands over its phase split: ``stage_ms``
    (batch h2d staging), ``dispatch_ms`` (the compiled call returning
    — async dispatch makes this pure host work) and ``sync_ms`` (the
    block-until-ready wait, i.e. the time the device was actually the
    bottleneck). When ``stage + dispatch > factor × sync`` and the step
    is big enough to matter, the host — not the device — owns the step,
    and a flight-recorder-style dump is emitted through the provided
    callback (bounded count; detection keeps counting after the cap).
    """

    def __init__(self, factor: float = 4.0, min_wall_ms: float = 50.0,
                 max_dumps: int = 4):
        self.factor = float(factor)
        self.min_wall_ms = float(min_wall_ms)
        self.max_dumps = int(max_dumps)
        self.findings: list = []
        self.dumps = 0

    def reset(self):
        """Restart the detection window (the warmup→reset idiom):
        findings clear and the dump budget refills — warmup's first-
        staging gap must not spend the measured window's budget."""
        self.findings = []
        self.dumps = 0

    def observe(self, step: int, stage_ms: float, dispatch_ms: float,
                sync_ms: float) -> Optional[Dict]:
        host_ms = stage_ms + dispatch_ms
        wall_ms = host_ms + sync_ms
        if wall_ms < self.min_wall_ms:
            return None
        if host_ms <= self.factor * max(sync_ms, 1e-3):
            return None
        finding = {"step": step, "host_ms": round(host_ms, 3),
                   "stage_ms": round(stage_ms, 3),
                   "dispatch_ms": round(dispatch_ms, 3),
                   "device_wait_ms": round(sync_ms, 3),
                   "host_over_device": round(
                       host_ms / max(sync_ms, 1e-3), 1)}
        self.findings.append(finding)
        return finding

    def should_dump(self) -> bool:
        if self.dumps >= self.max_dumps:
            return False
        self.dumps += 1
        return True
