"""Metrics primitives for the serving stack.

The ServingEngine grew its telemetry as a flat dict of counters plus
mean/max TTFT — fine for a smoke test, useless for a bench window that
must bank *distributions* (Liger Kernel's reporting harness is the
model: kernel wins only become trustworthy end-to-end claims through a
standardized latency/throughput/memory report). This module is the
bounded-memory substrate:

- ``Counter`` semantics stay plain dict entries (the engine's traced
  program bodies increment them at C speed; a method call there would
  be pure overhead) — the ``MetricsRegistry`` *adopts* the dict and
  owns its export.
- ``Histogram`` is a streaming log-bucketed histogram: O(1) observe,
  O(#buckets) percentile, memory bounded by the dynamic range (~9%
  relative resolution at the default growth). p50 <= p95 <= p99 holds
  by construction because percentiles walk the same bucket array.
- ``Gauge`` keeps the last value plus a bounded time series window so
  allocator pressure / cache effectiveness are visible *over time*
  (and exportable as chrome-trace counter tracks), not just at exit.
"""
from __future__ import annotations

import math
from collections import deque
from typing import Dict, Optional

__all__ = ["Histogram", "Gauge", "MetricsRegistry"]


class Histogram:
    """Streaming log-bucketed histogram with percentile export.

    Buckets grow geometrically by ``growth`` per index (default
    2**0.125, ~9% relative width), so a value ``v`` lands in bucket
    ``floor(log(v)/log(growth))`` and percentile queries are exact to
    one bucket width. Non-positive values collapse into a dedicated
    zero bucket. Memory is O(distinct buckets), bounded by the dynamic
    range of the data — never by the observation count.
    """

    __slots__ = ("unit", "_log_g", "_growth", "_buckets", "_zeros",
                 "count", "total", "min", "max")

    def __init__(self, unit: str = "ms", growth: float = 2.0 ** 0.125):
        if growth <= 1.0:
            raise ValueError("growth must be > 1")
        self.unit = unit
        self._growth = growth
        self._log_g = math.log(growth)
        self._buckets: Dict[int, int] = {}
        self._zeros = 0
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, value: float):
        value = float(value)
        self.count += 1
        self.total += value
        if self.min is None or value < self.min:
            self.min = value
        if self.max is None or value > self.max:
            self.max = value
        if value <= 0.0:
            self._zeros += 1
            return
        idx = int(math.floor(math.log(value) / self._log_g))
        self._buckets[idx] = self._buckets.get(idx, 0) + 1

    def percentile(self, q: float) -> float:
        """Value at quantile ``q`` in [0, 1] (bucket geometric midpoint;
        exact min/max returned at the extremes)."""
        if self.count == 0:
            return 0.0
        if q <= 0.0:
            return float(self.min)
        if q >= 1.0:
            return float(self.max)
        rank = max(1, int(math.ceil(q * self.count)))
        seen = self._zeros
        if rank <= seen:
            return 0.0
        for idx in sorted(self._buckets):
            seen += self._buckets[idx]
            if rank <= seen:
                # geometric midpoint of [growth^idx, growth^(idx+1)),
                # clamped to the observed range so p99 <= max always
                mid = self._growth ** (idx + 0.5)
                return float(min(max(mid, self.min), self.max))
        return float(self.max)

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def snapshot(self) -> Dict:
        r = lambda v: round(float(v), 3)  # noqa: E731
        return {"count": self.count, "unit": self.unit,
                "mean": r(self.mean),
                "min": r(self.min) if self.count else 0.0,
                "max": r(self.max) if self.count else 0.0,
                "p50": r(self.percentile(0.50)),
                "p95": r(self.percentile(0.95)),
                "p99": r(self.percentile(0.99))}


class Gauge:
    """Last-value gauge with a bounded (t, value) series window."""

    __slots__ = ("value", "series")

    def __init__(self, window: int = 512):
        self.value: Optional[float] = None
        self.series: deque = deque(maxlen=window)

    def set(self, value: float, t: Optional[float] = None):
        self.value = value
        self.series.append((t, value))

    def snapshot(self) -> Dict:
        if not self.series:
            return {"last": None, "min": None, "max": None, "mean": None}
        vals = [v for _, v in self.series]
        return {"last": self.value,
                "min": min(vals), "max": max(vals),
                "mean": round(sum(vals) / len(vals), 3)}


class MetricsRegistry:
    """One owner for a component's counters, gauges and histograms.

    Counters are adopted as a plain dict (``adopt_counters``) so hot
    loops — including python bodies that only run while XLA traces —
    keep dict-speed increments; the registry's job is the *export*:
    ``snapshot()`` renders everything as plain JSON-ready data.
    """

    def __init__(self):
        self.counters: Dict = {}
        self.gauges: Dict[str, Gauge] = {}
        self.histograms: Dict[str, Histogram] = {}

    def adopt_counters(self, counters: Dict) -> Dict:
        """Register an existing counter dict as this registry's counter
        store (shared by reference — increments stay visible here)."""
        self.counters = counters
        return counters

    def gauge(self, name: str, window: int = 512) -> Gauge:
        g = self.gauges.get(name)
        if g is None:
            g = self.gauges[name] = Gauge(window)
        return g

    def histogram(self, name: str, unit: str = "ms") -> Histogram:
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram(unit)
        return h

    def reset_histograms(self):
        """Restart the distribution window (e.g. after compile warmup)
        keeping the histogram identities."""
        for name, h in list(self.histograms.items()):
            self.histograms[name] = Histogram(h.unit, h._growth)

    def snapshot(self) -> Dict:
        return {
            "counters": {k: (dict(v) if isinstance(v, dict) else v)
                         for k, v in self.counters.items()},
            "gauges": {k: g.snapshot() for k, g in self.gauges.items()},
            "histograms": {k: h.snapshot()
                           for k, h in self.histograms.items()},
        }
