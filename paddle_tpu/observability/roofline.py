"""Kernel roofline observatory: modeled bytes/FLOPs per Pallas launch.

Five PRs of megakernel work were justified by raw microsecond A/Bs;
this module says *how close to the hardware* each kernel runs and
*why* a variant wins, in the units the fusion literature reports:
bytes moved, FLOPs, arithmetic intensity, and % of the roofline.

The two model halves live next to what they price:

- **bytes** — :func:`paddle_tpu.analysis.kernel_rules.modeled_launch_bytes`
  walks the SAME captured index maps the ``VMEM_OVERCOMMIT`` window
  model walks, but sums revisit-elided block fetches over the full
  grid instead of maxing windows over one step;
- **FLOPs** — :data:`paddle_tpu.analysis.kernel_catalog.FLOP_FORMULAS`
  registers one formula per audited launch name, with a
  ``FLOP_FORMULA_GAP`` finding when a kernel lacks one.

This module pairs them with the per-chip peaks
(:func:`~paddle_tpu.observability.compile.device_peak_flops` /
:func:`~paddle_tpu.observability.compile.device_peak_hbm_bw`, shared
env > generation > labelled-default contract) to classify each launch
memory- vs compute-bound and — given a measured time — compute
achieved-bandwidth / achieved-FLOPs fractions and the
time-at-peak-bandwidth lower bound the trace tooling prints.

Everything here is host-side arithmetic on captured
:class:`~paddle_tpu.ops.pallas._util.KernelLaunchSpec` geometry: no
device work, no syncs, usable under ``jax.eval_shape``.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

from .compile import device_peak_flops, device_peak_hbm_bw

__all__ = ["kernel_cost", "roofline_point", "capture_kernel_costs",
           "decode_step_bytes", "decode_roofline",
           "roofline_chrome_events"]


def peak_snapshot() -> Dict:
    """The labelled peak pair every roofline row prices against."""
    flops, flops_src = device_peak_flops()
    bw, bw_src = device_peak_hbm_bw()
    return {"peak_flops": flops, "peak_hbm_bw": bw,
            "peak_source": {"flops": flops_src, "hbm_bw": bw_src}}


def _sig4(x: float) -> float:
    # achieved fractions span ~1e-5 (interpret/CPU steps) to ~1.0 (on
    # chip): significant figures, not decimal places — round(2e-5, 4)
    # would report a real measurement as 0.0
    return float(f"{x:.4g}")


def roofline_point(bytes_modeled: Optional[float],
                   flops_modeled: Optional[float],
                   time_us: Optional[float] = None,
                   peaks: Optional[Dict] = None) -> Dict:
    """Classify one (bytes, FLOPs[, measured time]) point against the
    device roofline.

    Returns ``intensity`` (FLOPs/byte), ``bound`` (``"memory"`` /
    ``"compute"`` by the ridge point ``peak_flops / peak_hbm_bw``),
    the bound-side lower-bound execution time ``time_at_roofline_us``
    and — when a measured ``time_us`` is given — ``achieved_bw_frac``,
    ``achieved_flops_frac`` and ``roofline_frac`` (lower bound over
    measured: 1.0 means the launch runs AT the roofline). Fields whose
    inputs are missing are ``None``, never silently zero.
    """
    peaks = peaks or peak_snapshot()
    peak_flops = peaks["peak_flops"]
    peak_bw = peaks["peak_hbm_bw"]
    out: Dict = {"intensity": None, "bound": None,
                 "time_at_roofline_us": None,
                 "achieved_bw_frac": None, "achieved_flops_frac": None,
                 "roofline_frac": None,
                 "peak_source": peaks["peak_source"]}
    has_bytes = bytes_modeled is not None and bytes_modeled > 0
    has_flops = flops_modeled is not None and flops_modeled > 0
    if has_bytes and has_flops:
        intensity = flops_modeled / bytes_modeled
        out["intensity"] = round(intensity, 3)
        ridge = peak_flops / peak_bw
        out["bound"] = "memory" if intensity < ridge else "compute"
    t_bw = bytes_modeled / peak_bw if has_bytes else None
    t_fl = flops_modeled / peak_flops if has_flops else None
    t_roof = max(t for t in (t_bw, t_fl) if t is not None) \
        if (t_bw is not None or t_fl is not None) else None
    if t_roof is not None:
        out["time_at_roofline_us"] = round(t_roof * 1e6, 3)
    if time_us is not None and time_us > 0:
        t_s = time_us * 1e-6
        if has_bytes:
            out["achieved_bw_frac"] = _sig4(
                bytes_modeled / t_s / peak_bw)
        if has_flops:
            out["achieved_flops_frac"] = _sig4(
                flops_modeled / t_s / peak_flops)
        if t_roof is not None:
            out["roofline_frac"] = _sig4(t_roof / t_s)
    return out


def kernel_cost(spec, time_us: Optional[float] = None,
                memo: Optional[Dict] = None,
                peaks: Optional[Dict] = None) -> Dict:
    """One captured launch -> its full roofline row: modeled bytes
    (read/written split), modeled FLOPs (``None`` + a
    ``flops_model: "missing"`` marker when the kernel has no
    registered formula — the gap is also a gate finding), and the
    :func:`roofline_point` classification."""
    from ..analysis.kernel_catalog import modeled_flops
    from ..analysis.kernel_rules import modeled_launch_bytes

    bm = modeled_launch_bytes(spec, memo)
    flops = modeled_flops(spec)
    row = {"kernel": spec.name, "grid": list(spec.grid),
           "bytes_modeled": int(bm["total_bytes"]),
           "read_bytes": int(bm["read_bytes"]),
           "written_bytes": int(bm["written_bytes"]),
           "flops_modeled": flops,
           "flops_model": "formula" if flops is not None else "missing"}
    row.update(roofline_point(row["bytes_modeled"], flops,
                              time_us=time_us, peaks=peaks))
    return row


def capture_kernel_costs(fn: Callable, *args,
                         times_us: Optional[Dict[str, float]] = None
                         ) -> List[Dict]:
    """Trace ``fn(*args)`` under launch capture (``jax.eval_shape`` —
    abstract, no compute) and price every captured launch. ``times_us``
    optionally maps kernel name -> measured microseconds to fill the
    achieved fractions."""
    import jax

    from ..ops.pallas._util import capture_kernel_launches

    with capture_kernel_launches() as specs:
        jax.eval_shape(fn, *args)
    peaks = peak_snapshot()
    times_us = times_us or {}
    return [kernel_cost(s, time_us=times_us.get(s.name), peaks=peaks)
            for s in specs]


# -- per-decode-variant step model (engine metrics / trace_summary) -----


def decode_step_bytes(B: int, D: int, H: int, KV: int, hd: int, F: int,
                      BS: int, MB: int, act_itemsize: float = 2,
                      weight_itemsize: float = 2,
                      pool_itemsize: float = 2) -> Dict[str, int]:
    """Closed-form modeled HBM bytes for ONE decode step of each
    dispatch arm, at full occupancy (``B`` live rows, full ``MB``-page
    block tables — the same max-traffic convention as the kernel-level
    model). The arms differ exactly where the transition-count model
    says they differ:

    - ``pallas_block`` (single launch): attention weights resident
      once, but the MLP weight tiles REFETCH per batch row (the grid
      walks ``(B, attn_steps + mlp_tiles)``, so every row re-streams
      the MLP weights) — the B× term that makes block-vs-two-kernel
      arbitration a bytes question;
    - ``pallas_fused`` (attn kernel + mlp kernel): every weight read
      once, one extra residual round-trip between the launches;
    - ``unfused`` (reference composition): every weight read once plus
      the materialised intermediates (q/k/v/attn-out activations and
      the (B, F) gate/up/swish tensors) round-tripping through HBM.

    Weight scales / sin-cos rows / block tables are small and
    deliberately ignored. Returns bytes per variant name.
    """
    Hhd, KVhd = H * hd, KV * hd
    w_attn = (D * Hhd + 2 * D * KVhd + Hhd * D) * weight_itemsize
    w_mlp = 3 * D * F * weight_itemsize
    kv = 2 * B * MB * BS * KVhd * pool_itemsize
    x = B * D * act_itemsize
    return {
        # x in + out, new k/v rows out are ~B*KVhd (ignored: << kv)
        "pallas_block": int(w_attn + B * w_mlp + kv + 2 * x),
        # attn: x in, x' out; mlp: x' in, y out
        "pallas_fused": int(w_attn + w_mlp + kv + 4 * x),
        # norms + q/k/v/o + attn-out + mlp in/out: ~10 activation
        # round-trips of (B, D) + gate/up/swish (B, F) materialised
        "unfused": int(w_attn + w_mlp + kv + 10 * x
                       + 6 * B * F * act_itemsize),
    }


def decode_roofline(step_bytes: Dict[str, int],
                    measured_us: Optional[Dict[str, float]] = None,
                    peaks: Optional[Dict] = None) -> Dict:
    """The engine-metrics roofline sub-dict: per-variant modeled
    bytes/step and the bandwidth-bound lower-bound step time, plus
    achieved-bandwidth fraction where a measured mean step time is
    known (``measured_us``: variant -> microseconds)."""
    peaks = peaks or peak_snapshot()
    peak_bw = peaks["peak_hbm_bw"]
    measured_us = measured_us or {}
    variants = {}
    for name, nbytes in step_bytes.items():
        t_bw_us = nbytes / peak_bw * 1e6
        row = {"bytes_per_step": int(nbytes),
               "step_us_at_peak_bw": round(t_bw_us, 3),
               "achieved_bw_frac": None}
        t = measured_us.get(name)
        if t:
            row["achieved_bw_frac"] = _sig4(t_bw_us / t)
        variants[name] = row
    return {"variants": variants, "peak_hbm_bw": peak_bw,
            "peak_source": peaks["peak_source"]}


def roofline_chrome_events(report: Dict, t_us: float = 0.0) -> List[Dict]:
    """Render a :func:`decode_roofline` report (or any
    ``{"variants": {name: {...}}}`` mapping) as chrome-trace counter
    events — one ``roofline:<name>`` annotation track per arm carrying
    the modeled bytes/step, so the Perfetto view of a serving trace
    shows the bandwidth-bound floor next to the measured rows."""
    events = []
    for name, row in sorted(report.get("variants", {}).items()):
        events.append({"name": f"roofline:{name}", "ph": "C",
                       "ts": t_us,
                       "args": {"bytes_per_step":
                                row.get("bytes_per_step", 0)}})
    return events
