"""Stall diagnostics: flight-recorder dumps for a starved scheduler.

When ``drain()`` starves (queued work that can never admit) or a step
blows its deadline, an exception string is not a diagnosis. Reusing
the pattern of ``distributed/flight_recorder.py``: dump the timeline
ring buffer tail plus a scheduler snapshot (queue depth, slot phases,
per-slot seq_len, free pages, prefix-cache state) as one JSON report —
to a file when a path is configured, to stderr otherwise.
"""
from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, Optional

__all__ = ["dump_stall"]


def dump_stall(reason: str, scheduler: Dict, timeline_tail,
               metrics: Optional[Dict] = None,
               path: Optional[str] = None) -> str:
    """Write one stall report; returns the path written (or "" when the
    report went to stderr). Dumping must never raise into the engine —
    a failed write degrades to stderr."""
    report = {
        "reason": reason,
        "pid": os.getpid(),
        "time": time.time(),
        "iso": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "scheduler": scheduler,
        "metrics": metrics or {},
        "timeline_tail": list(timeline_tail),
    }
    text = json.dumps(report, indent=1, default=str)
    if path:
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(text)
            os.replace(tmp, path)
            return path
        except OSError as e:
            sys.stderr.write(f"[stall-dump] write to {path} failed "
                             f"({e}); falling back to stderr\n")
    sys.stderr.write(f"[stall-dump] {reason}\n{text}\n")
    return ""
