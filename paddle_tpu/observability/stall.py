"""Stall diagnostics: flight-recorder dumps for a starved scheduler.

When ``drain()`` starves (queued work that can never admit) or a step
blows its deadline, an exception string is not a diagnosis. Reusing
the pattern of ``distributed/flight_recorder.py``: dump the timeline
ring buffer tail plus a scheduler snapshot (queue depth, slot phases,
per-slot seq_len, free pages, prefix-cache state) as one JSON report —
to a file when a path is configured, to stderr otherwise.
"""
from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, Optional

__all__ = ["dump_stall", "dump_path_for"]


def dump_path_for(base: Optional[str], n_files: int, max_dumps: int):
    """The shared dump-retention policy (``Observability.stall_dump``
    and ``FlightRecorder.dump`` — one implementation, so the layers
    cannot diverge): returns ``(path, suppressed)``. ``n_files`` is
    the number of files ALREADY WRITTEN for this ``base`` (the caller
    owns that count — per base path, surviving window resets, so a
    re-enabled recorder can never hand a new hang the first report's
    path to clobber).

    - no ``base`` configured: always stderr (path None), never capped —
      console diagnostics must not go dark on a long-flapping failure;
    - first file lands at ``base``, later ones at uniquely-suffixed
      ``root.N.ext`` so a second report never clobbers the first;
    - only written files count against ``max_dumps``; past the cap the
      report is suppressed (``suppressed=True``) instead of scribbling
      over history or filling the disk.
    """
    if not base:
        return None, False
    if n_files >= max_dumps:
        return None, True
    if n_files:
        # splitext, not rpartition: a dot in a parent directory must
        # not get the counter spliced into it
        root, ext = os.path.splitext(base)
        return f"{root}.{n_files}{ext}", False
    return base, False


def dump_stall(reason: str, scheduler: Dict, timeline_tail,
               metrics: Optional[Dict] = None,
               path: Optional[str] = None,
               extra: Optional[Dict] = None) -> str:
    """Write one stall report; returns the path written (or "" when the
    report went to stderr). Dumping must never raise into the engine —
    a failed write degrades to stderr. ``extra`` merges additional
    top-level fields (the flight recorder rides its ring entries and
    clock base through here so every dump shares ONE format)."""
    report = {
        "reason": reason,
        "pid": os.getpid(),
        "time": time.time(),
        "iso": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "scheduler": scheduler,
        "metrics": metrics or {},
        "timeline_tail": list(timeline_tail),
    }
    if extra:
        report.update(extra)
    text = json.dumps(report, indent=1, default=str)
    if path:
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(text)
            os.replace(tmp, path)
            return path
        except OSError as e:
            sys.stderr.write(f"[stall-dump] write to {path} failed "
                             f"({e}); falling back to stderr\n")
    sys.stderr.write(f"[stall-dump] {reason}\n{text}\n")
    return ""
