"""Continuous telemetry plane (r22): time-series sampling over the
``metrics()`` protocol, OpenMetrics exposition, SLO burn-rate alerts.

Every component in the stack (ServingEngine, DisaggregatedEngine,
ServingFleet, Trainer) exposes a pull-on-demand ``metrics()`` snapshot.
This module makes those snapshots *continuous*: a :class:`TelemetryPlane`
holds registered sources, samples them on a step cadence into bounded
in-memory time-series (flattened dotted paths, ``per_class``/
``per_replica`` sub-trees lifted into labels, counter→rate derivation),
and exports two ways —

* ``expose()`` renders a Prometheus/OpenMetrics text exposition
  (``# HELP``/``# TYPE`` per family, ``_total`` counters, sanitized
  names, ``# EOF`` terminator); ``lint_exposition`` checks any such
  text against the scrape grammar so a hostile metric key
  (``collective_psum@tp_ms``) can never silently ship unscrapeable.
* ``write_jsonl()`` / the incremental ``jsonl_path`` bank persist the
  sample log as rotated JSONL next to the existing timeline banks.

On top of the series sit two alerting layers, both evaluated at sample
time on the host (no device syncs, deterministic under an injected
``clock``):

* **multi-window SLO burn-rate** over the scheduler's new
  ``slo_seen``/``slo_attained`` counters — burn = windowed error rate /
  error budget; a *page* fires when BOTH the fast and slow windows
  exceed ``page_burn_rate`` (Google-SRE 14.4 default), a *ticket* at
  ``ticket_burn_rate``. Windows are counted in samples, not seconds,
  so tier-1 tests are exact.
* **robust anomaly detectors** (rolling median + MAD): p95 decode-step
  / TTFT drift, queue-depth growth, warm-hit-ratio collapse,
  preemption storms, tokens/s collapse. Each fire lands an ``alert``
  timeline event and (for pages) a flight-recorder dump via the
  component's ``on_alert`` callback.

The overhead contract mirrors PR 3: a component built with
``telemetry=False`` never constructs a plane; an enabled plane touches
only host-side numbers already materialised by ``metrics()``.
"""
from __future__ import annotations

import io
import json
import math
import os
import re
import sys
import time
from collections import deque
from dataclasses import dataclass, field
from typing import (Any, Callable, Deque, Dict, List, Optional, Sequence,
                    Tuple)

__all__ = [
    "TelemetryConfig", "TelemetryPlane", "TimeSeries",
    "flatten_metrics", "render_exposition", "lint_exposition",
    "DEFAULT_DETECTORS",
]

# ---------------------------------------------------------------------------
# flattening

# metric sub-trees whose keys are dynamic identities, not metric names:
# lift the key into a label so the series name stays a closed set
_LABEL_SUBTREES = {"per_class": "cls", "per_replica": "replica"}

# top-level keys never sampled: "telemetry" is the plane's own snapshot
# (sampling it would recurse), the rest are large static/structural
# blobs with their own dedicated readouts
_DEFAULT_SKIP = ("telemetry", "roofline", "roofline_replicas", "collectives")


def flatten_metrics(tree: Dict[str, Any], skip: Sequence[str] = ()
                    ) -> List[Tuple[str, Tuple[Tuple[str, str], ...], float]]:
    """Flatten a ``metrics()`` dict into ``(path, labels, value)`` rows.

    Nested dicts join with ``.``; only finite int/float leaves survive
    (bools/strings/lists are identity, not measurement). ``per_class`` /
    ``per_replica`` sub-trees keep their path segment but move the child
    key into a ``cls`` / ``replica`` label. ``skip`` names top-level
    keys to drop (always includes the plane defaults).
    """
    drop = set(_DEFAULT_SKIP)
    drop.update(skip)
    out: List[Tuple[str, Tuple[Tuple[str, str], ...], float]] = []

    def walk(node, prefix, labels):
        if isinstance(node, dict):
            for k, v in node.items():
                k = str(k)
                if not prefix and k in drop:
                    continue
                path = prefix + "." + k if prefix else k
                if k in _LABEL_SUBTREES and isinstance(v, dict):
                    lname = _LABEL_SUBTREES[k]
                    for lval, sub in v.items():
                        walk(sub, path, labels + ((lname, str(lval)),))
                    continue
                walk(v, path, labels)
            return
        if isinstance(node, bool) or not isinstance(node, (int, float)):
            return
        v = float(node)
        if math.isfinite(v):
            out.append((prefix, labels, v))

    walk(tree, "", ())
    return out


# ---------------------------------------------------------------------------
# series

class TimeSeries:
    """One bounded series: ``(t, step, value)`` triples for a flattened
    metric path + label set. ``kind`` is ``"counter"`` (monotone source
    counter — gets a derived ``_per_s`` rate sibling and a ``_total``
    exposition suffix) or ``"gauge"``."""

    __slots__ = ("path", "labels", "kind", "samples")

    def __init__(self, path: str, labels: Tuple[Tuple[str, str], ...],
                 kind: str, capacity: int):
        self.path = path
        self.labels = labels
        self.kind = kind
        self.samples: Deque[Tuple[float, int, float]] = deque(maxlen=capacity)

    def add(self, t: float, step: int, value: float) -> None:
        self.samples.append((t, step, value))

    @property
    def last(self) -> Optional[Tuple[float, int, float]]:
        return self.samples[-1] if self.samples else None

    def values(self) -> List[float]:
        return [v for _, _, v in self.samples]


class _Source:
    __slots__ = ("name", "fn", "labels", "counter_names", "skip")

    def __init__(self, name, fn, labels, counter_names, skip):
        self.name = name
        self.fn = fn
        self.labels = labels
        self.counter_names = counter_names
        self.skip = skip


# ---------------------------------------------------------------------------
# config

# default anomaly detector specs; ``path`` matches the flattened series
# path exactly (rate series end in ``_per_s``)
DEFAULT_DETECTORS: Tuple[Dict[str, Any], ...] = (
    {"rule": "drift_up", "path": "latency.decode_step_ms.p95",
     "severity": "ticket"},
    {"rule": "drift_up", "path": "latency.ttft_ms.p95",
     "severity": "ticket"},
    {"rule": "growth", "path": "scheduler.queue_depth",
     "severity": "ticket"},
    {"rule": "collapse", "path": "routing.warm_hit_ratio",
     "severity": "ticket"},
    {"rule": "storm", "path": "preemptions_per_s", "severity": "page"},
    {"rule": "collapse", "path": "tokens_per_sec", "severity": "page"},
)


@dataclass
class TelemetryConfig:
    """Knobs for the telemetry plane. All windows count *samples* so
    behaviour is exact under a fake ``clock`` in tests."""

    sample_every: int = 8          # steps between samples
    series_capacity: int = 512     # points kept per series
    namespace: str = "paddle_tpu"  # exposition name prefix

    # --- SLO burn-rate alerting (over scheduler.slo_seen/slo_attained)
    slo_target: float = 0.99
    burn_fast_window: int = 8      # samples
    burn_slow_window: int = 64     # samples (clamped to history)
    page_burn_rate: float = 14.4
    ticket_burn_rate: float = 3.0

    # --- robust anomaly detection
    detectors: Optional[Tuple[Dict[str, Any], ...]] = None  # None → defaults
    anomaly_window: int = 32       # history points fed to median/MAD
    anomaly_min_samples: int = 12  # history required before judging
    anomaly_mad_k: float = 6.0     # drift threshold: med + k*MAD
    collapse_frac: float = 0.5     # collapse: cur < frac*median
    growth_min: float = 4.0        # growth: monotone rise >= this much
    storm_min: float = 1.0         # storm: absolute floor on the rate

    alert_cooldown: int = 8        # samples between re-fires per rule
    page_dumps: bool = True        # page alerts request a stall dump

    # --- export
    jsonl_path: Optional[str] = None   # incremental rotated bank
    jsonl_max_bytes: int = 4 << 20
    jsonl_backups: int = 2
    exposition_path: Optional[str] = None  # rewritten every sample

    # injectable monotonic clock (tests); None → time.perf_counter
    clock: Optional[Callable[[], float]] = None

    @staticmethod
    def coerce(value) -> Optional["TelemetryConfig"]:
        """Normalise a ``telemetry=`` kwarg: falsy → None (disabled),
        ``True`` → defaults, a config instance → itself."""
        if not value:
            return None
        if value is True:
            return TelemetryConfig()
        if isinstance(value, TelemetryConfig):
            return value
        raise TypeError("telemetry= expects bool or TelemetryConfig, got "
                        f"{type(value).__name__}")


# ---------------------------------------------------------------------------
# exposition

_METRIC_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
_LABEL_NAME_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*\Z")
_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_:]")
_SAMPLE_LINE_RE = re.compile(
    r"([^{\s]+)(\{[^}]*\})?\s+([0-9.eE+\-NnAaIiFf]+)\Z")


def _metric_name(namespace: str, path: str, kind: str) -> str:
    name = _SANITIZE_RE.sub("_", f"{namespace}_{path}" if namespace else path)
    if not name or not (name[0].isalpha() or name[0] in "_:"):
        name = "_" + name
    if kind == "counter" and not name.endswith("_total"):
        name += "_total"
    return name


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def render_exposition(series: Sequence[TimeSeries],
                      namespace: str = "paddle_tpu") -> str:
    """Render the latest point of each series as Prometheus/OpenMetrics
    text. Series sharing a (sanitized) family name are grouped under one
    ``# HELP``/``# TYPE`` block; counters win the type vote if mixed."""
    fams: Dict[str, Dict[str, Any]] = {}
    for s in series:
        if not s.samples:
            continue
        name = _metric_name(namespace, s.path, s.kind)
        fam = fams.setdefault(name, {"type": "gauge", "help": s.path,
                                     "rows": []})
        if s.kind == "counter":
            fam["type"] = "counter"
        lbl = ""
        if s.labels:
            pairs = ",".join(f'{_SANITIZE_RE.sub("_", k)}="'
                             f'{_escape_label(str(v))}"'
                             for k, v in s.labels)
            lbl = "{" + pairs + "}"
        fam["rows"].append((lbl, s.samples[-1][2]))
    out = io.StringIO()
    for name in sorted(fams):
        fam = fams[name]
        out.write(f"# HELP {name} sampled from metrics() path "
                  f"{fam['help']}\n")
        out.write(f"# TYPE {name} {fam['type']}\n")
        for lbl, v in sorted(fam["rows"]):
            out.write(f"{name}{lbl} {_fmt_value(v)}\n")
    out.write("# EOF\n")
    return out.getvalue()


def lint_exposition(text: str) -> List[str]:
    """Validate exposition text against the scrape grammar. Returns a
    list of problems (empty == clean): bad metric/label names, samples
    without a preceding ``# TYPE``/``# HELP``, counter families missing
    the ``_total`` suffix, duplicate TYPE lines, missing ``# EOF``."""
    problems: List[str] = []
    lines = text.splitlines()
    if not lines or lines[-1].strip() != "# EOF":
        problems.append("missing # EOF terminator")
    typed: Dict[str, str] = {}
    helped: set = set()
    for ln, line in enumerate(lines, 1):
        if not line.strip():
            continue
        if line.strip() == "# EOF":
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) < 4:
                problems.append(f"line {ln}: HELP without text")
            else:
                helped.add(parts[2])
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                problems.append(f"line {ln}: malformed TYPE line")
                continue
            name, typ = parts[2], parts[3]
            if not _METRIC_NAME_RE.match(name):
                problems.append(f"line {ln}: invalid metric name {name!r}")
            if typ not in ("counter", "gauge", "histogram", "summary",
                           "untyped", "info"):
                problems.append(f"line {ln}: unknown type {typ!r}")
            if name in typed:
                problems.append(f"line {ln}: duplicate TYPE for {name}")
            if typ == "counter" and not name.endswith("_total"):
                problems.append(f"line {ln}: counter {name} lacks _total")
            typed[name] = typ
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_LINE_RE.match(line.strip())
        if m is None:
            problems.append(f"line {ln}: unparseable sample {line!r}")
            continue
        name, lbl, val = m.group(1), m.group(2), m.group(3)
        if not _METRIC_NAME_RE.match(name):
            problems.append(f"line {ln}: invalid metric name {name!r}")
        if name not in typed:
            problems.append(f"line {ln}: sample for {name} before TYPE")
        if name not in helped:
            problems.append(f"line {ln}: sample for {name} without HELP")
        if lbl:
            for pair in re.findall(r'([^{,=]+)="((?:[^"\\]|\\.)*)"',
                                   lbl):
                if not _LABEL_NAME_RE.match(pair[0]):
                    problems.append(
                        f"line {ln}: invalid label name {pair[0]!r}")
            if not re.match(r'\{([^{,=]+="(?:[^"\\]|\\.)*")'
                            r'(,[^{,=]+="(?:[^"\\]|\\.)*")*\}\Z', lbl):
                problems.append(f"line {ln}: malformed label set {lbl!r}")
        try:
            float(val)
        except ValueError:
            problems.append(f"line {ln}: non-numeric value {val!r}")
    return problems


# ---------------------------------------------------------------------------
# robust statistics

def _median(vals: Sequence[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if n == 0:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _mad(vals: Sequence[float], med: float) -> float:
    return _median([abs(v - med) for v in vals])


# ---------------------------------------------------------------------------
# the plane

class TelemetryPlane:
    """Samples registered ``metrics()`` sources into bounded series and
    evaluates burn-rate + anomaly rules on every sample. See module
    docstring for the full contract."""

    def __init__(self, config: Optional[TelemetryConfig] = None,
                 on_alert: Optional[Callable[[Dict[str, Any]], None]] = None):
        self.config = config or TelemetryConfig()
        cfg = self.config
        self._clock = cfg.clock or time.perf_counter
        self._sources: List[_Source] = []
        self._series: Dict[Tuple[str, Tuple], TimeSeries] = {}
        self._ticks = 0
        self._samples = 0
        self._sample_log: Deque[Dict[str, Any]] = deque(
            maxlen=max(cfg.series_capacity, 16))
        self.alerts: Deque[Dict[str, Any]] = deque(maxlen=256)
        self.alert_counts: Dict[str, int] = {"page": 0, "ticket": 0}
        self.rule_counts: Dict[str, int] = {}
        self._last_fire: Dict[Any, int] = {}
        self._on_alert = on_alert
        self._detectors = tuple(cfg.detectors if cfg.detectors is not None
                                else DEFAULT_DETECTORS)
        self._bank_fresh = True
        self._bank_dead = False

    # -- registration ------------------------------------------------------

    def register(self, name: str, metrics_fn: Callable[[], Dict[str, Any]],
                 labels: Optional[Dict[str, str]] = None,
                 counters: Optional[Dict[str, Any]] = None,
                 skip: Sequence[str] = ()) -> None:
        """Add a source. ``labels`` attach to every series it emits
        (after the implicit ``component`` label); ``counters`` names the
        component's monotone counter dict so its top-level paths get
        counter semantics (rates + ``_total``); ``skip`` drops extra
        top-level metric keys for this source."""
        base = (("component", name),) + tuple(
            sorted((labels or {}).items()))
        cnames = frozenset(str(k) for k in (counters or {}))
        self._sources.append(_Source(name, metrics_fn, base, cnames,
                                     tuple(skip)))

    # -- sampling ----------------------------------------------------------

    def on_step(self) -> None:
        """Per-step tick; samples every ``sample_every`` steps."""
        self._ticks += 1
        if self._ticks % self.config.sample_every == 0:
            self.sample()

    def sample(self) -> None:
        """Take one sample of every source now and run the alert rules."""
        cfg = self.config
        t = self._clock()
        self._samples += 1
        step = self._ticks
        values: Dict[str, float] = {}
        for src in self._sources:
            try:
                tree = src.fn()
            except Exception as e:  # a dying source must not kill the loop
                print(f"paddle_tpu telemetry: source {src.name!r} failed: "
                      f"{e}", file=sys.stderr)
                continue
            for path, extra, v in flatten_metrics(tree, skip=src.skip):
                self._record(src, path, src.labels + extra, v, t, step,
                             values)
        rec = {"kind": "sample", "i": self._samples, "step": step,
               "t": round(t, 6), "values": values}
        self._sample_log.append(rec)
        self._bank(rec)
        for alert in self._evaluate(t, step):
            self._fire(alert)
        if cfg.exposition_path:
            self.write_exposition()

    def _record(self, src, path, labels, v, t, step, values):
        cfg = self.config
        key = (path, labels)
        s = self._series.get(key)
        if s is None:
            kind = ("counter" if path.split(".", 1)[0] in src.counter_names
                    else "gauge")
            s = self._series[key] = TimeSeries(path, labels, kind,
                                               cfg.series_capacity)
        prev = s.last
        s.add(t, step, v)
        values[_series_id(path, labels)] = v
        if s.kind == "counter" and prev is not None:
            dt, dv = t - prev[0], v - prev[2]
            # negative delta == counter reset (reset_metrics): skip
            if dt > 0.0 and dv >= 0.0:
                rpath = path + "_per_s"
                rkey = (rpath, labels)
                rs = self._series.get(rkey)
                if rs is None:
                    rs = self._series[rkey] = TimeSeries(
                        rpath, labels, "gauge", cfg.series_capacity)
                rate = dv / dt
                rs.add(t, step, rate)
                values[_series_id(rpath, labels)] = rate

    # -- alert rules -------------------------------------------------------

    def _evaluate(self, t: float, step: int) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        cfg = self.config
        # 1) multi-window SLO burn rate, per label-set that carries the
        #    scheduler counters (the fleet sees one per replica)
        for (path, labels), seen in list(self._series.items()):
            if path != "scheduler.slo_seen":
                continue
            att = self._series.get(("scheduler.slo_attained", labels))
            if att is None:
                continue
            fast = self._burn_rate(seen, att, cfg.burn_fast_window)
            slow = self._burn_rate(seen, att, cfg.burn_slow_window)
            if fast is None or slow is None:
                continue
            sev = None
            if fast >= cfg.page_burn_rate and slow >= cfg.page_burn_rate:
                sev = "page"
            elif (fast >= cfg.ticket_burn_rate
                  and slow >= cfg.ticket_burn_rate):
                sev = "ticket"
            if sev is None:
                continue
            rid = ("slo_burn_rate", labels)
            if not self._cooldown_ok(rid):
                continue
            thr = (cfg.page_burn_rate if sev == "page"
                   else cfg.ticket_burn_rate)
            out.append({"rule": "slo_burn_rate", "severity": sev,
                        "metric": "scheduler.slo_burn_rate",
                        "labels": dict(labels),
                        "value": round(min(fast, slow), 4),
                        "fast": round(fast, 4), "slow": round(slow, 4),
                        "threshold": thr, "t": round(t, 6), "step": step,
                        "sample": self._samples})
        # 2) robust anomaly detectors
        for i, spec in enumerate(self._detectors):
            for (path, labels), s in list(self._series.items()):
                if path != spec["path"]:
                    continue
                hit = self._eval_detector(spec, s)
                if hit is None:
                    continue
                rid = (i, path, labels)
                if not self._cooldown_ok(rid):
                    continue
                value, threshold = hit
                out.append({"rule": spec["rule"],
                            "severity": spec.get("severity", "ticket"),
                            "metric": path, "labels": dict(labels),
                            "value": round(value, 4),
                            "threshold": round(threshold, 4),
                            "t": round(t, 6), "step": step,
                            "sample": self._samples})
        return out

    def _burn_rate(self, seen: TimeSeries, att: TimeSeries,
                   window: int) -> Optional[float]:
        ss, aa = list(seen.samples), list(att.samples)
        n = min(len(ss), len(aa))
        if n < 2:
            return None
        w = min(window, n - 1)
        s0, s1 = ss[n - 1 - w][2], ss[n - 1][2]
        a0, a1 = aa[n - 1 - w][2], aa[n - 1][2]
        dseen = s1 - s0
        if dseen <= 0:  # idle window or counter reset: nothing to judge
            return 0.0
        dbad = (s1 - a1) - (s0 - a0)
        if dbad < 0:
            return 0.0
        budget = max(1.0 - self.config.slo_target, 1e-9)
        return (dbad / dseen) / budget

    def _eval_detector(self, spec: Dict[str, Any], s: TimeSeries
                       ) -> Optional[Tuple[float, float]]:
        cfg = self.config
        vals = s.values()
        if len(vals) < 2:
            return None
        cur = vals[-1]
        hist = vals[:-1][-cfg.anomaly_window:]
        rule = spec["rule"]
        min_n = spec.get("min_samples", cfg.anomaly_min_samples)
        if rule == "drift_up":
            if len(hist) < min_n:
                return None
            med = _median(hist)
            # floor the spread so a dead-flat history doesn't page on
            # the first nanosecond of jitter
            floor = max(_mad(hist, med), 0.25 * abs(med), 1e-9)
            thr = med + spec.get("k", cfg.anomaly_mad_k) * floor
            return (cur, thr) if cur > thr else None
        if rule == "collapse":
            if len(hist) < min_n:
                return None
            med = _median(hist)
            frac = spec.get("frac", cfg.collapse_frac)
            if med > 1e-9 and cur < frac * med:
                return (cur, frac * med)
            return None
        if rule == "growth":
            need = max(min_n, 4)
            recent = vals[-need:]
            if len(recent) < need:
                return None
            rise = spec.get("min_rise", cfg.growth_min)
            if (all(b >= a for a, b in zip(recent, recent[1:]))
                    and recent[-1] - recent[0] >= rise):
                return (recent[-1], recent[0] + rise)
            return None
        if rule == "storm":
            if len(hist) < min_n:
                return None
            med = _median(hist)
            floor = max(_mad(hist, med), 0.25 * abs(med), 1e-9)
            thr = max(med + spec.get("k", cfg.anomaly_mad_k) * floor,
                      spec.get("min_abs", cfg.storm_min))
            return (cur, thr) if cur >= thr else None
        return None

    def _cooldown_ok(self, rule_id) -> bool:
        last = self._last_fire.get(rule_id)
        if (last is not None
                and self._samples - last < self.config.alert_cooldown):
            return False
        self._last_fire[rule_id] = self._samples
        return True

    def _fire(self, alert: Dict[str, Any]) -> None:
        self.alerts.append(alert)
        sev = alert.get("severity", "ticket")
        self.alert_counts[sev] = self.alert_counts.get(sev, 0) + 1
        rule = alert.get("rule", "?")
        self.rule_counts[rule] = self.rule_counts.get(rule, 0) + 1
        self._bank({"kind": "alert", **alert})
        if self._on_alert is not None:
            try:
                self._on_alert(alert)
            except Exception as e:
                print(f"paddle_tpu telemetry: on_alert failed: {e}",
                      file=sys.stderr)

    # -- export ------------------------------------------------------------

    def expose(self) -> str:
        """Return the current series as OpenMetrics text. Takes an
        initial sample if none has been taken yet."""
        if self._samples == 0:
            self.sample()
        return render_exposition(self._series.values(),
                                 namespace=self.config.namespace)

    def write_exposition(self, path: Optional[str] = None) -> Optional[str]:
        """Atomically write ``expose()`` to ``path`` (default: the
        configured ``exposition_path``). Never raises."""
        path = path or self.config.exposition_path
        if not path:
            return None
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(self.expose())
            os.replace(tmp, path)
            return path
        except OSError as e:
            print(f"paddle_tpu telemetry: exposition write failed: {e}",
                  file=sys.stderr)
            return None

    def write_jsonl(self, path: str) -> Optional[str]:
        """One-shot dump: meta line, the retained sample log, then every
        retained alert. Never raises."""
        try:
            with open(path, "w") as f:
                f.write(json.dumps(self._meta()) + "\n")
                for rec in self._sample_log:
                    f.write(json.dumps(rec) + "\n")
                for alert in self.alerts:
                    f.write(json.dumps({"kind": "alert", **alert}) + "\n")
            return path
        except OSError as e:
            print(f"paddle_tpu telemetry: jsonl write failed: {e}",
                  file=sys.stderr)
            return None

    def _meta(self) -> Dict[str, Any]:
        return {"kind": "telemetry_meta", "schema": 1,
                "namespace": self.config.namespace,
                "sample_every": self.config.sample_every,
                "samples": self._samples, "series": len(self._series),
                "sources": [s.name for s in self._sources]}

    def _bank(self, rec: Dict[str, Any]) -> None:
        """Append one record to the incremental JSONL bank, rotating at
        ``jsonl_max_bytes``. Never raises; a failing filesystem disables
        the bank for the rest of the run."""
        cfg = self.config
        if not cfg.jsonl_path or self._bank_dead:
            return
        path = cfg.jsonl_path
        try:
            if (not self._bank_fresh and os.path.exists(path)
                    and os.path.getsize(path) >= cfg.jsonl_max_bytes):
                self._rotate(path)
                self._bank_fresh = True
            if self._bank_fresh or not os.path.exists(path):
                with open(path, "w") as f:
                    f.write(json.dumps(self._meta()) + "\n")
                self._bank_fresh = False
            with open(path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError as e:
            self._bank_dead = True
            print(f"paddle_tpu telemetry: bank disabled ({e})",
                  file=sys.stderr)

    def _rotate(self, path: str) -> None:
        backups = max(self.config.jsonl_backups, 0)
        if backups == 0:
            os.remove(path)
            return
        for i in range(backups - 1, 0, -1):
            src = f"{path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{path}.{i + 1}")
        os.replace(path, f"{path}.1")

    # -- introspection -----------------------------------------------------

    def series(self) -> List[TimeSeries]:
        return list(self._series.values())

    def snapshot(self) -> Dict[str, Any]:
        """The frozen ``metrics()["telemetry"]`` sub-schema."""
        return {"samples": self._samples, "series": len(self._series),
                "alerts": {"page": self.alert_counts.get("page", 0),
                           "ticket": self.alert_counts.get("ticket", 0)},
                "rules": dict(sorted(self.rule_counts.items()))}


def _series_id(path: str, labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return path
    return path + "{" + ",".join(f"{k}={v}" for k, v in labels) + "}"
