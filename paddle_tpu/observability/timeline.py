"""Per-request lifecycle timelines in a bounded ring buffer.

Every serving request walks the same life: submit -> queued -> admit ->
prefill chunk(s) -> first_token -> decode steps -> finish. Recording
those transitions (host timestamps only — never a device sync) into a
ring buffer gives three things the flat counters could not:

- TTFT / TPOT / queue-wait *distributions* per request,
- a chrome trace (one row per request, exported through the existing
  ``profiler/`` machinery) a human can scrub in Perfetto,
- a flight-recorder tail: when the engine stalls, the last N events
  ARE the diagnosis.

The buffer is bounded (``capacity`` events, drop-oldest) so an engine
serving millions of requests holds a constant footprint; ``dropped``
counts what rolled off.
"""
from __future__ import annotations

import json
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["TimelineEvent", "Timeline"]

# canonical event taxonomy (DESIGN.md "observability" section); meta
# keys ride alongside, e.g. prefill_chunk carries pos0/n/bucket and
# train_step carries step/stage_ms/dispatch_ms/sync_ms
EVENT_NAMES = ("submit", "admit", "prefill_chunk", "first_token",
               "decode_step", "finish", "drain_truncated", "stall",
               "retrace", "prefix_evict",
               # training/multichip events (r9)
               "train_step", "compile", "host_gap", "collective",
               # fleet routing (r18) and telemetry alerts (r22)
               "route", "alert")


class TimelineEvent:
    __slots__ = ("t_ns", "name", "req_id", "dur_ms", "meta")

    def __init__(self, t_ns: int, name: str, req_id: Optional[int],
                 dur_ms: Optional[float], meta: Optional[Dict]):
        self.t_ns = t_ns
        self.name = name
        self.req_id = req_id
        self.dur_ms = dur_ms
        self.meta = meta

    def to_dict(self) -> Dict:
        d = {"t_ns": self.t_ns, "name": self.name}
        if self.req_id is not None:
            d["req_id"] = self.req_id
        if self.dur_ms is not None:
            d["dur_ms"] = round(self.dur_ms, 3)
        if self.meta:
            d.update(self.meta)
        return d


class Timeline:
    """Bounded ring of :class:`TimelineEvent` with chrome/JSONL export."""

    def __init__(self, capacity: int = 4096):
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self.dropped = 0
        self.t0_ns = time.perf_counter_ns()

    def __len__(self):
        return len(self._ring)

    def record(self, name: str, req_id: Optional[int] = None,
               dur_ms: Optional[float] = None, t_ns: Optional[int] = None,
               **meta):
        if len(self._ring) == self.capacity:
            self.dropped += 1
        self._ring.append(TimelineEvent(
            t_ns if t_ns is not None else time.perf_counter_ns(),
            name, req_id, dur_ms, meta or None))

    def events(self) -> List[TimelineEvent]:
        return list(self._ring)

    def tail(self, n: int = 256) -> List[Dict]:
        evs = list(self._ring)[-n:]
        return [e.to_dict() for e in evs]

    # -- chrome trace (through the profiler/ machinery) ----------------
    def to_host_events(self):
        """Render the ring as profiler ``HostEvent`` spans.

        Per request (one chrome row each, tid = req_id + 1):
        ``queued`` (submit -> admit), ``prefill`` (admit -> first
        token), ``decode`` (first token -> finish), plus each
        ``prefill_chunk`` with its measured duration. Scheduler-wide
        ``decode_step`` spans land on tid 0. Requests still in flight
        render the spans they have completed so far.
        """
        from ..profiler.record_event import HostEvent, TracerEventType

        per_req: Dict[int, Dict[str, TimelineEvent]] = {}
        host_events = []
        for ev in self._ring:
            if ev.req_id is not None and ev.name in (
                    "submit", "admit", "first_token", "finish"):
                per_req.setdefault(ev.req_id, {})[ev.name] = ev
            if ev.dur_ms is not None:
                tid = 0 if ev.req_id is None else ev.req_id + 1
                start = ev.t_ns - int(ev.dur_ms * 1e6)
                host_events.append(HostEvent(
                    ev.name, start, ev.t_ns,
                    TracerEventType.UserDefined, tid=tid))
        spans = (("queued", "submit", "admit"),
                 ("prefill", "admit", "first_token"),
                 ("decode", "first_token", "finish"))
        for rid, evs in per_req.items():
            for name, a, b in spans:
                if a in evs and b in evs:
                    host_events.append(HostEvent(
                        f"req{rid}:{name}", evs[a].t_ns, evs[b].t_ns,
                        TracerEventType.PythonUserDefined, tid=rid + 1))
        host_events.sort(key=lambda e: e.start_ns)
        return host_events

    def export_chrome(self, path: str, gauges: Optional[Dict] = None,
                      process_name: str = "paddle_tpu serving",
                      extra_host_events=None,
                      extra_events: Optional[List[Dict]] = None) -> str:
        """Write a chrome-trace json of the ring (plus gauge series as
        counter tracks, plus any pre-built ``extra_host_events`` spans —
        e.g. the flight recorder's per-rank collective tracks — plus
        raw ``extra_events`` chrome dicts, e.g. the per-kernel roofline
        annotation track) via the profiler's shared trace writer."""
        from ..profiler.profiler import write_chrome_trace

        extra = list(extra_events or ())
        for name, g in (gauges or {}).items():
            for t, v in g.series:
                if t is None:
                    continue
                # no explicit pid: the trace writer assigns the process
                # pid, keeping counters under the same Perfetto process
                # as the request rows
                extra.append({"name": name, "ph": "C",
                              "ts": t * 1e6,
                              "args": {"value": v}})
        host_events = self.to_host_events()
        if extra_host_events:
            host_events = sorted(host_events + list(extra_host_events),
                                 key=lambda e: e.start_ns)
        write_chrome_trace(path, host_events,
                           process_name=process_name, extra_events=extra)
        return path

    # -- JSONL ---------------------------------------------------------
    def write_jsonl(self, path: str, request_records=(),
                    header: Optional[Dict] = None) -> str:
        """Structured per-phase JSONL: one ``meta`` line, one ``event``
        line per ring entry, one ``request`` line per finished-request
        record — the raw material for ``tools/trace_summary.py`` and
        for BENCH captures that carry distributions."""
        with open(path, "w") as f:
            meta = {"kind": "meta", "schema": 1,
                    "t0_ns": self.t0_ns, "events": len(self._ring),
                    "dropped": self.dropped}
            if header:
                meta.update(header)
            f.write(json.dumps(meta) + "\n")
            for ev in self._ring:
                f.write(json.dumps({"kind": "event", **ev.to_dict()})
                        + "\n")
            for rec in request_records:
                f.write(json.dumps({"kind": "request", **rec}) + "\n")
        return path
