"""Retrace watchdog: steady state is 1 decode program, <=1 per bucket.

The engine's whole design guarantees zero steady-state retraces — but
nothing *enforced* it at runtime. A silent retrace storm (a shape leak,
a weak-ref'd jit cache eviction, a new dtype sneaking into the carry)
costs seconds per occurrence and today is invisible until a bench
regresses. The watchdog snapshots the trace counters once warmup is
declared and warns (``RuntimeWarning`` + a recorded event) the moment
any program traces again.
"""
from __future__ import annotations

import warnings
from typing import Dict, List, Optional

__all__ = ["RetraceWatchdog"]


class RetraceWatchdog:
    """Compare-and-warn over the engine's trace counters.

    ``mark_warmup(counters)`` freezes the baseline; each ``check``
    diffs against it and warns once per *new* retrace (the baseline
    advances, so one storm does not emit per-step noise forever).
    """

    def __init__(self, warn: bool = True):
        self.warn = warn
        self._base: Optional[Dict] = None
        self.events: List[Dict] = []

    @staticmethod
    def _snap(counters: Dict) -> Dict:
        return {"decode": counters.get("decode_traces", 0),
                "calibration": counters.get("calibration_traces", 0),
                "prefill": dict(counters.get("prefill_traces", {}))}

    @property
    def armed(self) -> bool:
        return self._base is not None

    def mark_warmup(self, counters: Dict):
        """Declare warmup complete: any trace-count growth past this
        point is a steady-state retrace."""
        self._base = self._snap(counters)

    def check(self, counters: Dict) -> int:
        """Diff against the warmup baseline; returns the number of new
        retrace findings (0 when disarmed or clean)."""
        if self._base is None:
            return 0
        cur = self._snap(counters)
        findings = []
        if cur["decode"] > self._base["decode"]:
            findings.append(
                {"program": "decode",
                 "traces": cur["decode"] - self._base["decode"]})
        if cur["calibration"] > self._base["calibration"]:
            findings.append(
                {"program": "calibration",
                 "traces": cur["calibration"] - self._base["calibration"]})
        for bucket, n in cur["prefill"].items():
            base_n = self._base["prefill"].get(bucket, 0)
            if n > base_n:
                findings.append({"program": f"prefill[{bucket}]",
                                 "traces": n - base_n})
        if findings:
            self.events.extend(findings)
            self._base = cur       # warn once per retrace, not per step
            if self.warn:
                detail = ", ".join(f"{f['program']} +{f['traces']}"
                                   for f in findings)
                warnings.warn(
                    f"ServingEngine retrace after warmup: {detail} — "
                    "steady state should be 1 decode program and <=1 "
                    "trace per prefill bucket; a retrace storm here "
                    "silently eats the bench window", RuntimeWarning,
                    stacklevel=3)
        return len(findings)
