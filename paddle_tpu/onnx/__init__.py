"""paddle_tpu.onnx: real ONNX export.

reference: python/paddle/onnx/export.py (shells out to paddle2onnx, a
ProgramDesc -> ONNX translator). Here the converter is first-party:
``export`` traces the layer to a jaxpr (parameters closed over as
constants -> graph initializers) and ``exporter.jaxpr_to_onnx`` maps jax
primitives onto ONNX opset-17 ops. The schema bindings are vendored
(onnx.proto), so no external onnx package is needed to WRITE models;
the serialized file uses upstream field numbers and loads in
onnx/onnxruntime. ``runner.run_model`` is a bundled numpy evaluator used
by tests for numeric verification.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=17, **configs):
    """reference: python/paddle/onnx/export.py export — writes
    ``path + '.onnx'`` and returns that filename.

    ``layer``: a Layer (uses ``.functional()``) or a plain callable over
    Tensors. ``input_spec``: list of InputSpec / example arrays fixing
    the traced input shapes (None dims are exported at 1)."""
    import jax
    import jax.numpy as jnp
    from ..core.tensor import Tensor, to_value
    from ..nn import Layer
    from ..static import InputSpec
    from .exporter import jaxpr_to_onnx

    if not 13 <= opset_version <= 17:
        raise ValueError(
            f"opset_version {opset_version} unsupported: the exporter "
            "emits opset-13+ op forms (ReduceSum with axes input, "
            "Einsum) and declares opset 17; pass 13..17")

    if input_spec is None:
        raise ValueError("onnx.export requires input_spec")
    example = []
    for spec in input_spec:
        if isinstance(spec, InputSpec):
            shape = [1 if (s is None or int(s) < 0) else int(s)
                     for s in spec.shape]
            example.append(jnp.zeros(tuple(shape), spec.dtype))
        elif isinstance(spec, Tensor):
            example.append(to_value(spec))
        else:
            example.append(jnp.asarray(spec))

    was_training = False
    if isinstance(layer, Layer):
        # export traces inference behavior; restore the caller's mode
        # after tracing (pure_fn reads layer state at trace time)
        was_training = layer.training
        layer.eval()
        pure_fn, params, buffers = layer.functional()

        def fn(*xs):
            out, _ = pure_fn(params, buffers, *xs)
            return out
    else:
        def fn(*xs):
            out = layer(*tuple(Tensor(x) for x in xs))
            return jax.tree_util.tree_map(
                lambda o: to_value(o) if isinstance(o, Tensor) else o, out,
                is_leaf=lambda o: isinstance(o, Tensor))

    try:
        closed = jax.make_jaxpr(fn)(*example)
    finally:
        if was_training:
            layer.train()
    input_names = [f"x{i}" for i in range(len(example))]
    model = jaxpr_to_onnx(closed, input_names,
                          graph_name=type(layer).__name__,
                          opset_version=opset_version)
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(model.SerializeToString())
    return out_path
