"""paddle_tpu.onnx (reference: python/paddle/onnx/export.py, which shells
out to paddle2onnx).

This environment ships no ``onnx``/converter package, so true .onnx
serialization is gated; ``export`` still produces a portable serialized
model — the StableHLO program + weights that ``paddle.jit.save`` emits
(StableHLO is the interchange format of the XLA ecosystem, playing the
role .onnx plays for the reference's deployment path).
"""
from __future__ import annotations

import os

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """reference: python/paddle/onnx/export.py export."""
    try:
        import onnx  # noqa: F401
        raise NotImplementedError(
            "onnx is importable but no StableHLO->ONNX converter is "
            "bundled; use the StableHLO artifact from paddle.jit.save "
            "for deployment")
    except ImportError:
        pass
    from ..jit import save as jit_save
    jit_save(layer, path, input_spec=input_spec)
    import warnings
    warnings.warn(
        f"onnx package unavailable — exported StableHLO + weights to "
        f"{path}* instead (loadable via paddle.jit.load / any StableHLO "
        "runtime)", stacklevel=2)
    return path
