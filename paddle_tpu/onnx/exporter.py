"""jaxpr -> ONNX converter.

The reference delegates ONNX export to paddle2onnx (a ProgramDesc ->
ONNX graph translator, python/paddle/onnx/export.py). Here the source IR
is the jaxpr of the traced function: constants (parameters, folded
subexpressions) become graph initializers, jax primitives map to ONNX
ops via the handler table below, and anything not reachable from the
graph inputs is constant-folded by evaluating the primitive eagerly.

Emitted opset: 17 (Einsum needs >= 12; ReduceSum-with-axes-input needs
>= 13). The schema bindings are vendored (onnx.proto / onnx_pb2.py) —
serialized models carry upstream field numbers, so onnx/onnxruntime can
load them; tests verify numerics with the bundled numpy runner
(runner.py) since onnxruntime is not shipped in this environment.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.extend import core as jex_core

from . import onnx_pb2 as ox

OPSET = 17

_DTYPE_MAP = {
    "float32": ox.TensorProto.FLOAT, "float64": ox.TensorProto.DOUBLE,
    "float16": ox.TensorProto.FLOAT16, "bfloat16": ox.TensorProto.BFLOAT16,
    "int64": ox.TensorProto.INT64, "int32": ox.TensorProto.INT32,
    "int16": ox.TensorProto.INT16, "int8": ox.TensorProto.INT8,
    "uint8": ox.TensorProto.UINT8, "uint32": ox.TensorProto.UINT32,
    "uint64": ox.TensorProto.UINT64, "bool": ox.TensorProto.BOOL,
}


class UnsupportedOp(NotImplementedError):
    pass


def _onnx_dtype(dt) -> int:
    name = str(np.dtype(dt)) if not str(dt).startswith("bfloat") \
        else "bfloat16"
    try:
        return _DTYPE_MAP[name]
    except KeyError:
        raise UnsupportedOp(f"dtype {dt} has no ONNX mapping")


def _tensor_proto(name: str, arr: np.ndarray) -> "ox.TensorProto":
    arr = np.asarray(arr)
    if str(arr.dtype) == "bfloat16":
        raw = arr.view(np.uint16).tobytes()
        dt = ox.TensorProto.BFLOAT16
    else:
        raw = np.ascontiguousarray(arr).tobytes()
        dt = _onnx_dtype(arr.dtype)
    return ox.TensorProto(name=name, dims=list(arr.shape), data_type=dt,
                          raw_data=raw)


def _value_info(name: str, shape, dt) -> "ox.ValueInfoProto":
    vi = ox.ValueInfoProto(name=name)
    vi.type.tensor_type.elem_type = _onnx_dtype(dt)
    for d in shape:
        vi.type.tensor_type.shape.dim.add(dim_value=int(d))
    return vi


class _Graph:
    """Accumulates nodes/initializers with unique naming."""

    def __init__(self):
        self.nodes = []
        self.initializers = {}
        self._n = 0

    def fresh(self, hint="t"):
        self._n += 1
        return f"{hint}_{self._n}"

    def const(self, arr, hint="const"):
        name = self.fresh(hint)
        self.initializers[name] = np.asarray(arr)
        return name

    def node(self, op_type, inputs, n_out=1, **attrs):
        outs = [self.fresh(op_type.lower()) for _ in range(n_out)]
        n = ox.NodeProto(op_type=op_type, input=list(inputs), output=outs,
                         name=self.fresh(op_type))
        for k, v in attrs.items():
            a = n.attribute.add(name=k)
            if isinstance(v, int):
                a.type = ox.AttributeProto.INT
                a.i = v
            elif isinstance(v, float):
                a.type = ox.AttributeProto.FLOAT
                a.f = v
            elif isinstance(v, str):
                a.type = ox.AttributeProto.STRING
                a.s = v.encode()
            elif isinstance(v, (list, tuple)) and all(
                    isinstance(e, int) for e in v):
                a.type = ox.AttributeProto.INTS
                a.ints.extend(v)
            else:
                raise UnsupportedOp(f"attr {k}={v!r}")
        self.nodes.append(n)
        return outs[0] if n_out == 1 else outs


# -- primitive handlers -------------------------------------------------------
# handler(graph, in_names, in_avals, out_avals, params) -> out_name(s)
_HANDLERS = {}


def _register(*names):
    def deco(fn):
        for n in names:
            _HANDLERS[n] = fn
        return fn
    return deco


_ELEMENTWISE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "pow": "Pow", "neg": "Neg",
    "exp": "Exp", "log": "Log", "tanh": "Tanh", "logistic": "Sigmoid",
    "erf": "Erf", "sqrt": "Sqrt", "abs": "Abs", "sign": "Sign",
    "floor": "Floor", "ceil": "Ceil", "round": "Round",
    "sin": "Sin", "cos": "Cos", "tan": "Tan", "asin": "Asin",
    "acos": "Acos", "atan": "Atan", "sinh": "Sinh", "cosh": "Cosh",
    "asinh": "Asinh", "acosh": "Acosh", "atanh": "Atanh",
    "not": "Not", "and": "And", "or": "Or", "xor": "Xor",
    "eq": "Equal", "lt": "Less", "le": "LessOrEqual", "gt": "Greater",
    "ge": "GreaterOrEqual", "is_finite": "IsInf",
}

for _p, _o in _ELEMENTWISE.items():
    if _p == "is_finite":
        continue

    def _mk(op):
        def h(g, ins, iav, oav, params):
            return g.node(op, ins)
        return h
    _HANDLERS[_p] = _mk(_o)


@_register("ne")
def _ne(g, ins, iav, oav, params):
    return g.node("Not", [g.node("Equal", ins)])


@_register("is_finite")
def _isfinite(g, ins, iav, oav, params):
    # finite = not(isinf) and not(isnan)
    ninf = g.node("Not", [g.node("IsInf", ins)])
    nnan = g.node("Not", [g.node("IsNaN", ins)])
    return g.node("And", [ninf, nnan])


@_register("rsqrt")
def _rsqrt(g, ins, iav, oav, params):
    return g.node("Reciprocal", [g.node("Sqrt", ins)])


@_register("erfc")
def _erfc(g, ins, iav, oav, params):
    one = g.const(np.ones((), np.dtype(iav[0].dtype)), "one")
    return g.node("Sub", [one, g.node("Erf", ins)])


@_register("log1p")
def _log1p(g, ins, iav, oav, params):
    one = g.const(np.ones((), np.dtype(iav[0].dtype)), "one")
    return g.node("Log", [g.node("Add", [ins[0], one])])


@_register("expm1")
def _expm1(g, ins, iav, oav, params):
    one = g.const(np.ones((), np.dtype(iav[0].dtype)), "one")
    return g.node("Sub", [g.node("Exp", ins), one])


@_register("square")
def _square(g, ins, iav, oav, params):
    return g.node("Mul", [ins[0], ins[0]])


@_register("integer_pow")
def _ipow(g, ins, iav, oav, params):
    y = g.const(np.asarray(params["y"], np.dtype(iav[0].dtype)))
    return g.node("Pow", [ins[0], y])


@_register("clamp")
def _clamp(g, ins, iav, oav, params):
    # jax clamp(min, x, max)
    return g.node("Clip", [ins[1], ins[0], ins[2]])


@_register("select_n")
def _select(g, ins, iav, oav, params):
    if len(ins) != 3:
        raise UnsupportedOp("select_n with >2 cases")
    # select_n(pred, on_false, on_true); Where(cond, X=true, Y=false)
    return g.node("Where", [ins[0], ins[2], ins[1]])


@_register("convert_element_type")
def _cast(g, ins, iav, oav, params):
    return g.node("Cast", ins, to=int(_onnx_dtype(params["new_dtype"])))


@_register("stop_gradient", "copy")
def _identity(g, ins, iav, oav, params):
    return g.node("Identity", ins)


@_register("reshape")
def _reshape(g, ins, iav, oav, params):
    shp = g.const(np.asarray(params["new_sizes"], np.int64), "shape")
    return g.node("Reshape", [ins[0], shp])


@_register("squeeze")
def _squeeze(g, ins, iav, oav, params):
    shp = g.const(np.asarray(oav[0].shape, np.int64), "shape")
    return g.node("Reshape", [ins[0], shp])


@_register("expand_dims")
def _expand_dims(g, ins, iav, oav, params):
    shp = g.const(np.asarray(oav[0].shape, np.int64), "shape")
    return g.node("Reshape", [ins[0], shp])


@_register("transpose")
def _transpose(g, ins, iav, oav, params):
    return g.node("Transpose", ins,
                  perm=[int(p) for p in params["permutation"]])


@_register("broadcast_in_dim")
def _broadcast(g, ins, iav, oav, params):
    shape = params["shape"]
    bdims = params["broadcast_dimensions"]
    # place source dims into a rank-len(shape) 1-filled frame, then Expand
    frame = [1] * len(shape)
    for src_i, dst_i in enumerate(bdims):
        frame[dst_i] = iav[0].shape[src_i]
    cur = ins[0]
    if list(iav[0].shape) != frame:
        shp = g.const(np.asarray(frame, np.int64), "shape")
        cur = g.node("Reshape", [cur, shp])
    tgt = g.const(np.asarray(shape, np.int64), "shape")
    return g.node("Expand", [cur, tgt])


@_register("concatenate")
def _concat(g, ins, iav, oav, params):
    return g.node("Concat", ins, axis=int(params["dimension"]))


@_register("slice")
def _slice(g, ins, iav, oav, params):
    starts = g.const(np.asarray(params["start_indices"], np.int64))
    ends = g.const(np.asarray(params["limit_indices"], np.int64))
    axes = g.const(np.arange(len(params["start_indices"]), dtype=np.int64))
    strides = params.get("strides") or [1] * len(params["start_indices"])
    steps = g.const(np.asarray(strides, np.int64))
    return g.node("Slice", [ins[0], starts, ends, axes, steps])


@_register("rev")
def _rev(g, ins, iav, oav, params):
    dims = list(params["dimensions"])
    starts = g.const(np.asarray([-1] * len(dims), np.int64))
    ends = g.const(np.asarray([np.iinfo(np.int64).min] * len(dims),
                              np.int64))
    axes = g.const(np.asarray(dims, np.int64))
    steps = g.const(np.asarray([-1] * len(dims), np.int64))
    return g.node("Slice", [ins[0], starts, ends, axes, steps])


@_register("reduce_sum")
def _reduce_sum(g, ins, iav, oav, params):
    axes = g.const(np.asarray(params["axes"], np.int64), "axes")
    return g.node("ReduceSum", [ins[0], axes], keepdims=0)


def _axes_attr_reduce(op):
    def h(g, ins, iav, oav, params):
        return g.node(op, ins, axes=[int(a) for a in params["axes"]],
                      keepdims=0)
    return h


_HANDLERS["reduce_max"] = _axes_attr_reduce("ReduceMax")
_HANDLERS["reduce_min"] = _axes_attr_reduce("ReduceMin")
_HANDLERS["reduce_prod"] = _axes_attr_reduce("ReduceProd")


@_register("argmax", "argmin")
def _argminmax(g, ins, iav, oav, params):
    op = "ArgMax" if params.get("_prim", "argmax") == "argmax" else "ArgMin"
    (axis,) = params["axes"]
    out = g.node(op, ins, axis=int(axis), keepdims=0)
    want = _onnx_dtype(params["index_dtype"])
    if want != ox.TensorProto.INT64:
        out = g.node("Cast", [out], to=int(want))
    return out


@_register("cumsum")
def _cumsum(g, ins, iav, oav, params):
    ax = g.const(np.asarray(params["axis"], np.int64))
    return g.node("CumSum", [ins[0], ax],
                  reverse=int(bool(params.get("reverse", False))))


@_register("dot_general")
def _dot_general(g, ins, iav, oav, params):
    (lc, rc), (lb, rb) = params["dimension_numbers"]
    lrank, rrank = len(iav[0].shape), len(iav[1].shape)
    letters = iter("abcdefghijklmnopqrstuvwxyz")
    lhs = [None] * lrank
    rhs = [None] * rrank
    out = []
    for li, ri in zip(lb, rb):                    # batch dims (shared)
        c = next(letters)
        lhs[li] = rhs[ri] = c
        out.append(c)
    for li, ri in zip(lc, rc):                    # contracting (shared)
        c = next(letters)
        lhs[li] = rhs[ri] = c
    for i in range(lrank):                        # lhs free
        if lhs[i] is None:
            lhs[i] = next(letters)
            out.append(lhs[i])
    for i in range(rrank):                        # rhs free
        if rhs[i] is None:
            rhs[i] = next(letters)
            out.append(rhs[i])
    eq = f"{''.join(lhs)},{''.join(rhs)}->{''.join(out)}"
    return g.node("Einsum", ins, equation=eq)


@_register("conv_general_dilated")
def _conv(g, ins, iav, oav, params):
    dn = params["dimension_numbers"]
    lhs_spec, rhs_spec, out_spec = dn
    nd = len(iav[0].shape) - 2
    if any(d != 1 for d in params["lhs_dilation"]):
        raise UnsupportedOp("transposed/dilated-input conv")
    if params.get("batch_group_count", 1) != 1:
        raise UnsupportedOp("conv with batch_group_count != 1")
    # specs give, for each component (N/C or O/I, then spatial...), its
    # dim index in the respective tensor. Transpose perm semantics:
    # out[k] = in[perm[k]], so normalizing to NC<sp>/OI<sp> uses the
    # spec ITSELF as the perm.
    x = ins[0]
    if list(lhs_spec) != list(range(nd + 2)):
        x = g.node("Transpose", [x], perm=[int(p) for p in lhs_spec])
    w = ins[1]
    if list(rhs_spec) != list(range(nd + 2)):
        w = g.node("Transpose", [w], perm=[int(p) for p in rhs_spec])
    pads_lo = [int(p[0]) for p in params["padding"]]
    pads_hi = [int(p[1]) for p in params["padding"]]
    y = g.node("Conv", [x, w],
               strides=[int(s) for s in params["window_strides"]],
               pads=pads_lo + pads_hi,
               dilations=[int(d) for d in params["rhs_dilation"]],
               group=int(params["feature_group_count"]))
    if list(out_spec) != list(range(nd + 2)):
        # y is NC<sp>; component k must land at dim out_spec[k], i.e.
        # perm[out_spec[k]] = k — the inverse permutation of out_spec
        y = g.node("Transpose", [y],
                   perm=[int(p) for p in np.argsort(out_spec)])
    return y


@_register("gather")
def _gather(g, ins, iav, oav, params):
    # support the take/embedding pattern: gather along ONE operand axis
    # with full slices on every other axis
    dn = params["dimension_numbers"]
    operand = iav[0]
    slice_sizes = params["slice_sizes"]
    collapsed = list(dn.collapsed_slice_dims)
    start_map = list(dn.start_index_map)
    if len(start_map) != 1 or collapsed != start_map:
        raise UnsupportedOp("gather pattern beyond single-axis take")
    axis = start_map[0]
    for i, s in enumerate(slice_sizes):
        if i != axis and s != operand.shape[i]:
            raise UnsupportedOp("gather with partial slices")
    if slice_sizes[axis] != 1:
        raise UnsupportedOp("gather with slice span > 1")
    # indices carry a trailing singleton index-vector dim: drop it
    idx_aval = iav[1]
    idx = ins[1]
    if idx_aval.shape and idx_aval.shape[-1] == 1:
        shp = g.const(np.asarray(idx_aval.shape[:-1], np.int64), "shape")
        idx = g.node("Reshape", [idx, shp])
    return g.node("Gather", [ins[0], idx], axis=int(axis))


def _check_window_undilated(params):
    for key in ("base_dilation", "window_dilation"):
        if any(d != 1 for d in params.get(key) or ()):
            raise UnsupportedOp(f"reduce_window with {key} != 1")


@_register("reduce_window_max")
def _maxpool(g, ins, iav, oav, params):
    _check_window_undilated(params)
    wd = list(params["window_dimensions"])
    ws = list(params["window_strides"])
    pad = params["padding"]
    if wd[0] != 1 or wd[1] != 1 or ws[0] != 1 or ws[1] != 1:
        raise UnsupportedOp("windowed reduce over non-spatial dims")
    sp = len(wd) - 2
    pads_lo = [int(p[0]) for p in pad[2:]]
    pads_hi = [int(p[1]) for p in pad[2:]]
    if any(p != (0, 0) for p in pad[:2]):
        raise UnsupportedOp("padding on batch/channel dims")
    return g.node("MaxPool", ins, kernel_shape=[int(k) for k in wd[2:]],
                  strides=[int(s) for s in ws[2:]],
                  pads=pads_lo + pads_hi)


@_register("reduce_window_sum")
def _sumpool(g, ins, iav, oav, params):
    _check_window_undilated(params)
    wd = list(params["window_dimensions"])
    ws = list(params["window_strides"])
    pad = params["padding"]
    if wd[0] != 1 or wd[1] != 1 or ws[0] != 1 or ws[1] != 1:
        raise UnsupportedOp("windowed reduce over non-spatial dims")
    if any(p != (0, 0) for p in pad[:2]):
        raise UnsupportedOp("padding on batch/channel dims")
    pads_lo = [int(p[0]) for p in pad[2:]]
    pads_hi = [int(p[1]) for p in pad[2:]]
    # sum pool = AveragePool(count_include_pad) * window_size
    y = g.node("AveragePool", ins,
               kernel_shape=[int(k) for k in wd[2:]],
               strides=[int(s) for s in ws[2:]],
               pads=pads_lo + pads_hi, count_include_pad=1)
    size = float(np.prod(wd[2:]))
    c = g.const(np.asarray(size, np.dtype(iav[0].dtype)))
    return g.node("Mul", [y, c])


@_register("pad")
def _pad(g, ins, iav, oav, params):
    cfg = params["padding_config"]
    if any(interior != 0 for _, _, interior in cfg):
        raise UnsupportedOp("interior padding")
    los = [int(lo) for lo, _, _ in cfg]
    his = [int(hi) for _, hi, _ in cfg]
    if any(v < 0 for v in los + his):
        raise UnsupportedOp("negative padding")
    pads = g.const(np.asarray(los + his, np.int64))
    return g.node("Pad", [ins[0], pads, ins[1]])


# -- the conversion driver ----------------------------------------------------
_INLINE_CALLS = {"pjit", "jit", "closed_call", "custom_jvp_call",
                 "custom_vjp_call", "custom_vjp_call_jaxpr", "remat",
                 "checkpoint", "custom_jvp_call_jaxpr"}


def jaxpr_to_onnx(closed_jaxpr, input_names, graph_name="paddle_tpu",
                  opset_version=OPSET):
    """Convert a ClosedJaxpr to a ModelProto. ``input_names`` label the
    jaxpr invars (the graph inputs); constvars become initializers and
    every eqn unreachable from the inputs is folded eagerly."""
    g = _Graph()
    jaxpr = closed_jaxpr.jaxpr
    env = {}            # var -> ("sym", name) | ("const", ndarray)

    for var, val in zip(jaxpr.constvars, closed_jaxpr.consts):
        env[var] = ("const", np.asarray(val))
    if len(input_names) != len(jaxpr.invars):
        raise ValueError(f"{len(jaxpr.invars)} graph inputs, "
                         f"{len(input_names)} names")
    for var, name in zip(jaxpr.invars, input_names):
        env[var] = ("sym", name)

    def read(atom):
        if isinstance(atom, jex_core.Literal):
            return ("const", np.asarray(atom.val))
        return env[atom]

    def as_name(entry, aval, var=None):
        kind, v = entry
        if kind == "sym":
            return v
        name = g.const(np.asarray(v, np.dtype(aval.dtype)), "w")
        if var is not None:
            # a constvar referenced by N eqns must serialize ONCE, not N
            # weight copies; flip the env entry to the materialized name
            env[var] = ("sym", name)
        return name

    def walk(jaxpr_inner, consts_inner):
        for var, val in zip(jaxpr_inner.constvars, consts_inner):
            env[var] = ("const", np.asarray(val))
        for eqn in jaxpr_inner.eqns:
            prim = eqn.primitive.name
            entries = [read(a) for a in eqn.invars]
            if prim in _INLINE_CALLS:
                sub = (eqn.params.get("jaxpr")
                       or eqn.params.get("call_jaxpr")
                       or eqn.params.get("fun_jaxpr"))
                if sub is None:
                    raise UnsupportedOp(
                        f"call primitive {prim} with no recognizable "
                        f"jaxpr param (keys: {sorted(eqn.params)})")
                if hasattr(sub, "jaxpr"):        # ClosedJaxpr
                    sub_consts = sub.consts
                    sub = sub.jaxpr
                else:
                    sub_consts = ()
                for v_in, entry in zip(sub.invars, entries):
                    env[v_in] = entry
                walk(sub, sub_consts)
                for v_out, v_sub in zip(eqn.outvars, sub.outvars):
                    env[v_out] = read(v_sub)
                continue
            if all(k == "const" for k, _ in entries):
                vals = [jnp.asarray(v) for _, v in entries]
                out = eqn.primitive.bind(*vals, **eqn.params)
                outs = out if eqn.primitive.multiple_results else [out]
                for v, o in zip(eqn.outvars, outs):
                    env[v] = ("const", np.asarray(o))
                continue
            handler = _HANDLERS.get(prim)
            if handler is None:
                raise UnsupportedOp(
                    f"primitive '{prim}' has no ONNX mapping")
            in_names = [as_name(e, a.aval,
                                var=None if isinstance(a, jex_core.Literal)
                                else a)
                        for e, a in zip(entries, eqn.invars)]
            in_avals = [a.aval for a in eqn.invars]
            out_avals = [v.aval for v in eqn.outvars]
            params = dict(eqn.params)
            if prim in ("argmax", "argmin"):
                params["_prim"] = prim
            res = handler(g, in_names, in_avals, out_avals, params)
            results = res if isinstance(res, list) else [res]
            for v, name in zip(eqn.outvars, results):
                env[v] = ("sym", name)

    walk(jaxpr, closed_jaxpr.consts)

    model = ox.ModelProto(ir_version=8, producer_name="paddle_tpu",
                          producer_version="0.3")
    # the emitted op forms are opset-13 compatible, so declaring the
    # caller's requested opset (13..17) is sound
    model.opset_import.add(domain="", version=int(opset_version))
    graph = model.graph
    graph.name = graph_name
    for var, name in zip(jaxpr.invars, input_names):
        graph.input.append(_value_info(name, var.aval.shape,
                                       var.aval.dtype))
    out_names = []
    for i, var in enumerate(jaxpr.outvars):
        entry = read(var)
        name = as_name(entry, var.aval)
        if entry[0] == "const" or name in out_names:
            name = g.node("Identity", [name])
        out_names.append(name)
        graph.output.append(_value_info(name, var.aval.shape,
                                        var.aval.dtype))
    graph.node.extend(g.nodes)
    for name, arr in g.initializers.items():
        graph.initializer.append(_tensor_proto(name, arr))
    return model
