"""Minimal numpy evaluator for the ONNX subset the exporter emits.

onnxruntime is not shipped in this environment, so tests verify exported
models by executing them here and comparing against the original jax
function. Semantics follow the ONNX operator spec (opset 17) for exactly
the ops in exporter._HANDLERS.
"""
from __future__ import annotations

import math

import numpy as np

from . import onnx_pb2 as ox

_NP_DTYPES = {
    ox.TensorProto.FLOAT: np.float32, ox.TensorProto.DOUBLE: np.float64,
    ox.TensorProto.FLOAT16: np.float16, ox.TensorProto.INT64: np.int64,
    ox.TensorProto.INT32: np.int32, ox.TensorProto.INT16: np.int16,
    ox.TensorProto.INT8: np.int8, ox.TensorProto.UINT8: np.uint8,
    ox.TensorProto.UINT32: np.uint32, ox.TensorProto.UINT64: np.uint64,
    ox.TensorProto.BOOL: np.bool_,
}


def tensor_to_numpy(tp: "ox.TensorProto") -> np.ndarray:
    if tp.data_type == ox.TensorProto.BFLOAT16:
        import ml_dtypes
        arr = np.frombuffer(tp.raw_data, np.uint16).view(ml_dtypes.bfloat16)
    else:
        arr = np.frombuffer(tp.raw_data, _NP_DTYPES[tp.data_type])
    return arr.reshape(list(tp.dims)).copy()


def _attrs(node):
    out = {}
    for a in node.attribute:
        if a.type == ox.AttributeProto.INT:
            out[a.name] = int(a.i)
        elif a.type == ox.AttributeProto.FLOAT:
            out[a.name] = float(a.f)
        elif a.type == ox.AttributeProto.STRING:
            out[a.name] = a.s.decode()
        elif a.type == ox.AttributeProto.INTS:
            out[a.name] = [int(v) for v in a.ints]
    return out


def _pool(x, kernel, strides, pads, mode, count_include_pad=False):
    sp = len(kernel)
    lo, hi = pads[:sp], pads[sp:]
    pad_width = [(0, 0), (0, 0)] + [(lo[i], hi[i]) for i in range(sp)]
    fill = 0.0 if (mode == "avg" and count_include_pad) else (
        -np.inf if mode == "max" else np.nan)
    xp = np.pad(x.astype(np.float64), pad_width, constant_values=fill)
    win = np.lib.stride_tricks.sliding_window_view(
        xp, tuple(kernel), axis=tuple(range(2, 2 + sp)))
    idx = (slice(None), slice(None)) + tuple(
        slice(None, None, s) for s in strides)
    win = win[idx]
    red_axes = tuple(range(win.ndim - sp, win.ndim))
    if mode == "max":
        out = win.max(axis=red_axes)
    else:
        out = np.nanmean(win, axis=red_axes) if not count_include_pad \
            else win.mean(axis=red_axes)
    return out.astype(x.dtype)


def run_model(model: "ox.ModelProto", feeds: dict) -> list:
    env = dict(feeds)
    for init in model.graph.initializer:
        env[init.name] = tensor_to_numpy(init)

    def conv(x, w, at):
        group = at.get("group", 1)
        strides = at["strides"]
        dil = at.get("dilations", [1] * len(strides))
        sp = len(strides)
        lo, hi = at["pads"][:sp], at["pads"][sp:]
        xp = np.pad(x, [(0, 0), (0, 0)] + [(lo[i], hi[i])
                                           for i in range(sp)])
        N, C = xp.shape[0], xp.shape[1]
        O = w.shape[0]
        kernel = w.shape[2:]
        eff_k = [dil[i] * (kernel[i] - 1) + 1 for i in range(sp)]
        out_sp = [(xp.shape[2 + i] - eff_k[i]) // strides[i] + 1
                  for i in range(sp)]
        out = np.zeros((N, O) + tuple(out_sp), np.float64)
        cin_g = C // group
        o_g = O // group
        # im2col per group
        for gi in range(group):
            xg = xp[:, gi * cin_g:(gi + 1) * cin_g]
            wg = w[gi * o_g:(gi + 1) * o_g]
            win = np.lib.stride_tricks.sliding_window_view(
                xg, tuple(eff_k), axis=tuple(range(2, 2 + sp)))
            idx = (slice(None), slice(None)) + tuple(
                slice(None, None, strides[i]) for i in range(sp)) + tuple(
                slice(None, None, dil[i]) for i in range(sp))
            win = win[idx]            # [N, Cg, *out_sp, *kernel]
            o_label = 2 + 2 * sp     # einsum int labels must be < 52
            out[:, gi * o_g:(gi + 1) * o_g] = np.einsum(
                win, [0, 1] + list(range(2, 2 + sp))
                + list(range(2 + sp, 2 + 2 * sp)),
                wg, [o_label, 1] + list(range(2 + sp, 2 + 2 * sp)),
                [0, o_label] + list(range(2, 2 + sp)))
        return out.astype(x.dtype)

    for node in model.graph.node:
        ins = [env[i] for i in node.input]
        at = _attrs(node)
        op = node.op_type
        if op == "Add":
            out = ins[0] + ins[1]
        elif op == "Sub":
            out = ins[0] - ins[1]
        elif op == "Mul":
            out = ins[0] * ins[1]
        elif op == "Div":
            out = ins[0] / ins[1]
        elif op == "Max":
            out = np.maximum(ins[0], ins[1])
        elif op == "Min":
            out = np.minimum(ins[0], ins[1])
        elif op == "Pow":
            out = np.power(ins[0], ins[1]).astype(ins[0].dtype)
        elif op == "Neg":
            out = -ins[0]
        elif op == "Exp":
            out = np.exp(ins[0])
        elif op == "Log":
            out = np.log(ins[0])
        elif op == "Tanh":
            out = np.tanh(ins[0])
        elif op == "Sigmoid":
            out = 1.0 / (1.0 + np.exp(-ins[0].astype(np.float64)))
            out = out.astype(ins[0].dtype)
        elif op == "Erf":
            out = np.vectorize(math.erf)(
                ins[0].astype(np.float64)).astype(ins[0].dtype)
        elif op == "Sqrt":
            out = np.sqrt(ins[0])
        elif op == "Reciprocal":
            out = 1.0 / ins[0]
        elif op == "Abs":
            out = np.abs(ins[0])
        elif op == "Sign":
            out = np.sign(ins[0])
        elif op == "Floor":
            out = np.floor(ins[0])
        elif op == "Ceil":
            out = np.ceil(ins[0])
        elif op == "Round":
            out = np.round(ins[0])
        elif op in ("Sin", "Cos", "Tan", "Arcsin", "Arccos", "Arctan",
                    "Sinh", "Cosh", "Arcsinh", "Arccosh", "Arctanh"):
            out = getattr(np, op.lower())(ins[0])
        elif op in ("Asin", "Acos", "Atan", "Asinh", "Acosh", "Atanh"):
            out = getattr(np, "arc" + op[1:].lower())(ins[0])
        elif op == "Not":
            out = ~ins[0]
        elif op == "And":
            out = ins[0] & ins[1]
        elif op == "Or":
            out = ins[0] | ins[1]
        elif op == "Xor":
            out = ins[0] ^ ins[1]
        elif op == "Equal":
            out = ins[0] == ins[1]
        elif op == "Less":
            out = ins[0] < ins[1]
        elif op == "LessOrEqual":
            out = ins[0] <= ins[1]
        elif op == "Greater":
            out = ins[0] > ins[1]
        elif op == "GreaterOrEqual":
            out = ins[0] >= ins[1]
        elif op == "IsInf":
            out = np.isinf(ins[0])
        elif op == "IsNaN":
            out = np.isnan(ins[0])
        elif op == "Where":
            out = np.where(ins[0], ins[1], ins[2])
        elif op == "Clip":
            out = np.clip(ins[0], ins[1], ins[2])
        elif op == "Cast":
            out = ins[0].astype(_NP_DTYPES[at["to"]])
        elif op == "Identity":
            out = ins[0]
        elif op == "Reshape":
            out = ins[0].reshape([int(d) for d in ins[1]])
        elif op == "Transpose":
            out = np.transpose(ins[0], at["perm"])
        elif op == "Expand":
            out = np.broadcast_to(
                ins[0], np.broadcast_shapes(ins[0].shape,
                                            tuple(int(d) for d in ins[1])))
        elif op == "Concat":
            out = np.concatenate(ins, axis=at["axis"])
        elif op == "Slice":
            starts, ends, axes, steps = (ins[1].tolist(), ins[2].tolist(),
                                         ins[3].tolist(), ins[4].tolist())
            sl = [slice(None)] * ins[0].ndim
            for s, e, a, st in zip(starts, ends, axes, steps):
                e = None if (st < 0 and e == np.iinfo(np.int64).min) else e
                sl[a] = slice(s, e, st)
            out = ins[0][tuple(sl)]
        elif op == "ReduceSum":
            out = ins[0].sum(axis=tuple(int(a) for a in ins[1]),
                             keepdims=bool(at.get("keepdims", 1)))
        elif op == "ReduceMax":
            out = ins[0].max(axis=tuple(at["axes"]),
                             keepdims=bool(at.get("keepdims", 1)))
        elif op == "ReduceMin":
            out = ins[0].min(axis=tuple(at["axes"]),
                             keepdims=bool(at.get("keepdims", 1)))
        elif op == "ReduceProd":
            out = ins[0].prod(axis=tuple(at["axes"]),
                              keepdims=bool(at.get("keepdims", 1)))
        elif op == "ArgMax":
            out = np.argmax(ins[0], axis=at["axis"]).astype(np.int64)
        elif op == "ArgMin":
            out = np.argmin(ins[0], axis=at["axis"]).astype(np.int64)
        elif op == "CumSum":
            ax = int(ins[1])
            if at.get("reverse", 0):
                out = np.flip(np.cumsum(np.flip(ins[0], ax), axis=ax), ax)
            else:
                out = np.cumsum(ins[0], axis=ax)
        elif op == "Einsum":
            out = np.einsum(at["equation"], *ins)
        elif op == "Gather":
            out = np.take(ins[0], ins[1].astype(np.int64),
                          axis=at.get("axis", 0))
        elif op == "Conv":
            out = conv(ins[0], ins[1], at)
        elif op == "MaxPool":
            out = _pool(ins[0], at["kernel_shape"], at["strides"],
                        at.get("pads", [0] * 2 * len(at["kernel_shape"])),
                        "max")
        elif op == "AveragePool":
            out = _pool(ins[0], at["kernel_shape"], at["strides"],
                        at.get("pads", [0] * 2 * len(at["kernel_shape"])),
                        "avg",
                        count_include_pad=bool(at.get("count_include_pad",
                                                      0)))
        elif op == "Pad":
            cfg = ins[1].tolist()
            nd = ins[0].ndim
            out = np.pad(ins[0], [(cfg[i], cfg[nd + i]) for i in range(nd)],
                         constant_values=ins[2] if len(ins) > 2 else 0)
        else:
            raise NotImplementedError(f"runner: op {op}")
        env[node.output[0]] = np.asarray(out)
    return [env[o.name] for o in model.graph.output]
