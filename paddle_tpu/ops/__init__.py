"""Fused op pack.

TPU-native replacement for the reference's CUDA fused kernels
(paddle/phi/kernels/fusion/): Pallas kernels where they beat XLA fusion,
jnp compositions (which XLA fuses) elsewhere. Each op is a pure jax function
usable under jit/vjp; Pallas variants carry custom_vjp.

Routing: flash_attention / rms_norm / layer_norm try the Pallas kernel on TPU
and fall back to the jnp composition off-TPU or on any kernel error.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


# -- rms_norm ---------------------------------------------------------------
def rms_norm_ref(x, weight, epsilon=1e-6):
    ms = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(ms + epsilon)
            ).astype(x.dtype) * weight


def rms_norm(x, weight, epsilon=1e-6, mode=None):
    """``mode`` (fused-train contract: None reads FLAGS_fused_train,
    "pallas"/"ref" pin) selects the Pallas BACKWARD variant on TPU; a
    "pallas" pin also forces the Pallas kernel off-TPU (interpret
    mode — how tests and the audit catalog trace it on CPU)."""
    from .pallas._util import fused_train_mode
    m = fused_train_mode(mode)
    if _on_tpu() or m == "pallas":
        try:
            from .pallas.norms import rms_norm_pallas
            return rms_norm_pallas(x, weight, epsilon, mode)
        except Exception:
            if m == "pallas":
                raise     # an explicit pin must not silently fall back
            pass
    return rms_norm_ref(x, weight, epsilon)


# -- layer_norm -------------------------------------------------------------
def layer_norm_ref(x, weight, bias, epsilon=1e-5):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = ((xf - mean) * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


def layer_norm(x, weight, bias, epsilon=1e-5):
    return layer_norm_ref(x, weight, bias, epsilon)


# -- rope -------------------------------------------------------------------
def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True):
    """reference: python/paddle/incubate/nn/functional/
    fused_rotary_position_embedding.py. Layout [b, s, h, d]."""
    from .rope import apply_rope
    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
        else:
            outs.append(apply_rope(t, sin, cos, position_ids,
                                   use_neox_rotary_style))
    return tuple(outs)


# -- swiglu -----------------------------------------------------------------
def swiglu(x, y=None):
    if y is None:
        a, b = jnp.split(x, 2, axis=-1)
    else:
        a, b = x, y
    return jax.nn.silu(a) * b


from . import flash_attention  # noqa: E402,F401
from . import rope  # noqa: E402,F401
