"""Flash attention.

reference: paddle/phi/kernels/gpu/flash_attn_kernel.cu:517 (dynload of the
flash-attn CUDA library). TPU-native: a Pallas kernel (ops/pallas/
flash_attention.py) with the blockwise online-softmax algorithm; this module
routes to it on TPU and to a fused-friendly jnp composition elsewhere.

Layout: [batch, seq, heads, head_dim] (paddle flash-attn convention).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _ref_attention(q, k, v, causal=False, scale=None):
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * s
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(mask, logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def flash_attention(q, k, v, causal=False, scale=None):
    if jax.default_backend() in ("tpu", "axon"):
        try:
            from .pallas.flash_attention import flash_attention_pallas
            return flash_attention_pallas(q, k, v, causal=causal, scale=scale)
        except Exception:
            pass
    return _ref_attention(q, k, v, causal=causal, scale=scale)
