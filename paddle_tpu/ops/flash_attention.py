"""Flash attention.

reference: paddle/phi/kernels/gpu/flash_attn_kernel.cu:517 (dynload of the
flash-attn CUDA library; varlen path at :137). TPU-native: a Pallas kernel
(ops/pallas/flash_attention.py) with the blockwise online-softmax algorithm,
native GQA, segment-id (varlen) masking and additive bias; this module
routes to it on TPU and to a fused-friendly jnp composition elsewhere.

Layout: [batch, seq, heads, head_dim] (paddle flash-attn convention).
K/V may carry fewer heads than Q (GQA) on both paths.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _ref_attention(q, k, v, causal=False, scale=None, bias=None,
                   segment_ids=None, kv_segment_ids=None,
                   dropout_rate=0.0, dropout_seed=None):
    d = q.shape[-1]
    h, kvh = q.shape[2], k.shape[2]
    if kvh != h:
        k = jnp.repeat(k, h // kvh, axis=2)
        v = jnp.repeat(v, h // kvh, axis=2)
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * s
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    ql, kl = logits.shape[-2], logits.shape[-1]
    mask = jnp.ones((ql, kl), bool)
    if causal:
        mask = jnp.tril(mask, k=kl - ql)
    mask = mask[None, None]
    if segment_ids is not None:
        kv_seg = kv_segment_ids if kv_segment_ids is not None \
            else segment_ids
        mask = mask & (segment_ids[:, None, :, None] ==
                       kv_seg[:, None, None, :])
    logits = jnp.where(mask, logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    if dropout_rate and dropout_rate > 0.0:
        # EXACT same position-keyed hash mask as the Pallas kernels (one
        # "block" spanning the full matrix), so ref and kernel agree
        # bit-for-mask under a shared seed
        from .pallas.flash_attention import _dropout_keep
        b = q.shape[0]
        seed = jnp.asarray(dropout_seed, jnp.uint32)
        bh = jnp.arange(b * h, dtype=jnp.int32)
        keep = jax.vmap(lambda i: _dropout_keep(
            seed, i, jnp.int32(0), jnp.int32(0), ql, kl,
            float(dropout_rate)))(bh).reshape(b, h, ql, kl)
        p = jnp.where(keep, p, 0.0) / (1.0 - dropout_rate)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    # rows with no valid key (segment padding) must yield 0, not uniform avg
    if segment_ids is not None:
        any_valid = jnp.any(mask, axis=-1)  # [b, h|1, q]
        out = jnp.where(jnp.swapaxes(any_valid, 1, 2)[..., None], out, 0.0)
    return out.astype(q.dtype)


from ..core.flags import GLOBAL_FLAGS

GLOBAL_FLAGS.define(
    "use_flash_attention", True,
    "route attention through the Pallas flash kernel on TPU "
    "(0 = jnp composition, for A/B perf diagnosis)")


def flash_attention(q, k, v, causal=False, scale=None, bias=None,
                    segment_ids=None, kv_segment_ids=None, bias_grad=False,
                    dropout_rate=0.0, dropout_seed=None):
    if bias is not None and not bias_grad:
        bias = jax.lax.stop_gradient(bias)
    if dropout_rate and dropout_rate > 0.0 and dropout_seed is None:
        # draw once here so the pallas path and any ref fallback of the
        # SAME call share one seed
        from ..core.random import next_key
        dropout_seed = jax.random.randint(
            next_key(), (), 0, jnp.iinfo(jnp.int32).max, dtype=jnp.int32)
    if jax.default_backend() in ("tpu", "axon") and \
            GLOBAL_FLAGS.get("use_flash_attention"):
        try:
            from .pallas.flash_attention import flash_attention_pallas
            return flash_attention_pallas(
                q, k, v, causal=causal, scale=scale, bias=bias,
                segment_ids=segment_ids, kv_segment_ids=kv_segment_ids,
                bias_grad=bias_grad, dropout_rate=dropout_rate,
                dropout_seed=dropout_seed)
        except ImportError:
            pass
        except Exception as e:  # noqa: BLE001
            from .paged_attention import _warn_fallback
            _warn_fallback("flash_attention", e)
    return _ref_attention(q, k, v, causal=causal, scale=scale, bias=bias,
                          segment_ids=segment_ids,
                          kv_segment_ids=kv_segment_ids,
                          dropout_rate=dropout_rate,
                          dropout_seed=dropout_seed)


def segment_ids_from_cu_seqlens(cu_seqlens, total: int):
    """[n+1] cumulative lengths -> [total] int32 segment ids; positions past
    cu_seqlens[-1] get id -1 (masked against every real segment)."""
    pos = jnp.arange(total, dtype=jnp.int32)
    seg = jnp.searchsorted(jnp.asarray(cu_seqlens, jnp.int32), pos,
                           side="right").astype(jnp.int32) - 1
    n = cu_seqlens.shape[0] - 1
    return jnp.where(seg >= n, -1, seg)
