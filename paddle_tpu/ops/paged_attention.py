"""Paged KV-cache attention for serving.

TPU-native redesign of the reference's paged-attention inference kernels
(paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu and
block_attn.h — "block multi-head attention" with a paged KV cache): the KV
cache lives in a pool of fixed-size blocks; each sequence owns a list of
block ids (its block table), so cache memory is allocated in O(block_size)
units instead of max_seq_len per sequence.

Layout choices for TPU:
- pools are [num_blocks, block_size, KV_heads, head_dim] so a block gather
  (jnp.take on axis 0) is a contiguous HBM read and the trailing
  [head_dim] axis stays lane-aligned (128) for the MXU/VPU;
- decode attention is one fused einsum over the gathered blocks — XLA fuses
  the gather + QK^T + softmax + PV chain; block_tables make the gather
  bounded by max_blocks_per_seq, not the pool size.
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core.flags import GLOBAL_FLAGS as _FLAGS

_FLAGS.define(
    "use_paged_kernel", True,
    "route paged-KV decode attention through the Pallas kernel on TPU "
    "(0 = XLA gather+einsum composition, for A/B perf diagnosis)")


def paged_attention_decode(q, k_pool, v_pool, block_tables, seq_lens,
                           scale: Optional[float] = None):
    """Single-step decode attention over a paged cache.

    q:            [B, H, hd]     query for the current position
    k_pool/v_pool:[N, BS, KV, hd] physical block pools
    block_tables: [B, MB] int32  physical block id per logical block
    seq_lens:     [B]    int32   valid tokens per sequence (incl. current)
    returns       [B, H, hd]

    On TPU this routes to the Pallas kernel (ops/pallas/paged_attention.py)
    that streams pages through VMEM via scalar-prefetched block tables; the
    gather+einsum below is the reference-numerics fallback.
    """
    from ..core.flags import GLOBAL_FLAGS
    if jax.default_backend() in ("tpu", "axon") and \
            GLOBAL_FLAGS.get("use_paged_kernel"):
        try:
            from .pallas.paged_attention import paged_attention_decode_pallas
            return paged_attention_decode_pallas(
                q, k_pool, v_pool, block_tables, seq_lens, scale=scale)
        except ImportError:
            pass
        except Exception as e:  # noqa: BLE001
            _warn_fallback("paged_attention_decode", e)
    return paged_attention_decode_xla(q, k_pool, v_pool, block_tables,
                                      seq_lens, scale=scale)


_warned_fallbacks = set()


def _warn_fallback(name, e):
    """A real kernel defect must not silently become the slow XLA path."""
    if name not in _warned_fallbacks:
        _warned_fallbacks.add(name)
        import warnings
        warnings.warn(f"{name}: Pallas kernel failed "
                      f"({type(e).__name__}: {e}); falling back to the XLA "
                      "composition", stacklevel=3)


def paged_attention_decode_xla(q, k_pool, v_pool, block_tables, seq_lens,
                               scale: Optional[float] = None,
                               k_scale=None, v_scale=None):
    """Gather+einsum reference path (always XLA, any backend).
    ``k_scale``/``v_scale`` [KV]: per-head dequant for int8 pools —
    applied right after the gather so the rest of the math is shared
    with the bf16 path."""
    B, H, hd = q.shape
    N, BS, KV, _ = k_pool.shape
    MB = block_tables.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(hd)

    # gather each sequence's blocks: [B, MB, BS, KV, hd] → [B, T, KV, hd]
    k = jnp.take(k_pool, block_tables, axis=0).reshape(B, MB * BS, KV, hd)
    v = jnp.take(v_pool, block_tables, axis=0).reshape(B, MB * BS, KV, hd)
    if k_scale is not None:
        k = k.astype(jnp.float32) * k_scale[None, None, :, None]
    if v_scale is not None:
        v = v.astype(jnp.float32) * v_scale[None, None, :, None]
    rep = H // KV
    if rep > 1:
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    scores = jnp.einsum("bhd,bthd->bht", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    T = MB * BS
    mask = jnp.arange(T)[None, None, :] < seq_lens[:, None, None]
    # finite mask value: a padding slot with seq_len 0 would otherwise get
    # an all--inf row and softmax NaN; zero its output instead
    scores = jnp.where(mask, scores, jnp.float32(-1e30))
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bht,bthd->bhd", probs, v.astype(jnp.float32))
    out = jnp.where(seq_lens[:, None, None] > 0, out, 0.0)
    return out.astype(q.dtype)


def write_to_pool(k_pool, v_pool, block_tables, seq_lens, k_new, v_new):
    """Append one token's K/V per sequence into the paged pools.

    k_new/v_new: [B, KV, hd] for the token at position seq_lens[b] (0-based
    position == current length before append). Returns updated pools.
    """
    B = k_new.shape[0]
    BS = k_pool.shape[1]
    pos = seq_lens                       # position to write
    blk_idx = pos // BS                  # logical block
    offset = pos % BS
    phys = jnp.take_along_axis(block_tables, blk_idx[:, None],
                               axis=1)[:, 0]          # [B]
    k_pool = k_pool.at[phys, offset].set(k_new)
    v_pool = v_pool.at[phys, offset].set(v_new)
    return k_pool, v_pool


def write_chunk_to_pool(k_pool, v_pool, wtable, pos0, n_valid,
                        k_new, v_new):
    """Scatter one prefill chunk's K/V into the paged pools.

    k_new/v_new: [P, KV, hd] for token positions pos0..pos0+P-1 of ONE
    request; ``wtable`` [MB] is the request's WRITE table (prefix-cache
    shared pages redirected to scratch page 0, the COW contract), and
    rows at/after ``n_valid`` (bucket padding) are redirected to the
    scratch page too — so the fused prefill path writes exactly the
    chunk's own tokens instead of re-scattering the whole dense view,
    and can never touch a shared page whatever it computes.
    """
    P = k_new.shape[0]
    BS = k_pool.shape[1]
    rows = jnp.arange(P, dtype=jnp.int32)
    pos = jnp.asarray(pos0, jnp.int32) + rows
    valid = rows < jnp.asarray(n_valid, jnp.int32)
    page = jnp.where(valid, jnp.take(jnp.asarray(wtable, jnp.int32),
                                     pos // BS), 0)
    off = pos % BS
    k_pool = k_pool.at[page, off].set(k_new.astype(k_pool.dtype))
    v_pool = v_pool.at[page, off].set(v_new.astype(v_pool.dtype))
    return k_pool, v_pool


def write_chunk_to_pool_quant(k_pool, v_pool, wtable, pos0, n_valid,
                              k_new, v_new, k_scale, v_scale):
    """``write_chunk_to_pool`` for int8 pools: the chunk's K/V quantize
    with the static per-head scales on the way in (the same formula as
    ``quant_cache``, so re-quantizing untouched positions stays exact)."""
    def q(x, s):
        return jnp.clip(jnp.round(x.astype(jnp.float32)
                                  / s[None, :, None]),
                        -127, 127).astype(jnp.int8)
    return write_chunk_to_pool(k_pool, v_pool, wtable, pos0, n_valid,
                               q(k_new, k_scale), q(v_new, v_scale))


# -- int8 cache quantization (static per-head scales) -----------------------
# Reference capability: block_multihead_attention's cache_k/v quant —
# paddle/phi/kernels/fusion/gpu/block_attn.h int8 cache load path with
# static [num_head] dequant scales. On TPU this is purely a memory
# optimization: int8 pools halve KV HBM (2x batch at the same footprint);
# the attention math runs bf16/fp32 after a per-head dequant multiply that
# XLA fuses into the gather consumer.

def quantize_pools(k_pool, v_pool):
    """bf16/f32 pools [N, BS, KV, hd] -> (int8 pools, k_scale [KV],
    v_scale [KV]) with symmetric per-head absmax scales (unwritten
    slots are zero-initialized, so whole-pool absmax is safe)."""
    def one(p):
        amax = jnp.max(jnp.abs(p.astype(jnp.float32)), axis=(0, 1, 3))
        scale = jnp.maximum(amax / 127.0, 1e-8)              # [KV]
        q = jnp.clip(jnp.round(p.astype(jnp.float32)
                               / scale[None, None, :, None]),
                     -127, 127).astype(jnp.int8)
        return q, scale
    kq, ks = one(k_pool)
    vq, vs = one(v_pool)
    return kq, vq, ks, vs


def dequant_cache(x, scale):
    """int8 dense cache view [L, B, T, KV, hd] -> fp32 with per-layer-
    per-head scales [L, KV] (the serving engine's chunked prefill pulls
    quantized pages into a dense view through this)."""
    return x.astype(jnp.float32) * scale[:, None, None, :, None]


def quant_cache(x, scale):
    """Inverse of ``dequant_cache``: fp dense view -> int8 with the same
    static scales. round(clip(q*s/s)) == q, so requantizing positions
    that were only dequantized (not rewritten) is exact."""
    return jnp.clip(jnp.round(x.astype(jnp.float32)
                              / scale[:, None, None, :, None]),
                    -127, 127).astype(jnp.int8)


def write_to_pool_quant(k_pool, v_pool, block_tables, seq_lens,
                        k_new, v_new, k_scale, v_scale):
    """``write_to_pool`` for int8 pools: the new token's K/V quantize
    with the static per-head scales on the way in."""
    def q(x, s):
        return jnp.clip(jnp.round(x.astype(jnp.float32)
                                  / s[None, :, None]),
                        -127, 127).astype(jnp.int8)
    return write_to_pool(k_pool, v_pool, block_tables, seq_lens,
                         q(k_new, k_scale), q(v_new, v_scale))


def paged_attention_decode_quant(q, k_pool, v_pool, block_tables,
                                 seq_lens, k_scale, v_scale,
                                 scale: Optional[float] = None):
    """Decode attention over int8 pools: gather int8 (the HBM win),
    dequant per head, then the SAME attention math as the bf16 path."""
    return paged_attention_decode_xla(q, k_pool, v_pool, block_tables,
                                      seq_lens, scale=scale,
                                      k_scale=k_scale, v_scale=v_scale)


class BlockManager:
    """Host-side physical block allocator (reference: the block-table
    bookkeeping AnalysisPredictor does around block_multihead_attention).
    Not jitted — runs in the serving loop between steps.

    Pages are REF-COUNTED so one physical page can back multiple block
    tables (the radix prefix cache shares prompt-prefix pages across
    requests, inference/prefix_cache.py): ``allocate`` hands out pages
    at refcount 1, ``attach`` appends already-populated shared pages to
    a table (incref), ``release`` decrefs every table entry and a page
    returns to the free list only when its count hits 0. When the free
    list runs dry, the ``reclaim`` callback (the prefix cache's LRU
    eviction) gets one chance to free cold cached pages before the
    allocator gives up."""

    def __init__(self, num_blocks: int, block_size: int,
                 max_blocks_per_seq: int):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        self.free = list(range(num_blocks - 1, -1, -1))
        self.tables = {}            # seq_id -> list of physical block ids
        self.refcount = np.zeros(num_blocks, np.int32)
        self.reclaim = None         # callback(n_pages) -> pages freed

    def alloc_page(self) -> int:
        """Pop one free page at refcount 1 (sole owner: the caller)."""
        if not self.free and self.reclaim is not None:
            self.reclaim(1)
        if not self.free:
            raise RuntimeError("KV cache pool exhausted")
        p = self.free.pop()
        if self.refcount[p] != 0:
            raise RuntimeError(
                f"free list corrupt: page {p} has refcount "
                f"{int(self.refcount[p])}")
        self.refcount[p] = 1
        return p

    def incref(self, page: int):
        if self.refcount[page] <= 0:
            raise RuntimeError(
                f"incref on unowned page {page}: sharing a freed page "
                "would alias live KV data")
        self.refcount[page] += 1

    def decref(self, page: int) -> bool:
        """Drop one reference; returns True when the page was freed.
        Going below zero is a bookkeeping bug, never silently allowed —
        it means a page was double-released while possibly shared."""
        rc = int(self.refcount[page]) - 1
        if rc < 0:
            raise RuntimeError(f"refcount of page {page} went negative")
        self.refcount[page] = rc
        if rc == 0:
            self.free.append(page)
            return True
        return False

    def fork(self, src_page: int) -> int:
        """Copy-on-write allocation: a fresh page destined to receive a
        copy of ``src_page`` (the owner of the pools performs the device
        copy). The source is pinned for the duration so the reclaim
        callback cannot evict it while the fork is in flight."""
        self.incref(src_page)
        try:
            return self.alloc_page()
        finally:
            self.decref(src_page)

    def attach(self, seq_id: int, pages, owned: bool = False):
        """Append already-populated pages (a matched shared prefix, or
        a COW fork whose reference is transferred) to a sequence's
        table. Must run before ``allocate`` fills the suffix."""
        table = self.tables.setdefault(seq_id, [])
        for p in pages:
            if not owned:
                self.incref(p)
            table.append(p)
        return table

    def allocate(self, seq_id: int, num_tokens: int):
        need = (num_tokens + self.block_size - 1) // self.block_size
        table = self.tables.setdefault(seq_id, [])
        shortfall = (need - len(table)) - len(self.free)
        if shortfall > 0 and self.reclaim is not None:
            # one batched eviction pass instead of a tree walk per page
            self.reclaim(shortfall)
        while len(table) < need:
            table.append(self.alloc_page())
        return table

    def append_token(self, seq_id: int, cur_len: int):
        """Ensure capacity for one more token; returns the table."""
        return self.allocate(seq_id, cur_len + 1)

    def release(self, seq_id: int):
        for b in self.tables.pop(seq_id, []):
            self.decref(b)

    def table_array(self, seq_ids) -> np.ndarray:
        out = np.zeros((len(seq_ids), self.max_blocks_per_seq), np.int32)
        for i, sid in enumerate(seq_ids):
            t = self.tables.get(sid, [])
            out[i, :len(t)] = t
        return out

    def check(self, raise_on_violation: bool = True):
        """Cheap structural invariant sweep over the allocator — the
        single definition shared by the lifecycle model checker
        (analysis/lifecycle.py) and the engines' opt-in per-step
        self-check (``PADDLE_TPU_CHECK_INVARIANTS=1``). Returns the
        list of violation strings (empty = clean); raises instead when
        ``raise_on_violation``.

        Checked here (manager-local; the cross-structure refcount
        EQUALITY needs the radix tree and lives in
        ``PrefixCache.check``):

        - refcounts never negative; free-list pages have refcount 0;
        - no duplicate or out-of-range free-list entries;
        - page conservation: every page is either free or referenced
          (refcount > 0) — no page is ever lost;
        - every table entry is a valid page id with refcount >= the
          number of table references to it (a table can never hold
          more references than the refcount records).
        """
        problems = []
        seen_free = set()
        for p in self.free:
            if not (0 <= p < self.num_blocks):
                problems.append(f"free list holds invalid page {p}")
                continue
            if p in seen_free:
                problems.append(f"page {p} appears twice in free list")
            seen_free.add(p)
            if int(self.refcount[p]) != 0:
                problems.append(
                    f"free page {p} has refcount "
                    f"{int(self.refcount[p])} (must be 0)")
        table_refs = np.zeros(self.num_blocks, np.int64)
        for sid, table in self.tables.items():
            for p in table:
                if not (0 <= p < self.num_blocks):
                    problems.append(
                        f"table {sid} holds invalid page {p}")
                    continue
                table_refs[p] += 1
        for p in range(self.num_blocks):
            rc = int(self.refcount[p])
            if rc < 0:
                problems.append(f"page {p} refcount negative ({rc})")
            if rc == 0 and p not in seen_free:
                problems.append(
                    f"page {p} leaked: refcount 0 but not in free list")
            if rc > 0 and p in seen_free:
                problems.append(
                    f"page {p} in free list with refcount {rc}")
            if rc < int(table_refs[p]):
                problems.append(
                    f"page {p} refcount {rc} < {int(table_refs[p])} "
                    "table references (tables over-share the page)")
        if problems and raise_on_violation:
            raise RuntimeError(
                "BlockManager.check failed:\n  " + "\n  ".join(problems))
        return problems
